"""Host-side batching + device placement.

Replaces the reference's ``DataLoader(dataset, batch_size=256,
sampler=DistributedSampler(...), num_workers=…)`` (``demo.py:139-154``).
Design differences, deliberately TPU-first:

- The loader yields **numpy host batches**; a separate :func:`shard_batch`
  places them as *global* sharded ``jax.Array``s on the mesh (each process
  contributes its shard — the multi-controller JAX model), so the compiled
  step always sees one logical global batch.
- Determinism comes from :mod:`tpudist.data.sharding` (seeded permutation per
  epoch), not from worker processes; there is no fork/forkserver hazard to
  work around (the reference needed ``forkserver`` + ``file_system`` sharing,
  ``demo.py:163-170``).
- Optional C++-accelerated batch assembly via
  ``tpudist.data.native_loader`` (``--num_workers > 0``); numpy fallback
  otherwise.
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np

from tpudist.data.sharding import ShardPlan, epoch_indices
from tpudist.data.toy import ToyData


class ShardedLoader:
    """Iterates per-process batches of a (numpy-backed) dataset.

    ``set_epoch`` re-derives the shuffle, matching ``sampler.set_epoch``
    (``demo.py:96-98``).  Per-process batch size is fixed (the reference
    assumes equal per-rank batches every iteration, ``demo.py:113``); the
    trailing partial batch is dropped only if ``drop_last``.
    """

    def __init__(
        self,
        dataset: ToyData,
        batch_size: int,
        plan: ShardPlan,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.plan = plan
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        n = self.plan.samples_per_shard
        if self.plan.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        return self.iter_from(0)

    def iter_from(self, skip_batches: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Iterate the epoch starting ``skip_batches`` in — index-level skip,
        nothing is materialized for the skipped prefix (resume fast-forward)."""
        idx = epoch_indices(self.plan, self._epoch)
        for start in range(skip_batches * self.batch_size, len(idx), self.batch_size):
            sel = idx[start : start + self.batch_size]
            if len(sel) < self.batch_size and self.plan.drop_last:
                return
            yield self.dataset.x[sel], self.dataset.y[sel]

    def close(self) -> None:
        """Release loader resources.  No-op for the synchronous loader;
        the native PrefetchingLoader joins its C++ worker threads here —
        callers can close any loader unconditionally after training."""


def shard_batch(batch, sharding):
    """Place a per-process host batch as a global sharded array.

    ``sharding`` is a ``NamedSharding`` whose batch axis is split over the
    ``data`` mesh axis.  In multi-process jobs each process contributes its
    local shard via ``jax.make_array_from_process_local_data``; single-process
    it is a plain transfer.  Either way the jitted step sees a global array
    and XLA handles any cross-chip layout.
    """
    from tpudist.comm.collectives import device_put_global
    import jax

    return jax.tree.map(lambda x: device_put_global(np.asarray(x), sharding), batch)
