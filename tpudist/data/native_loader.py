"""ctypes bindings + prefetching loader over the native gather engine.

The TPU-native answer to ``DataLoader(num_workers=N)`` (``demo.py:150``;
the reference's host parallelism is torch's C++ worker pool — external
native code per SURVEY.md §2.4).  Split of responsibilities:

- **Python owns determinism**: batch order comes from the exact same
  seeded :class:`~tpudist.data.sharding.ShardPlan` permutation as the
  synchronous loader — the native path changes WHEN bytes move, never
  WHICH rows are chosen (tests assert batch-for-batch equality).
- **C++ owns the bytes**: ``gather.cpp``'s thread pool copies dataset rows
  into a ring of preallocated batch buffers up to ``prefetch_depth``
  batches ahead, overlapping host assembly with device steps.

The library is compiled lazily with g++ into a per-user cache dir (no
pip/build-system involvement — the environment bakes the toolchain) and
everything degrades to the synchronous numpy path when a compiler or the
.so is unavailable, so the native path is a pure accelerator, never a
dependency.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from tpudist.data.loader import ShardedLoader
from tpudist.data.sharding import epoch_indices

_SRC = Path(__file__).parent / "native" / "gather.cpp"
_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False


def _cache_dir() -> Path:
    base = os.environ.get("TPUDIST_CACHE", os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "tpudist",
    ))
    p = Path(base)
    p.mkdir(parents=True, exist_ok=True)
    return p


def _build_library() -> Optional[Path]:
    """Compile gather.cpp (cached by source hash); None if no toolchain."""
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = _cache_dir() / f"libtpugather-{tag}.so"
    if out.exists():
        return out
    # Build into a sibling temp dir so the final rename is same-filesystem
    # (a /tmp staging dir would make os.replace raise EXDEV on the common
    # tmpfs-/tmp + on-disk-~/.cache split).
    with tempfile.TemporaryDirectory(dir=out.parent) as td:
        tmp_out = Path(td) / out.name
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread",
               str(_SRC), "-o", str(tmp_out)]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp_out, out)  # atomic: concurrent builders are safe
        except (OSError, subprocess.SubprocessError):
            return None
    return out


def load_library() -> Optional[ctypes.CDLL]:
    """The process-wide gather library, built on first use; None on failure."""
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    path = _build_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError:
        return None
    lib.tg_create.restype = ctypes.c_void_p
    lib.tg_create.argtypes = [ctypes.c_int]
    lib.tg_submit.restype = ctypes.c_int64
    lib.tg_submit.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                              ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
    lib.tg_wait.restype = ctypes.c_int
    lib.tg_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.tg_poll.restype = ctypes.c_int
    lib.tg_poll.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.tg_destroy.restype = None
    lib.tg_destroy.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return _LIB


def native_available() -> bool:
    return load_library() is not None


class GatherPool:
    """Thin RAII wrapper over the C thread pool."""

    def __init__(self, num_workers: int):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native gather library unavailable (no g++?)")
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.tg_create(num_workers))

    def submit(self, src: np.ndarray, idx: np.ndarray, dst: np.ndarray) -> int:
        """Enqueue ``dst[i] = src[idx[i]]``.  All arrays must be C-contiguous
        and stay alive (and ``dst`` unread) until :meth:`wait` returns."""
        if self._handle is None:
            raise RuntimeError("GatherPool is closed")
        assert src.flags.c_contiguous and dst.flags.c_contiguous
        assert idx.dtype == np.int64 and idx.flags.c_contiguous
        row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
        return self._lib.tg_submit(
            self._handle,
            src.ctypes.data_as(ctypes.c_void_p), row_bytes,
            idx.ctypes.data_as(ctypes.c_void_p), len(idx),
            dst.ctypes.data_as(ctypes.c_void_p),
        )

    def wait(self, job: int) -> None:
        # After close() every worker has joined, so nothing is running and
        # waiting on a freed pool would be a use-after-free — no-op instead.
        if self._handle is None:
            return
        self._lib.tg_wait(self._handle, job)

    def close(self) -> None:
        if self._handle:
            self._lib.tg_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - interpreter-shutdown best effort
        try:
            self.close()
        except Exception:
            pass


class PrefetchingLoader(ShardedLoader):
    """ShardedLoader with native background batch assembly.

    Yields the same ``(x, y)`` batches in the same order as the synchronous
    loader.  Batch assembly happens in a ring of ``prefetch_depth + 1``
    reused buffers sized so the batch being materialized is never
    concurrently written; the yielded arrays are **copies** of the ring
    slot, upholding ShardedLoader's contract of independent batches.  (A
    zero-copy yield would alias a slot the C++ pool later overwrites —
    JAX's CPU client can do zero-copy ``device_put`` on aligned numpy
    arrays, which would silently corrupt training data on CPU runs.)
    """

    def __init__(self, dataset, batch_size, plan, *, num_workers: int = 2,
                 prefetch_depth: int = 4):
        super().__init__(dataset, batch_size, plan)
        self.num_workers = max(1, num_workers)
        self.prefetch_depth = max(1, prefetch_depth)
        self._pool = GatherPool(self.num_workers)
        self._fields: Sequence[np.ndarray] = [
            np.ascontiguousarray(dataset.x), np.ascontiguousarray(dataset.y)
        ]
        # depth+1 slots: batch i+depth (submitted while yielding batch i)
        # lands in the slot of batch i-1, never batch i's.
        self._slots = [
            tuple(np.empty((batch_size,) + f.shape[1:], f.dtype)
                  for f in self._fields)
            for _ in range(self.prefetch_depth + 1)
        ]

    def iter_from(self, skip_batches: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        idx_all = epoch_indices(self.plan, self._epoch).astype(np.int64)
        starts = list(range(skip_batches * self.batch_size, len(idx_all),
                            self.batch_size))
        if self.plan.drop_last:
            starts = [s for s in starts if s + self.batch_size <= len(idx_all)]

        # (jobs, idx_slice, slot, n_valid) per in-flight batch, FIFO order.
        inflight: list = []

        def submit(batch_i: int) -> None:
            start = starts[batch_i]
            sel = idx_all[start:start + self.batch_size]
            slot = self._slots[batch_i % (self.prefetch_depth + 1)]
            jobs = [
                self._pool.submit(f, sel, dst[: len(sel)])
                for f, dst in zip(self._fields, slot)
            ]
            inflight.append((jobs, sel, slot, len(sel)))

        try:
            for i in range(min(self.prefetch_depth, len(starts))):
                submit(i)
            for i in range(len(starts)):
                jobs, _sel, slot, n = inflight.pop(0)
                for j in jobs:
                    self._pool.wait(j)
                out = tuple(dst[:n].copy() for dst in slot)
                nxt = i + self.prefetch_depth
                if nxt < len(starts):
                    submit(nxt)
                yield out
        finally:
            # Abandoned mid-epoch (break / exception / GeneratorExit): the
            # C++ workers hold raw pointers into idx_all and the slots —
            # drain every in-flight job before this frame (and those
            # buffers) can be freed.
            for jobs, _sel, _slot, _n in inflight:
                for j in jobs:
                    self._pool.wait(j)

    def close(self) -> None:
        self._pool.close()


def make_loader(dataset, batch_size, plan, *, num_workers: int = 0,
                prefetch_depth: int = 4) -> ShardedLoader:
    """Loader factory honoring the reference's ``--num_workers`` semantics:
    0 → synchronous; >0 → native prefetching pool when buildable, with a
    silent fallback to synchronous otherwise (the flag is a performance
    hint, never a correctness requirement)."""
    if num_workers > 0 and native_available():
        return PrefetchingLoader(dataset, batch_size, plan,
                                 num_workers=num_workers,
                                 prefetch_depth=prefetch_depth)
    return ShardedLoader(dataset, batch_size, plan)
