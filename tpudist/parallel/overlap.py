"""Decomposed collective matmul: ppermute-pipelined TP/FSDP gathers.

The TP and FSDP layouts in this package are *pure layouts*: they leave
every all-gather / reduce(-scatter) to the XLA SPMD partitioner, which
schedules the whole gather BEFORE the matmul that consumes it — at scale
that gather is exposed wire time on the critical path (the multiproc
scaling artifact shows the in-step collective dominating everything
else).  This module is the explicit alternative: the collective is
decomposed into a chain of ``lax.ppermute`` hops, each hop moving ONE
chunk while the PREVIOUS chunk's matmul runs — the "collective matmul"
of Wang et al. (overlap-communication-with-dependent-computation) and
the weight-update-sharding line of work (arXiv:2004.13336), hand-built
so overlap is structural, not a compiler mood.

Two shard-local primitives (call them inside ``shard_map``, like
:func:`tpudist.parallel.tensor_parallel.tp_mlp_shard`):

- :func:`ag_matmul` — all-gather fused into a matmul.  Three gather
  geometries cover the TP/FSDP hot paths:

  * ``gather="lhs"``:   ``allgather(x) @ w``   (x row-sharded — the
    sequence/batch-parallel TP input gather);
  * ``gather="rhs"``:   ``x @ allgather(w)``   (w column-sharded — the
    FSDP forward gather of a column-split weight);
  * ``gather="contract"``: ``x @ allgather(w)`` (w row/contraction-
    sharded — the FSDP gather of a row-split weight, accumulated
    chunk-by-chunk as partial products).

  ``lhs``/``rhs`` assemble disjoint output chunks — **bit-exact** vs the
  monolithic gather-then-matmul (each output element is the same dot
  product over the full contraction).  ``contract`` sums one partial
  product per hop, which *reassociates* the contraction: documented
  bound f32 ``rtol <= 1e-5`` vs the monolithic matmul at the tested
  shapes (tests pin it far tighter in practice).

- :func:`matmul_rs` — matmul producing partial products consumed by a
  pipelined reduce-scatter ring: ``psum_scatter(x @ w, axis)`` with each
  ring step's chunk-matmul overlapping the accumulator's transfer.  The
  ring's accumulation order differs from a monolithic ``psum`` —
  same documented f32 bound as ``contract``.

Both take ``mode``:

- ``"ring"``  — unidirectional ring: ``n-1`` hops of one chunk each;
- ``"bidir"`` — bidirectional ring: chunks travel both directions
  simultaneously, ``ceil((n-1)/2)`` hop *depth* at the same total wire
  bytes — the right choice on duplex links (TPU ICI) once latency, not
  bandwidth, binds.

The chains are UNROLLED Python loops over a static ring size — one
compiled program regardless of ring length (the slow-lane test pins
compile counts flat), and XLA can schedule hop ``s+1``'s
collective-permute concurrently with hop ``s``'s matmul because there
is no loop barrier between them.  Every hop is emitted under the
:data:`OVERLAP_SCOPE` named scope, so the emitted collective-permutes
carry a ``tpudist_overlap`` tag in their HLO ``op_name`` metadata —
that tag is how :mod:`tpudist.utils.hlo_audit` classifies the traffic
as *overlapped* (pipeline bytes) rather than *exposed* (monolithic
pre-matmul gathers), and how ``benchmarks/comm_audit.py`` proves from
optimized HLO that the monolithic all-gather is gone.

Selection is by the registered knob ``TPUDIST_OVERLAP``
(``off``/``ring``/``bidir``, default ``off`` — every existing call site
keeps its byte-identical default path); see :func:`overlap_mode`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

#: jax.named_scope wrapped around every pipelined hop; shows up in HLO
#: ``op_name`` metadata (forward, jvp AND transpose ops inherit it) and
#: is what the audit keys on to credit bytes as overlapped.
OVERLAP_SCOPE = "tpudist_overlap"

#: Valid TPUDIST_OVERLAP values.
OVERLAP_MODES = ("off", "ring", "bidir")


def overlap_mode(override: str | None = None) -> str:
    """Resolve the collective-matmul overlap mode.

    ``override`` (a call-site argument) wins when given; otherwise the
    ``TPUDIST_OVERLAP`` env knob decides.  Unset, empty, ``0``/``off``/
    ``false``/``no`` and any unrecognized value all mean ``"off"`` — a
    typo'd knob must never take a job down (envutil contract), and the
    safe behavior is the byte-identical default path.
    """
    import os

    v = override if override is not None else os.environ.get(
        "TPUDIST_OVERLAP", "")
    v = v.strip().lower()
    if v in ("ring", "bidir"):
        return v
    if override is not None and v not in ("", "0", "off", "false", "no"):
        # Explicit call-site arguments are code, not config: fail loud.
        raise ValueError(
            f"overlap must be one of {OVERLAP_MODES}, got {override!r}")
    return "off"


def compat_shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` on jax >= 0.9 (``check_vma``), falling back to
    ``jax.experimental.shard_map`` (``check_rep``) on the older API —
    the overlap layer stays importable and TESTABLE on both, unlike the
    rep-check kwarg soup it papers over.  ``check_vma`` maps onto
    ``check_rep`` on the fallback — which stays ``False`` regardless:
    the old rep-checker has no ``pcast`` escape hatch, so bodies whose
    carries legitimately become varying (ppermute rotations) cannot be
    typed under it.  ``check_vma`` is honored only where the new vma
    checker exists."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def compat_pcast(x, axes, *, to):
    """``lax.pcast`` where the vma type system exists; identity on the
    older API, whose shard_map (run with ``check_rep=False`` — see
    :func:`compat_shard_map`) has no varying-axes types to cast
    between."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to=to)
    return x


def compat_axis_size(axis_name: str) -> int:
    """``lax.axis_size`` where the API has it, else the ``psum(1)``
    fold (static Python int either way — callers unroll chains with
    it, so it must never be a tracer)."""
    if hasattr(lax, "axis_size"):
        return int(lax.axis_size(axis_name))
    return int(lax.psum(1, axis_name))


def _ring_perm(n: int, shift: int):
    """source_target pairs moving every shard ``shift`` ranks around the
    ring (shift=+1: rank r's shard lands on rank r+1)."""
    return [(i, (i + shift) % n) for i in range(n)]


def _axis_env(axis_name: str):
    """(ring size, my index) inside ``shard_map`` — ``psum(1)`` folds to
    a static Python int, so the unrolled chains have static length."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    return int(n), idx


def _check_mode(mode: str) -> str:
    if mode not in ("ring", "bidir"):
        raise ValueError(f"mode must be 'ring' or 'bidir', got {mode!r}")
    return mode


def ag_matmul(x: jax.Array, w: jax.Array, *, axis_name: str,
              mode: str = "ring", gather: str = "lhs") -> jax.Array:
    """All-gather fused into a matmul, pipelined over a ppermute chain.

    Shard-local (call inside ``shard_map``).  ``x: [m, k]``,
    ``w: [k, f]`` are the LOCAL operands; what is sharded (and therefore
    what rides the ring, one chunk per hop, each hop overlapping the
    previous chunk's matmul) depends on ``gather``.

    **Decode-shaped inputs**: for ``gather="rhs"``/``"contract"``, ``x``
    may carry leading batch dims (``[..., m, k]`` — the serving decode
    step's ``[slots, 1, d]`` activations); they are flattened into the
    row axis for the ring and restored on the output.  ``"lhs"`` stays
    2-D only (its row axis IS the sharded global axis — flattening
    batch dims into it would change which rows each rank owns).

    - ``"lhs"``      x is the local ROW shard of a ``[m*n, k]`` global;
                     returns ``allgather(x) @ w: [m*n, f]`` (bit-exact).
    - ``"rhs"``      w is the local COLUMN shard of a ``[k, f*n]``
                     global; returns ``x @ allgather(w): [m, f*n]``
                     (bit-exact).
    - ``"contract"`` w is the local ROW (contraction) shard of a
                     ``[k*n, f]`` global and x holds the FULL ``[m, k*n]``
                     contraction; returns ``x @ allgather(w): [m, f]``
                     accumulated one partial product per hop
                     (reassociated — documented f32 bound 1e-5).

    ``mode="bidir"`` halves the hop depth by sending chunks both ways
    (same total wire bytes).  n=1 degenerates to the plain matmul.
    """
    _check_mode(mode)
    if gather not in ("lhs", "rhs", "contract"):
        raise ValueError(
            f"gather must be 'lhs', 'rhs' or 'contract', got {gather!r}")
    lead, m = x.shape[:-2], x.shape[-2]
    if lead:
        if gather == "lhs":
            raise ValueError(
                "gather='lhs' requires 2-D x (the row axis is the sharded "
                f"global axis); got shape {x.shape} — flatten explicitly "
                "or use gather='rhs'/'contract'")
        x = x.reshape((-1, x.shape[-1]))
    n, idx = _axis_env(axis_name)
    if n == 1:
        out = x @ w
    elif gather == "lhs":
        return _ag_matmul_lhs(x, w, axis_name, n, idx, mode)
    elif gather == "rhs":
        out = _ag_matmul_rhs(x, w, axis_name, n, idx, mode)
    else:
        out = _ag_matmul_contract(x, w, axis_name, n, idx, mode)
    if lead:
        out = out.reshape(lead + (m, out.shape[-1]))
    return out


def _ag_matmul_lhs(x, w, axis_name, n, idx, mode):
    m = x.shape[0]
    out = jnp.zeros((m * n, w.shape[1]), x.dtype)

    def write(buf, src_idx, chunk):
        return lax.dynamic_update_slice(buf, chunk, (src_idx * m, 0))

    with jax.named_scope(OVERLAP_SCOPE):
        if mode == "ring":
            cur = x
            for s in range(n):
                # after s hops (+1 direction) I hold rank (idx - s)'s rows
                out = write(out, (idx - s) % n, cur @ w)
                if s + 1 < n:
                    cur = lax.ppermute(cur, axis_name, _ring_perm(n, +1))
            return out
        # bidir: fwd buffer travels +1 (delivers idx-s), bwd travels -1
        # (delivers idx+s); full steps floor((n-1)/2), plus one final
        # forward half-step when n is even.
        fwd = bwd = x
        out = write(out, idx % n, x @ w)
        for s in range(1, (n - 1) // 2 + 1):
            fwd = lax.ppermute(fwd, axis_name, _ring_perm(n, +1))
            bwd = lax.ppermute(bwd, axis_name, _ring_perm(n, -1))
            out = write(out, (idx - s) % n, fwd @ w)
            out = write(out, (idx + s) % n, bwd @ w)
        if n % 2 == 0:
            fwd = lax.ppermute(fwd, axis_name, _ring_perm(n, +1))
            out = write(out, (idx - n // 2) % n, fwd @ w)
        return out


def _ag_matmul_rhs(x, w, axis_name, n, idx, mode):
    f = w.shape[1]
    out = jnp.zeros((x.shape[0], f * n), x.dtype)

    def write(buf, src_idx, chunk):
        return lax.dynamic_update_slice(buf, chunk, (0, src_idx * f))

    with jax.named_scope(OVERLAP_SCOPE):
        if mode == "ring":
            cur = w
            for s in range(n):
                out = write(out, (idx - s) % n, x @ cur)
                if s + 1 < n:
                    cur = lax.ppermute(cur, axis_name, _ring_perm(n, +1))
            return out
        fwd = bwd = w
        out = write(out, idx % n, x @ w)
        for s in range(1, (n - 1) // 2 + 1):
            fwd = lax.ppermute(fwd, axis_name, _ring_perm(n, +1))
            bwd = lax.ppermute(bwd, axis_name, _ring_perm(n, -1))
            out = write(out, (idx - s) % n, x @ fwd)
            out = write(out, (idx + s) % n, x @ bwd)
        if n % 2 == 0:
            fwd = lax.ppermute(fwd, axis_name, _ring_perm(n, +1))
            out = write(out, (idx - n // 2) % n, x @ fwd)
        return out


def _ag_matmul_contract(x, w, axis_name, n, idx, mode):
    k = w.shape[0]  # local contraction-shard depth
    if x.shape[1] != k * n:
        raise ValueError(
            f"gather='contract' needs x.shape[1] == {k * n} "
            f"(n={n} shards of k={k}), got {x.shape[1]}")

    def xchunk(src_idx):
        return lax.dynamic_slice(x, (0, src_idx * k), (x.shape[0], k))

    with jax.named_scope(OVERLAP_SCOPE):
        if mode == "ring":
            cur = w
            acc = xchunk(idx % n) @ cur
            for s in range(1, n):
                cur = lax.ppermute(cur, axis_name, _ring_perm(n, +1))
                acc = acc + xchunk((idx - s) % n) @ cur
            return acc
        # bidir: column halves of w travel opposite directions; each
        # accumulator sees every contraction shard once.
        fh = w.shape[1] // 2
        if fh == 0:
            raise ValueError("bidir contract-gather needs w.shape[1] >= 2")
        fwd, bwd = w[:, :fh], w[:, fh:]
        acc_f = xchunk(idx % n) @ fwd
        acc_b = xchunk(idx % n) @ bwd
        for s in range(1, n):
            fwd = lax.ppermute(fwd, axis_name, _ring_perm(n, +1))
            bwd = lax.ppermute(bwd, axis_name, _ring_perm(n, -1))
            acc_f = acc_f + xchunk((idx - s) % n) @ fwd
            acc_b = acc_b + xchunk((idx + s) % n) @ bwd
        return jnp.concatenate([acc_f, acc_b], axis=1)


def matmul_rs(x: jax.Array, w: jax.Array, *, axis_name: str,
              mode: str = "ring", pad_rows: bool = False) -> jax.Array:
    """Matmul feeding a pipelined reduce-scatter ring:
    ``psum_scatter(x @ w, axis_name, scatter over rows)``.

    Shard-local (call inside ``shard_map``).  ``x: [m, k]`` (k is this
    device's shard of the contraction, e.g. a row-parallel weight's
    input), ``w: [k, f]``; every device holds a partial ``[m, f]``
    product implicitly — instead of materializing it and reduce-
    scattering afterwards, each ring step computes ONE ``[m/n, f]`` row
    chunk of the partial and adds it to the accumulator arriving from
    the neighbor, so the chunk matmul overlaps the accumulator's
    transfer.  Returns this device's fully-reduced ``[m/n, f]`` chunk.

    Accumulation order differs from a monolithic ``psum`` (ring order,
    rotated per device) — documented f32 bound ``rtol <= 1e-5`` at the
    tested shapes.  ``mode="bidir"`` splits the f columns into halves
    riding opposite directions (same hop count, both link directions
    busy).  ``m`` must divide by the ring size — unless
    ``pad_rows=True`` (the decode-shaped variant: serving batches are
    ``num_slots`` rows, rarely a ring multiple), which zero-pads the
    rows up to the next multiple; every device then returns its
    ``ceil(m/n)``-row chunk of the PADDED result, and the caller slices
    the assembled ``[pad_m, f]`` back to ``m`` rows after the
    ``shard_map`` reassembles it.
    """
    _check_mode(mode)
    n, idx = _axis_env(axis_name)
    if n == 1:
        return x @ w
    m = x.shape[0]
    if m % n:
        if not pad_rows:
            raise ValueError(
                f"matmul_rs needs rows {m} divisible by ring {n} "
                "(pass pad_rows=True for the padded decode-shaped variant)")
        pad = (n - m % n) % n
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)
        m = x.shape[0]
    mloc = m // n

    def xrows(chunk_idx):
        return lax.dynamic_slice(x, (chunk_idx * mloc, 0), (mloc, x.shape[1]))

    with jax.named_scope(OVERLAP_SCOPE):
        if mode == "ring":
            # chunk c starts at rank c+1, travels +1, lands summed on c
            acc = xrows((idx - 1) % n) @ w
            for s in range(1, n):
                acc = lax.ppermute(acc, axis_name, _ring_perm(n, +1))
                acc = acc + xrows((idx - 1 - s) % n) @ w
            return acc
        fh = w.shape[1] // 2
        if fh == 0:
            raise ValueError("bidir matmul_rs needs w.shape[1] >= 2")
        wf, wb = w[:, :fh], w[:, fh:]
        # forward half: chunk c starts at c+1, travels +1; backward
        # half: chunk c starts at c-1, travels -1.
        acc_f = xrows((idx - 1) % n) @ wf
        acc_b = xrows((idx + 1) % n) @ wb
        for s in range(1, n):
            acc_f = lax.ppermute(acc_f, axis_name, _ring_perm(n, +1))
            acc_b = lax.ppermute(acc_b, axis_name, _ring_perm(n, -1))
            acc_f = acc_f + xrows((idx - 1 - s) % n) @ wf
            acc_b = acc_b + xrows((idx + 1 + s) % n) @ wb
        return jnp.concatenate([acc_f, acc_b], axis=1)


# Re-exported so call sites (tensor_parallel, fsdp) need one import and
# the registry test sees the knob consumed where it is parsed.
__all__ = [
    "OVERLAP_MODES",
    "OVERLAP_SCOPE",
    "ag_matmul",
    "compat_shard_map",
    "matmul_rs",
    "overlap_mode",
]
