"""Ring-attention sequence/context parallelism.

Long-context scaling: the sequence axis is sharded over the mesh's ``seq``
axis, each device holds one Q/K/V block, and K/V blocks rotate around the
ring with ``jax.lax.ppermute`` (one ICI hop per step) while each device
accumulates its Q block's attention with an online-softmax update — the
blockwise formulation of Liu et al.'s Ring Attention.  Peak memory per
device is O(seq/num_devices), so context length scales linearly with ring
size at constant per-chip memory.

The reference has no attention anywhere (its model is a 5-layer MLP on
2-dim inputs — ``toy_model_and_data.py:12-22``; SURVEY.md §5.7 records
sequence parallelism as absent), so this module is a capability extension,
designed TPU-first:

- every op inside the shard-local body is ``jnp``/``lax`` — XLA fuses the
  softmax-rescale chain and keeps the two matmuls per step on the MXU;
- the ring hop is ``lax.ppermute`` over the named axis, which XLA lowers to
  neighbor ICI transfers that overlap with the block's compute;
- the whole construct is differentiable (ppermute's transpose is the
  reverse permutation), so the same code path trains.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.runtime.mesh import AXIS_SEQ

# Finite stand-in for -inf: keeps exp() NaN-free when a whole row is masked
# (a fully-masked KV block contributes exp(NEG - m_finite) == 0).
_MASK_VALUE = -1e30


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
) -> jax.Array:
    """Plain softmax attention — the single-device ground truth.

    Shapes: ``q, k, v: [batch, heads, seq, head_dim]``.
    """
    scale = q.shape[-1] ** -0.5
    # Mixed-precision discipline (a no-op for f32 inputs): MXU operands in
    # the input dtype, score accumulation + softmax in f32, output cast back.
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_len, k_len = scores.shape[-2], scores.shape[-1]
        qi = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)
        kj = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 1)
        scores = jnp.where(qi >= kj, scores, _MASK_VALUE)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _block_update(q, k, v, m, l, o, *, scale, mask=None):
    """One online-softmax accumulation step over a KV block.

    ``m`` row-max, ``l`` normalizer sum, ``o`` unnormalized output — the
    (m, l, o) running triple of blockwise/flash attention.  The carry is
    f32 whatever the input dtype (mixed-precision discipline: MXU operands
    in the input dtype, accumulation in f32).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _MASK_VALUE)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def _causal_mask(q_off, k_off, bq: int, bk: int):
    q_pos = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos >= k_pos


def ring_attention_shard(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = AXIS_SEQ,
    causal: bool = False,
    inner_block: Optional[int] = None,
) -> jax.Array:
    """Shard-local ring attention body (call inside ``shard_map``).

    Each device holds contiguous blocks ``q, k, v: [b, h, seq_shard, d]`` of
    the globally seq-sharded arrays.  K/V travel the ring; at step ``t`` this
    device processes the block that originated on rank ``(i - t) mod n``, so
    step 0 is its own (diagonal) block — which guarantees the first processed
    block is never fully masked under causal attention.

    ``inner_block``: when set, each ring step's KV shard is consumed by a
    rematerialized ``lax.scan`` of ``inner_block``-wide sub-blocks instead
    of one [shard, shard] score matrix — peak per-device attention memory
    drops from O(shard²) to O(shard·inner_block), which is what lets very
    long shards (many thousands of tokens per chip) train.
    """
    axis_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = q.shape[-1] ** -0.5
    block = q.shape[-2]

    # pcast-to-varying: the carries join a scan whose outputs vary over the
    # seq axis (they mix in the sharded q/k/v), so the initial values must
    # carry the same varying-manual-axes type.
    m = lax.pcast(jnp.full(q.shape[:-1], _MASK_VALUE, jnp.float32),
                  (axis_name,), to="varying")
    l = lax.pcast(jnp.zeros(q.shape[:-1], jnp.float32),
                  (axis_name,), to="varying")
    o = lax.pcast(jnp.zeros(q.shape, jnp.float32), (axis_name,), to="varying")
    q_off = my_idx * block

    def consume_shard(kv_idx, k, v, m, l, o):
        """Fold one ring step's KV shard into the (m, l, o) carry."""
        if inner_block is None:
            mask = _causal_mask(q_off, kv_idx * block, block, block) \
                if causal else None
            return _block_update(q, k, v, m, l, o, scale=scale, mask=mask)
        nb = block // inner_block
        if block % inner_block:
            raise ValueError(
                f"inner_block {inner_block} must divide seq shard {block}"
            )
        kb = jnp.moveaxis(
            k.reshape(*k.shape[:-2], nb, inner_block, k.shape[-1]), -3, 0
        )
        vb = jnp.moveaxis(
            v.reshape(*v.shape[:-2], nb, inner_block, v.shape[-1]), -3, 0
        )

        @jax.checkpoint
        def sub(carry, blk):
            m, l, o = carry
            sub_i, kt, vt = blk
            mask = None
            if causal:
                mask = _causal_mask(
                    q_off, kv_idx * block + sub_i * inner_block,
                    block, inner_block,
                )
            return _block_update(q, kt, vt, m, l, o, scale=scale, mask=mask), None

        (m, l, o), _ = lax.scan(sub, (m, l, o), (jnp.arange(nb), kb, vb))
        return m, l, o

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    for step in range(axis_size):
        kv_idx = (my_idx - step) % axis_size
        m, l, o = consume_shard(kv_idx, k, v, m, l, o)
        if step + 1 < axis_size:
            # One ICI hop: K/V move to the right neighbor while the next
            # step's compute is still queued — XLA overlaps the two.
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
    return (o / l[..., None]).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    *,
    axis_name: str = AXIS_SEQ,
    causal: bool = False,
    batch_axis: Optional[str] = None,
    inner_block: Optional[int] = None,
):
    """Jitted global-view ring attention over ``mesh``.

    Inputs/outputs are global ``[batch, heads, seq, head_dim]`` arrays with
    ``seq`` sharded over ``axis_name`` (and optionally ``batch`` over
    ``batch_axis``).  Sequence length must divide evenly by the ring size
    (the equal-block contract, like the reference's equal-batch assumption
    ``demo.py:113``).
    """
    spec = P(batch_axis, None, axis_name, None)
    body = functools.partial(
        ring_attention_shard, axis_name=axis_name, causal=causal,
        inner_block=inner_block,
    )
    sharded = jax.shard_map(
        lambda q, k, v: body(q, k, v),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return jax.jit(sharded)
