"""Ring-attention sequence/context parallelism.

Long-context scaling: the sequence axis is sharded over the mesh's ``seq``
axis, each device holds one Q/K/V block, and K/V blocks rotate around the
ring with ``jax.lax.ppermute`` (one ICI hop per step) while each device
accumulates its Q block's attention with an online-softmax update — the
blockwise formulation of Liu et al.'s Ring Attention.  Peak memory per
device is O(seq/num_devices), so context length scales linearly with ring
size at constant per-chip memory.

The reference has no attention anywhere (its model is a 5-layer MLP on
2-dim inputs — ``toy_model_and_data.py:12-22``; SURVEY.md §5.7 records
sequence parallelism as absent), so this module is a capability extension,
designed TPU-first:

- every op inside the shard-local body is ``jnp``/``lax`` — XLA fuses the
  softmax-rescale chain and keeps the two matmuls per step on the MXU;
- the ring hop is ``lax.ppermute`` over the named axis, which XLA lowers to
  neighbor ICI transfers that overlap with the block's compute;
- the whole construct is differentiable (ppermute's transpose is the
  reverse permutation), so the same code path trains.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.parallel.overlap import (compat_axis_size,
                                     compat_pcast, compat_shard_map)
from tpudist.runtime.mesh import AXIS_SEQ

# Finite stand-in for -inf: keeps exp() NaN-free when a whole row is masked
# (a fully-masked KV block contributes exp(NEG - m_finite) == 0).
_MASK_VALUE = -1e30


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    window: Optional[int] = None,
) -> jax.Array:
    """Plain softmax attention — the single-device ground truth.

    Shapes: ``q, k, v: [batch, heads, seq, head_dim]``.  ``window``
    (requires ``causal``) masks to the sliding band ``q − k < window``.
    """
    scale = q.shape[-1] ** -0.5
    # Mixed-precision discipline (a no-op for f32 inputs): MXU operands in
    # the input dtype, score accumulation + softmax in f32, output cast back.
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if causal:
        q_len, k_len = scores.shape[-2], scores.shape[-1]
        qi = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)
        kj = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 1)
        keep = qi >= kj
        if window is not None:
            keep &= qi - kj < window
        scores = jnp.where(keep, scores, _MASK_VALUE)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _block_update(q, k, v, m, l, o, *, scale, mask=None):
    """One online-softmax accumulation step over a KV block.

    ``m`` row-max, ``l`` normalizer sum, ``o`` unnormalized output — the
    (m, l, o) running triple of blockwise/flash attention.  The carry is
    f32 whatever the input dtype (mixed-precision discipline: MXU operands
    in the input dtype, accumulation in f32).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _MASK_VALUE)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def _causal_mask(q_off, k_off, bq: int, bk: int, window=None):
    q_pos = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    keep = q_pos >= k_pos
    if window is not None:
        keep &= q_pos - k_pos < window
    return keep


def ring_attention_shard(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = AXIS_SEQ,
    causal: bool = False,
    inner_block: Optional[int] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Shard-local ring attention body (call inside ``shard_map``).

    Each device holds contiguous blocks ``q, k, v: [b, h, seq_shard, d]`` of
    the globally seq-sharded arrays.  K/V travel the ring; at step ``t`` this
    device processes the block that originated on rank ``(i - t) mod n``, so
    step 0 is its own (diagonal) block — which guarantees the first processed
    block is never fully masked under causal attention.

    ``inner_block``: when set, each ring step's KV shard is consumed by a
    rematerialized ``lax.scan`` of ``inner_block``-wide sub-blocks instead
    of one [shard, shard] score matrix — peak per-device attention memory
    drops from O(shard²) to O(shard·inner_block), which is what lets very
    long shards (many thousands of tokens per chip) train.
    """
    axis_size = compat_axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = q.shape[-1] ** -0.5
    block = q.shape[-2]
    # Grouped-query K/V: the RING carries the small hkv-headed tensors
    # (group x fewer bytes per ICI hop) and each device broadcasts to
    # full heads only at compute time, inside consume_shard.
    if q.shape[1] % k.shape[1]:
        raise ValueError(f"q heads {q.shape[1]} not a multiple of "
                         f"kv heads {k.shape[1]}")
    kv_group = q.shape[1] // k.shape[1]

    # pcast-to-varying: the carries join a scan whose outputs vary over the
    # seq axis (they mix in the sharded q/k/v), so the initial values must
    # carry the same varying-manual-axes type.
    m = compat_pcast(jnp.full(q.shape[:-1], _MASK_VALUE, jnp.float32),
                  (axis_name,), to="varying")
    l = compat_pcast(jnp.zeros(q.shape[:-1], jnp.float32),
                  (axis_name,), to="varying")
    o = compat_pcast(jnp.zeros(q.shape, jnp.float32), (axis_name,), to="varying")
    q_off = my_idx * block

    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")

    def consume_shard(kv_idx, k, v, m, l, o):
        """Fold one ring step's KV shard into the (m, l, o) carry."""
        if kv_group > 1:  # broadcast AFTER the hop — wire stays narrow
            k = jnp.repeat(k, kv_group, axis=1)
            v = jnp.repeat(v, kv_group, axis=1)
        if inner_block is None:
            mask = _causal_mask(q_off, kv_idx * block, block, block,
                                window) if causal else None
            return _block_update(q, k, v, m, l, o, scale=scale, mask=mask)
        nb = block // inner_block
        if block % inner_block:
            raise ValueError(
                f"inner_block {inner_block} must divide seq shard {block}"
            )
        kb = jnp.moveaxis(
            k.reshape(*k.shape[:-2], nb, inner_block, k.shape[-1]), -3, 0
        )
        vb = jnp.moveaxis(
            v.reshape(*v.shape[:-2], nb, inner_block, v.shape[-1]), -3, 0
        )

        @jax.checkpoint
        def sub(carry, blk):
            m, l, o = carry
            sub_i, kt, vt = blk
            mask = None
            if causal:
                mask = _causal_mask(
                    q_off, kv_idx * block + sub_i * inner_block,
                    block, inner_block, window,
                )
            return _block_update(q, kt, vt, m, l, o, scale=scale, mask=mask), None

        (m, l, o), _ = lax.scan(sub, (m, l, o), (jnp.arange(nb), kb, vb))
        return m, l, o

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    for step in range(axis_size):
        kv_idx = (my_idx - step) % axis_size
        m, l, o = consume_shard(kv_idx, k, v, m, l, o)
        if window is not None and window - (step + 1) * block <= -(block - 1):
            # Sliding window: every later hop is fully masked for every
            # device (un-wrapped hops sit left of the band at the static
            # offset (step+1)·block; wrapped hops are causally dead) —
            # stop the ring, same static break as the flash body.
            break
        if step + 1 < axis_size:
            # One ICI hop: K/V move to the right neighbor while the next
            # step's compute is still queued — XLA overlaps the two.
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
    return (o / l[..., None]).astype(q.dtype)


def _merge_partials(out_c, lse_c, out_h, lse_h):
    """Exact, stabilized merge of two attention partials over disjoint KV
    sets, each given as (normalized out, row logsumexp).  Fully-masked
    partials (lse == _MASK_VALUE, out == 0) merge to a no-op.  All f32."""
    m = jnp.maximum(lse_c, lse_h)
    w_c = jnp.exp(lse_c - m)
    w_h = jnp.exp(lse_h - m)
    denom = w_c + w_h
    lse_new = m + jnp.log(denom)
    out_new = (
        out_c * w_c[..., None] + out_h * w_h[..., None]
    ) / denom[..., None]
    return out_new, lse_new


def ring_attention_shard_flash(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = AXIS_SEQ,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
    window: Optional[int] = None,
) -> jax.Array:
    """Shard-local ring attention whose per-hop math is the Pallas flash
    kernel (call inside ``shard_map``).

    Same ring schedule as :func:`ring_attention_shard`, different
    decomposition: instead of threading the raw (m, l, o) online-softmax
    carry through XLA block updates, each hop computes a *complete*
    attention over its KV shard with :func:`tpudist.ops.flash_attention_with_lse`
    and the partials are merged via their logsumexps (`_merge_partials`) —
    O(shard) XLA work per hop, while every O(shard²·d) FLOP runs in the
    flash kernels, forward AND backward (the kernel's custom VJP folds the
    lse cotangent into its delta term).

    With equal shards and the step-t block originating on rank
    ``(i−t) mod n``, causal masking collapses to three static-per-hop
    cases: hop 0 is the diagonal (causal kernel), later hops are either
    fully live (unmasked kernel) or fully dead (skipped via ``lax.cond``
    — half the ring's compute under causal attention, the same work the
    XLA path spends masked).
    """
    from tpudist.ops import flash_attention_with_lse

    # Trace-time fit check (shard shapes are static here): the kernel needs
    # the clamped blocks to divide the shard.  Fall back to the XLA carry
    # path otherwise — same semantics, no shape constraint.
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    shard = q.shape[-2]
    if shard % min(block_q, shard) or shard % min(block_k, shard):
        if k.shape[1] != q.shape[1]:  # xla body needs equal heads
            group = q.shape[1] // k.shape[1]
            k = jnp.repeat(k, group, axis=1)
            v = jnp.repeat(v, group, axis=1)
        return ring_attention_shard(
            q, k, v, axis_name=axis_name, causal=causal, window=window
        )

    axis_size = compat_axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)

    # Hop 0 is this device's own (diagonal) KV shard: causal kernel
    # (windowed if requested).  out_f32: partials stay f32 through every
    # merge whatever the input dtype (parity with the XLA path's f32
    # (m, l, o) carry).
    out, lse = flash_attention_with_lse(
        q, k, v, causal, block_q, block_k, interpret, True, window
    )

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    for step in range(1, axis_size):
        if window is not None and window - step * shard <= -(shard - 1):
            # The band ends before this hop's shard for EVERY device (the
            # un-wrapped local offset q − k = step·shard is static), and
            # later hops are further left still: with a sliding window the
            # ring stops here — compute scales with window, not seq.
            break
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        kv_idx = (my_idx - step) % axis_size
        if causal:
            # Un-wrapped hops (kv_idx < my_idx) sit wholly in the causal
            # past: the per-hop kernel needs no causal mask, only the
            # window band shifted by the static hop offset step·shard.
            band = (None, window - step * shard) if window is not None \
                else None

            def live_hop(kt, vt):
                return flash_attention_with_lse(
                    q, kt, vt, False, block_q, block_k, interpret, True,
                    band,
                )

            def dead_hop(kt, vt):
                return (
                    jnp.zeros(q.shape, jnp.float32),
                    jnp.full(q.shape[:-1], _MASK_VALUE, jnp.float32),
                )

            out_h, lse_h = lax.cond(kv_idx < my_idx, live_hop, dead_hop, k, v)
        else:
            out_h, lse_h = flash_attention_with_lse(
                q, k, v, False, block_q, block_k, interpret, True
            )
        out, lse = _merge_partials(out, lse, out_h, lse_h)
    return out.astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    *,
    axis_name: str = AXIS_SEQ,
    causal: bool = False,
    batch_axis: Optional[str] = None,
    inner_block: Optional[int] = None,
    kernel: str = "auto",
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
    window: Optional[int] = None,
):
    """Jitted global-view ring attention over ``mesh``.

    Inputs/outputs are global ``[batch, heads, seq, head_dim]`` arrays with
    ``seq`` sharded over ``axis_name`` (and optionally ``batch`` over
    ``batch_axis``).  Sequence length must divide evenly by the ring size
    (the equal-block contract, like the reference's equal-batch assumption
    ``demo.py:113``).

    ``kernel`` selects the shard-local math: ``'xla'`` = the
    (m, l, o)-carry block updates (:func:`ring_attention_shard`),
    ``'flash'`` = the Pallas per-hop kernels
    (:func:`ring_attention_shard_flash`; shards whose shape doesn't fit
    the block contract fall back to the xla body at trace time),
    ``'auto'`` = flash on TPU — unless ``inner_block`` was explicitly
    requested (a memory-blocking contract only the xla body honors).
    """
    if kernel not in ("auto", "xla", "flash"):
        raise ValueError(f"kernel must be auto|xla|flash, got {kernel!r}")
    spec = P(batch_axis, None, axis_name, None)
    if kernel == "auto":
        on_tpu = jax.devices()[0].platform == "tpu"
        kernel = "flash" if (on_tpu or interpret) and inner_block is None \
            else "xla"
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if kernel == "flash":
        body = functools.partial(
            ring_attention_shard_flash, axis_name=axis_name, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
            window=window,
        )
    else:
        body = functools.partial(
            ring_attention_shard, axis_name=axis_name, causal=causal,
            inner_block=inner_block, window=window,
        )
    sharded = compat_shard_map(
        lambda q, k, v: body(q, k, v),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # pallas_call's out_shape carries no varying-manual-axes type, so
        # the vma checker cannot type the flash path; the xla path keeps it
        # (its carries are explicitly pcast).
        check_vma=(kernel != "flash"),
    )
    ring = jax.jit(sharded)
    # Window tag consumed by Block's sliding_window training-path guard.
    ring.window = window
    # BOTH bodies consume grouped-query K/V natively (Block then skips
    # its repeat): the flash kernels fetch KV tiles once per group; the
    # xla body hops the small hkv-headed tensors and broadcasts post-hop
    # — either way the ring wire carries group x fewer KV bytes.
    ring.supports_gqa = True
    return ring


# ---------------------------------------------------------------------------
# Zigzag (causal-balanced) ring layout
# ---------------------------------------------------------------------------

def zigzag_indices(seq_len: int, n_shards: int) -> jnp.ndarray:
    """Token permutation for the zigzag causal-balanced ring layout.

    The sequence splits into ``2n`` half-chunks; ring position ``i`` holds
    half-chunks ``i`` and ``2n−1−i``.  Returns the gather indices ``π``
    such that ``x[..., π, :]`` is the zigzag-ordered sequence whose
    contiguous ``seq_len/n``-wide shards land one per device under the
    usual ``P(seq)`` sharding.  Invert with ``jnp.argsort(π)``.
    """
    if seq_len % (2 * n_shards):
        raise ValueError(
            f"seq {seq_len} must divide into 2*{n_shards} half-chunks")
    half = seq_len // (2 * n_shards)
    order = []
    for i in range(n_shards):
        order += [i, 2 * n_shards - 1 - i]
    import numpy as _np

    chunks = [_np.arange(c * half, (c + 1) * half) for c in order]
    return jnp.asarray(_np.concatenate(chunks), jnp.int32)


def ring_attention_shard_zigzag(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = AXIS_SEQ,
) -> jax.Array:
    """Causal ring attention over the ZIGZAG layout — FLOP-balanced.

    The contiguous causal ring wastes ~half the machine: at hop ``t``
    only devices ``i ≥ t`` hold live (unmasked) K/V, so every hop runs at
    single-block latency while early ranks idle (or, in the uniform
    formulation, burn fully-masked FLOPs) — aggregate efficiency
    ``(n+1)/2n → ½``.  The zigzag layout (Brandon et al., "Striped
    Attention"-family; each device owns half-chunks ``i`` AND ``2n−1−i``)
    makes every (device, hop) pair cost EXACTLY two half-chunk attention
    blocks:

    - my high chunk ``2n−1−i`` attends every arriving low chunk ``j``
      (always fully live, never masked);
    - exactly one of {my low × arriving low (live iff ``j ≤ i``), my
      high × arriving high (live iff ``j ≥ i``)} is live per hop —
      selected by a ``lax.cond`` whose branches cost the same, so the
      ring never waits on a straggler;
    - hop 0 (``j == i``) additionally carries the two triangular
      diagonal blocks (statically unrolled — ``t`` is a Python int).

    Inputs: this device's zigzag-local blocks ``[b, h, shard, d]`` with
    ``shard = seq/n`` tokens = half-chunks ``(i, 2n−1−i)`` concatenated
    (produce with :func:`zigzag_indices`).  Causal only (that is the
    regime with the imbalance); equal q/kv heads (broadcast GQA first);
    sliding windows not supported — the window's early-exit already
    rebalances the contiguous ring.
    """
    axis_size = compat_axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale = q.shape[-1] ** -0.5
    shard = q.shape[-2]
    if shard % 2:
        raise ValueError(f"zigzag shard must be even, got {shard}")
    half = shard // 2
    if q.shape[1] % k.shape[1]:
        raise ValueError(f"q heads {q.shape[1]} not a multiple of "
                         f"kv heads {k.shape[1]}")
    kv_group = q.shape[1] // k.shape[1]

    q_lo, q_hi = q[..., :half, :], q[..., half:, :]

    def fresh(qb):
        return (
            compat_pcast(jnp.full(qb.shape[:-1], _MASK_VALUE, jnp.float32),
                      (axis_name,), to="varying"),
            compat_pcast(jnp.zeros(qb.shape[:-1], jnp.float32),
                      (axis_name,), to="varying"),
            compat_pcast(jnp.zeros(qb.shape, jnp.float32),
                      (axis_name,), to="varying"),
        )

    lo_carry, hi_carry = fresh(q_lo), fresh(q_hi)

    def diag_mask():
        qi = lax.broadcasted_iota(jnp.int32, (half, half), 0)
        kj = lax.broadcasted_iota(jnp.int32, (half, half), 1)
        return qi >= kj

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    for t in range(axis_size):
        if kv_group > 1:  # hop the small tensors, broadcast at compute
            kf = jnp.repeat(k, kv_group, axis=1)
            vf = jnp.repeat(v, kv_group, axis=1)
        else:
            kf, vf = k, v
        k_lo, k_hi = kf[..., :half, :], kf[..., half:, :]
        v_lo, v_hi = vf[..., :half, :], vf[..., half:, :]
        if t == 0:
            # j == i: both diagonals (triangular) + the always-live full.
            lo_carry = _block_update(q_lo, k_lo, v_lo, *lo_carry,
                                     scale=scale, mask=diag_mask())
            hi_carry = _block_update(q_hi, k_lo, v_lo, *hi_carry,
                                     scale=scale)
            hi_carry = _block_update(q_hi, k_hi, v_hi, *hi_carry,
                                     scale=scale, mask=diag_mask())
        else:
            j = jnp.mod(my - t, axis_size)
            # my high × arriving low: always fully live, maskless.
            hi_carry = _block_update(q_hi, k_lo, v_lo, *hi_carry,
                                     scale=scale)

            # exactly one of (lo×lo | hi×hi) is live; equal-cost branches.
            def lo_branch(args):
                lo, hi, kl, vl, kh, vh = args
                return (_block_update(q_lo, kl, vl, *lo, scale=scale), hi)

            def hi_branch(args):
                lo, hi, kl, vl, kh, vh = args
                return (lo, _block_update(q_hi, kh, vh, *hi, scale=scale))

            lo_carry, hi_carry = lax.cond(
                j < my, lo_branch, hi_branch,
                (lo_carry, hi_carry, k_lo, v_lo, k_hi, v_hi))
        if t + 1 < axis_size:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)

    m_lo, l_lo, o_lo = lo_carry
    m_hi, l_hi, o_hi = hi_carry
    out_lo = (o_lo / l_lo[..., None]).astype(q.dtype)
    out_hi = (o_hi / l_hi[..., None]).astype(q.dtype)
    return jnp.concatenate([out_lo, out_hi], axis=-2)


def make_zigzag_ring_attention(
    mesh: Mesh,
    *,
    axis_name: str = AXIS_SEQ,
    batch_axis: Optional[str] = None,
):
    """Jitted global-view zigzag ring attention (causal).

    Consumes/produces arrays in the ZIGZAG order — permute tokens with
    ``zigzag_indices(seq, mesh.shape[axis_name])`` before, and apply the
    inverse (``jnp.argsort``) after if positional order matters
    downstream.  For an LM, permute the token stream once at the data
    layer (positions travel with the tokens via RoPE/position ids) and
    the loss — a per-position mean — needs no unpermute.
    """
    spec = P(batch_axis, None, axis_name, None)
    sharded = compat_shard_map(
        functools.partial(ring_attention_shard_zigzag, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=True,
    )
    ring = jax.jit(sharded)
    ring.window = None
    ring.supports_gqa = True  # hops hkv-headed K/V, broadcasts post-hop
    return ring


def make_zigzag_lm_loss(seq_len: int, n_shards: int):
    """Next-token LM loss for a zigzag-permuted token stream.

    Under ``π = zigzag_indices(seq_len, n_shards)``, array position ``p``
    holds the token of temporal position ``π(p)``; its prediction target
    is the token at temporal ``π(p)+1``, which lives at array position
    ``argsort(π)[π(p)+1]``.  Both maps are static, so targets are one
    gather of the (permuted) token batch itself, with the final temporal
    position masked out.  Returns ``loss_fn(logits, tokens)`` —
    drop-in for ``make_lm_train_step(..., loss_fn=...)`` — numerically
    identical to :func:`tpudist.models.transformer.lm_loss` on the
    natural order (tests assert it).
    """
    import numpy as _np

    from tpudist.models.transformer import lm_loss_with_targets

    pi = _np.asarray(zigzag_indices(seq_len, n_shards))
    inv = _np.argsort(pi)
    nxt = _np.where(pi + 1 < seq_len, inv[(pi + 1) % seq_len], -1)
    nxt_idx = jnp.asarray(_np.where(nxt >= 0, nxt, 0), jnp.int32)
    mask = jnp.asarray(nxt >= 0)

    def loss_fn(logits, tokens):
        targets = jnp.where(mask[None, :], tokens[:, nxt_idx], -1)
        return lm_loss_with_targets(logits, targets)

    return loss_fn
