"""Pipeline-parallel TransformerLM: the GPipe schedule of
:mod:`tpudist.parallel.pipeline` applied to the LM block stack, composed
with data parallelism on a ``(data, stage)`` mesh.

Placement: token/position embeddings and the final-norm/head run
replicated on every device (they are a sliver of the FLOPs; replicating
them avoids two extra pipeline hops), while the N transformer blocks are
stacked ``[n_stages, layers_per_stage, ...]`` and sharded one stage per
device along the ``stage`` axis.  Activations move stage-to-stage with
``lax.ppermute`` over ICI; the whole schedule — fill, steady state, drain
— is one ``lax.scan`` inside one jitted ``shard_map``, differentiable
end-to-end (the backward is the reverse-ring schedule XLA derives).

The reference's only model parallelism is the manual 2-stage split of
``demo_one_model_multi_gpu.py:17-42``; this is its scalable TPU-native
generalization, and it composes with DP the same way the reference's
DDP(model-split) composition does (``demo_one_model_multi_gpu.py:96-98``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.parallel.overlap import compat_shard_map
from tpudist.parallel.pipeline import pipeline_1f1b_shard, pipeline_shard
from tpudist.runtime.mesh import AXIS_DATA, AXIS_STAGE

# NOTE: tpudist.models.transformer is imported lazily inside the builders —
# it imports tpudist.parallel for the attention references, so a module-level
# import here would be circular.


class _LMEmbed(nn.Module):
    """Embedding head whose param names match TransformerLM's tree."""

    vocab: int
    d_model: int
    max_len: int
    rope: bool = False  # rope models carry no pos_embed table
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        x = nn.Embed(self.vocab, self.d_model, name="tok_embed",
                     dtype=self.dtype)(tokens)
        if not self.rope:
            pos = nn.Embed(self.max_len, self.d_model, name="pos_embed",
                           dtype=self.dtype)(
                jnp.arange(tokens.shape[1], dtype=jnp.int32)
            )
            x = x + pos[None]
        return x


class _LMHead(nn.Module):
    """Final norm + vocab projection, names matching TransformerLM."""

    vocab: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        # same precision split as TransformerLM: f32 norm, dtype projection
        x = nn.LayerNorm(use_bias=False, dtype=jnp.float32)(x)  # 'LayerNorm_0'
        return nn.Dense(self.vocab, use_bias=False, name="head",
                        dtype=self.dtype)(x)


_EMBED_KEYS = ("tok_embed", "pos_embed")
_HEAD_KEYS = ("LayerNorm_0", "head")


def stack_block_params(params, n_stages: int):
    """TransformerLM params → pipeline layout.

    Returns ``{"blocks": stacked, "rest": {...}}`` where ``stacked`` leaves
    have shape ``[n_stages, layers_per_stage, ...]`` (stage axis sharded,
    inner axis walked sequentially per stage) and ``rest`` holds the
    embeddings/norm/head unchanged.
    """
    p = dict(params["params"])
    block_keys = sorted(
        (k for k in p if k.startswith("block_")),
        key=lambda k: int(k.split("_")[1]),
    )
    n_layers = len(block_keys)
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} blocks do not split into {n_stages} stages")
    per_stage = n_layers // n_stages
    blocks = [p.pop(k) for k in block_keys]
    stacked = jax.tree.map(
        lambda *leaves: jnp.stack(leaves).reshape(
            (n_stages, per_stage) + leaves[0].shape
        ),
        *blocks,
    )
    return {"blocks": stacked, "rest": p}


def unstack_block_params(pp_params):
    """Inverse of :func:`stack_block_params` (checkpoint/parity interop)."""
    stacked = pp_params["blocks"]
    shape = jax.tree.leaves(stacked)[0].shape
    n_stages, per_stage = shape[0], shape[1]
    p = dict(pp_params["rest"])
    for s in range(n_stages):
        for j in range(per_stage):
            p[f"block_{s * per_stage + j}"] = jax.tree.map(
                lambda a, s=s, j=j: a[s, j], stacked
            )
    return {"params": p}


def stack_block_params_interleaved(params, n_dev: int, n_chunks: int):
    """TransformerLM params → the interleaved pipeline layout: blocks
    stacked to ``n_dev·n_chunks`` virtual stages, then depth-strided so
    device ``d`` holds global stages ``{c·n_dev + d}``
    (:func:`tpudist.parallel.pipeline_interleaved.interleave_block_params`).
    For checkpoint interop with the contiguous layout, apply
    ``deinterleave_block_params`` to ``blocks`` before
    :func:`unstack_block_params`."""
    from tpudist.parallel.pipeline_interleaved import interleave_block_params

    pp = stack_block_params(params, n_dev * n_chunks)
    return {"blocks": interleave_block_params(pp["blocks"], n_dev),
            "rest": pp["rest"]}


def pp_state_sharding(mesh: Mesh, tree, *, axis_name: str = AXIS_STAGE):
    """Shardings for a pipeline ``ModelState`` pytree: every leaf under a
    ``blocks`` key is stage-sharded on its leading axis, everything else
    (embeddings, head, Adam's scalar count) replicated."""
    staged = NamedSharding(mesh, P(axis_name))
    repl = NamedSharding(mesh, P())

    def shard_for(path, leaf):
        keys = [getattr(e, "key", getattr(e, "name", None)) for e in path]
        if "blocks" in keys and getattr(leaf, "ndim", 0) >= 1:
            return staged
        return repl

    return jax.tree_util.tree_map_with_path(shard_for, tree)


def _lm_pipeline_parts(module):
    """Shared sub-modules + stage fn for the pipelined TransformerLM:
    ``(embed_mod, head_mod, stage_fn)`` — one construction point so the
    GPipe apply and the 1F1B train step cannot drift."""
    from tpudist.models.transformer import (
        Block,
        _default_attention,
        make_length_aware_attention,
    )

    # Honor the model's sliding window: TransformerLM guarantees
    # attention_fn is None when sliding_window is set, so rebuild the
    # windowed default here exactly as the unpipelined model would.
    if module.sliding_window is not None:
        attn = make_length_aware_attention(module.sliding_window)
    else:
        attn = module.attention_fn or _default_attention
    block_mod = Block(
        module.d_model, module.n_heads, module.d_ff, attn,
        n_experts=module.n_experts, moe_fn=module.moe_fn,
        dtype=module.dtype, rope=module.rope,
        n_kv_heads=module.n_kv_heads,
        sliding_window=module.sliding_window,
    )
    embed_mod = _LMEmbed(module.vocab, module.d_model, module.max_len,
                         rope=module.rope, dtype=module.dtype)
    head_mod = _LMHead(module.vocab, dtype=module.dtype)

    def stage_fn(stage_params, x):
        # stage_params leaves: [layers_per_stage, ...]; apply sequentially.
        per_stage = jax.tree.leaves(stage_params)[0].shape[0]
        for j in range(per_stage):
            layer = jax.tree.map(lambda a, j=j: a[j], stage_params)
            x = block_mod.apply({"params": layer}, x)
        return x

    return embed_mod, head_mod, stage_fn


def make_pp_lm_apply(
    mesh: Mesh,
    module,  # a tpudist.models.transformer.TransformerLM
    *,
    n_stages: int,
    num_microbatches: int = 4,
    axis_name: str = AXIS_STAGE,
    data_axis: Optional[str] = AXIS_DATA,
    remat: bool = False,
):
    """Build ``apply(pp_params, tokens) -> logits`` with the block stack
    pipelined over ``axis_name`` and the batch sharded over ``data_axis``.

    ``pp_params`` comes from :func:`stack_block_params`.  Feed the result
    to :func:`tpudist.train.make_lm_train_step` together with
    :func:`pp_state_sharding` — the loss/grad/optimizer path needs no
    pipeline awareness.  (Training through this apply is the GPipe
    schedule: autodiff replays every microbatch's backward after all
    forwards.  For the memory-bounded 1F1B alternative, use
    :func:`make_pp_lm_train_step` with ``schedule='1f1b'``.)
    """
    embed_mod, head_mod, stage_fn = _lm_pipeline_parts(module)

    data_in_spec = P(None, data_axis) if data_axis else P()
    out_spec = (
        P(axis_name, None, data_axis) if data_axis else P(axis_name)
    )

    def apply(pp_params, tokens):
        rest = pp_params["rest"]
        x = embed_mod.apply(
            {"params": {k: rest[k] for k in _EMBED_KEYS if k in rest}},
            tokens
        )
        b, s, d = x.shape
        if b % num_microbatches:
            raise ValueError(
                f"batch {b} must divide into {num_microbatches} microbatches"
            )
        xm = x.reshape(num_microbatches, b // num_microbatches, s, d)

        def body(sp, xmb):
            return pipeline_shard(
                sp, xmb, stage_fn=stage_fn, axis_name=axis_name, remat=remat
            )[None]

        out = compat_shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis_name), data_in_spec),
            out_specs=out_spec,
        )(pp_params["blocks"], xm)
        # Last stage's block only — one stage's data moves, not a psum of
        # the whole [n_stages, ...] stack.
        x = out[-1].reshape(b, s, d)
        return head_mod.apply(
            {"params": {k: rest[k] for k in _HEAD_KEYS}}, x
        )

    return apply


def make_pp_lm_train_step(
    mesh: Mesh,
    module,  # a tpudist.models.transformer.TransformerLM
    tx,      # optax.GradientTransformation
    *,
    n_stages: int,
    num_microbatches: int = 4,
    schedule: str = "1f1b",
    n_chunks: int = 1,
    axis_name: str = AXIS_STAGE,
    data_axis: Optional[str] = AXIS_DATA,
    donate_state: bool = True,
    state_sharding=None,
):
    """Build the jitted pipeline-parallel LM train step
    ``step(state, tokens) -> (state, loss)`` with a selectable schedule.

    ``schedule='gpipe'``: training through :func:`make_pp_lm_apply` +
    ``make_lm_train_step`` — all microbatch forwards, then autodiff's
    backward replay; peak activation memory grows with ``num_microbatches``.

    ``schedule='1f1b'``: the hand-interleaved one-forward-one-backward
    schedule (:func:`tpudist.parallel.pipeline.pipeline_1f1b_shard`) —
    backward of each microbatch starts the tick its loss exists, so peak
    residual memory is O(n_stages), CONSTANT in ``num_microbatches``.
    Raise ``num_microbatches`` to amortize the pipeline bubble for free.
    Loss/grad numerics match GPipe up to summation order (tests assert
    parity).  MoE blocks are not supported under 1F1B (their expert
    all_to_all would nest inside this shard_map); use GPipe there.

    ``schedule='interleaved'``: virtual-stage 1F1B
    (:mod:`tpudist.parallel.pipeline_interleaved`) — each device holds
    ``n_chunks`` depth-strided model chunks, shrinking the fill/drain
    bubble ~÷``n_chunks`` at the cost of more (smaller) activation hops.
    Requires ``num_microbatches % n_stages == 0`` and a state over the
    :func:`stack_block_params_interleaved` layout.  MoE unsupported, as
    for 1f1b.

    ``state``: ``ModelState`` over the :func:`stack_block_params` layout
    (:func:`stack_block_params_interleaved` for ``schedule='interleaved'``),
    sharded per :func:`pp_state_sharding`.
    """
    import optax

    from tpudist.models.transformer import lm_loss
    from tpudist.train.step import ModelState

    if schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(
            f"schedule must be gpipe|1f1b|interleaved, got {schedule!r}")
    if n_chunks != 1 and schedule != "interleaved":
        raise ValueError(
            f"n_chunks={n_chunks} requires schedule='interleaved'")
    if schedule == "gpipe":
        from tpudist.train.lm import make_lm_train_step

        apply_fn = make_pp_lm_apply(
            mesh, module, n_stages=n_stages,
            num_microbatches=num_microbatches, axis_name=axis_name,
            data_axis=data_axis,
        )
        return make_lm_train_step(
            apply_fn, tx, mesh, donate_state=donate_state,
            state_sharding=state_sharding,
        )
    if module.n_experts > 0:
        raise ValueError(f"schedule={schedule!r} does not support MoE blocks")

    embed_mod, head_mod, stage_fn = _lm_pipeline_parts(module)
    data_in_spec = P(None, data_axis) if data_axis else P()

    def micro_loss(head_params, act, toks):
        logits = head_mod.apply({"params": head_params}, act)
        return lm_loss(logits, toks)

    if schedule == "interleaved":
        from tpudist.parallel.pipeline_interleaved import (
            interleaved_schedule, pipeline_interleaved_shard)

        sched = interleaved_schedule(n_stages, n_chunks, num_microbatches)

        def body(blocks, head_params, xm, tm):
            return pipeline_interleaved_shard(
                blocks, head_params, xm, tm, stage_fn=stage_fn,
                loss_fn=micro_loss, schedule=sched, axis_name=axis_name,
                data_axis=data_axis,
            )
    else:
        def body(blocks, head_params, xm, tm):
            return pipeline_1f1b_shard(
                blocks, head_params, xm, tm, stage_fn=stage_fn,
                loss_fn=micro_loss, axis_name=axis_name, data_axis=data_axis,
            )

    sharded_body = compat_shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis_name), P(), data_in_spec, data_in_spec),
        out_specs=(P(), P(axis_name), P(), data_in_spec),
    )

    def step(state: ModelState, tokens):
        pp_params = state.params
        rest = pp_params["rest"]
        embed_params = {k: rest[k] for k in _EMBED_KEYS if k in rest}
        head_params = {k: rest[k] for k in _HEAD_KEYS}
        b = tokens.shape[0]
        m = num_microbatches
        if b % m:
            raise ValueError(
                f"batch {b} must divide into {m} microbatches")

        x, embed_vjp = jax.vjp(
            lambda ep: embed_mod.apply({"params": ep}, tokens), embed_params)
        _, s, d = x.shape
        xm = x.reshape(m, b // m, s, d)
        tm = tokens.reshape(m, b // m, s)

        loss_sum, stage_g, head_g, dxm = sharded_body(
            pp_params["blocks"], head_params, xm, tm)

        # The shard body returns per-microbatch SUMS (data-axis already
        # mean-reduced inside); the step's loss is the mean over the m
        # equal microbatches, so every gradient scales by 1/m too.
        loss = loss_sum / m
        head_g = jax.tree.map(lambda g: g / m, head_g)
        stage_g = jax.tree.map(lambda g: g / m, stage_g)
        # dx was NOT data-mean-reduced inside (each shard's activations
        # are its own): the global cotangent is d(global mean)/dx =
        # local_sum / (m · data_axis_size); the embed vjp under jit's
        # global view then inserts the cross-shard embedding-grad psum.
        d_size = mesh.shape[data_axis] if data_axis else 1
        dx = dxm.reshape(b, s, d) / (m * d_size)
        (embed_g,) = embed_vjp(dx)

        grads = {"blocks": stage_g,
                 "rest": {**embed_g, **head_g}}
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = ModelState(params=new_params, opt_state=new_opt)
        return new_state, loss

    return jax.jit(
        step,
        in_shardings=(state_sharding, None) if state_sharding is not None
        else None,
        out_shardings=(state_sharding, None) if state_sharding is not None
        else None,
        donate_argnums=(0,) if donate_state else (),
    )
