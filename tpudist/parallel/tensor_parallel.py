"""Tensor parallelism: Megatron-style column/row-split linear layers.

Absent from the reference (SURVEY.md §2.4 marks TP "not required for
parity"); provided as the natural TPU extension on the mesh's ``model``
axis.  Two equivalent formulations are exposed:

1. **Sharding-spec formulation** (preferred): annotate the weight pytree
   with :func:`column_spec` / :func:`row_spec` partition specs and run the
   unmodified dense computation under ``jit`` — XLA inserts the all-reduce
   where the row-parallel contraction needs it.  This is the idiomatic
   pjit path: no manual collectives, compiler-scheduled comms.

2. **Explicit shard_map formulation** (:func:`tp_mlp_shard`,
   :func:`make_tp_mlp`): the textbook column→row pair with a single
   ``psum`` at the end, for when hand-placed collectives are wanted
   (e.g. fusing with other shard_map stages).

The pair composes as: ``y = (act(x @ W1) @ W2)`` with ``W1`` column-split
and ``W2`` row-split — one all-reduce per MLP block, activations stay
sharded on the feature axis in between.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.runtime.mesh import AXIS_MODEL


def column_spec(axis_name: str = AXIS_MODEL) -> P:
    """Weight ``[in, out]`` split on ``out`` — each device computes a slice
    of the activations; no communication in the forward."""
    return P(None, axis_name)


def row_spec(axis_name: str = AXIS_MODEL) -> P:
    """Weight ``[in, out]`` split on ``in`` — partial sums per device,
    all-reduced after the contraction."""
    return P(axis_name, None)


def mlp_param_sharding(mesh: Mesh, params: dict, *, axis_name: str = AXIS_MODEL):
    """Sharding pytree for a {'w1','b1','w2','b2'} MLP block: w1 column-split,
    w2 row-split, biases replicated/split to match."""
    specs = {
        "w1": column_spec(axis_name),
        "b1": P(axis_name),
        "w2": row_spec(axis_name),
        "b2": P(),
    }
    return {k: NamedSharding(mesh, specs[k]) for k in params}


def tp_mlp_shard(
    params: dict,
    x: jax.Array,
    *,
    axis_name: str = AXIS_MODEL,
    activation: Callable[[jax.Array], jax.Array] = jax.nn.gelu,
) -> jax.Array:
    """Shard-local column→row MLP body (call inside ``shard_map``).

    ``params['w1']: [d, f/n]`` (column shard), ``params['w2']: [f/n, d]``
    (row shard); ``x: [batch, d]`` replicated over the model axis.  One
    ``psum`` carries the row-parallel partial sums — the only collective.
    """
    h = activation(x @ params["w1"] + params["b1"])
    partial_out = h @ params["w2"]
    out = lax.psum(partial_out, axis_name)
    return out + params["b2"]


def tp_mlp_overlap_shard(
    params: dict,
    x: jax.Array,
    *,
    axis_name: str = AXIS_MODEL,
    activation: Callable[[jax.Array], jax.Array] = jax.nn.gelu,
    mode: str = "ring",
) -> jax.Array:
    """Shard-local collective-matmul MLP body (call inside ``shard_map``)
    — the overlapped twin of :func:`tp_mlp_shard`.

    Same weight shards (``w1`` column, ``w2`` row), but ``x: [batch/n, d]``
    arrives BATCH-SHARDED over the model axis and no monolithic
    collective ever runs: the input gather is pipelined into the first
    matmul (:func:`tpudist.parallel.overlap.ag_matmul`, chunk transfers
    overlapping chunk matmuls) and the row-parallel reduction is a
    pipelined reduce-scatter fused into the second matmul
    (:func:`tpudist.parallel.overlap.matmul_rs`) — so the output comes
    back batch-sharded too, and the big exposed ``psum`` of the default
    body becomes overlapped ppermute wire.  Global values match the
    default body within the reassociation bound documented in
    :mod:`tpudist.parallel.overlap` (the gather half is bit-exact; the
    reduce-scatter reassociates the n-way partial sum).
    """
    from tpudist.parallel.overlap import ag_matmul, matmul_rs

    h = ag_matmul(x, params["w1"], axis_name=axis_name, mode=mode,
                  gather="lhs")
    h = activation(h + params["b1"])
    out = matmul_rs(h, params["w2"], axis_name=axis_name, mode=mode)
    return out + params["b2"]


def make_tp_mlp(
    mesh: Mesh,
    *,
    axis_name: str = AXIS_MODEL,
    batch_axis: str | None = None,
    activation: Callable[[jax.Array], jax.Array] = jax.nn.gelu,
    overlap: str | None = None,
):
    """Jitted global-view TP MLP: weights arrive globally shaped, sharded per
    :func:`mlp_param_sharding`; ``x`` is replicated over the model axis.

    ``overlap`` selects the collective-matmul pipeline
    (``tpudist.parallel.overlap``): ``None`` defers to the
    ``TPUDIST_OVERLAP`` env knob (default off), ``"off"`` forces the
    psum body, ``"ring"``/``"bidir"`` run :func:`tp_mlp_overlap_shard` —
    batch sharded over the model axis internally, all wire traffic in
    ppermute chunks pipelined against the matmuls, no monolithic
    collective.  Global output VALUES match the default body (gather
    half bit-exact, reduce half within the documented reassociation
    bound); the output lands batch-sharded over ``axis_name`` instead of
    replicated.  The overlapped body needs ``batch_axis=None`` (the
    model axis carries the batch pipeline) and a batch divisible by the
    axis size.
    """
    from tpudist.parallel.overlap import compat_shard_map, overlap_mode

    mode = overlap_mode(overlap)
    param_specs = {
        "w1": column_spec(axis_name),
        "b1": P(axis_name),
        "w2": row_spec(axis_name),
        "b2": P(),
    }
    if mode != "off":
        if batch_axis is not None:
            raise ValueError(
                "overlapped TP MLP pipelines the batch over the model "
                "axis; batch_axis must be None")
        body = functools.partial(tp_mlp_overlap_shard, axis_name=axis_name,
                                 activation=activation, mode=mode)
        sharded = compat_shard_map(
            body,
            mesh=mesh,
            in_specs=(param_specs, P(axis_name, None)),
            out_specs=P(axis_name, None),
        )
        return jax.jit(sharded)
    body = functools.partial(tp_mlp_shard, axis_name=axis_name,
                             activation=activation)
    sharded = compat_shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, P(batch_axis, None)),
        out_specs=P(batch_axis, None),
    )
    return jax.jit(sharded)


def init_mlp_params(rng: jax.Array, d_model: int, d_hidden: int) -> dict:
    """Dense (unsharded) init for the TP MLP block — shard with
    ``jax.device_put(params, mlp_param_sharding(mesh, params))``."""
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (d_model, d_hidden)) / jnp.sqrt(d_model),
        "b1": jnp.zeros((d_hidden,)),
        "w2": jax.random.normal(k2, (d_hidden, d_model)) / jnp.sqrt(d_hidden),
        "b2": jnp.zeros((d_model,)),
    }
