"""Fully-sharded data parallelism (ZeRO-3-style) as a sharding layout.

The reference's DDP keeps a full replica of parameters, gradients, and
optimizer state on every rank (torch DDP, ``demo.py:70-72``); at scale the
optimizer state dominates memory.  The TPU-native formulation needs no
wrapper class and no hand-written gather/scatter: FSDP is *just a layout*
— every large parameter (and its Adam moments, which mirror the param
tree) is sharded over the ``data`` mesh axis, and the XLA SPMD partitioner
inserts the all-gather before each use and the reduce-scatter after each
backward that ZeRO implements by hand.  Per-chip state memory drops by the
data-axis size; step math is bit-identical to replicated DP (tests assert
it).

Usage::

    sharding = fsdp_sharding(mesh, state)         # state: ModelState pytree
    state = jax.device_put(state, sharding)
    step = make_lm_train_step(apply, tx, mesh, state_sharding=sharding)

Composes with tensor parallelism by passing ``skip`` specs for leaves that
:func:`tpudist.models.transformer.transformer_tp_sharding` already shards
— see :func:`merge_shardings`.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.runtime.mesh import AXIS_DATA


def _leaf_spec(leaf, n: int, axis_name: str, min_size: int) -> P:
    """Shard the largest dimension divisible by ``n``; replicate leaves that
    are small (gather overhead beats the memory win) or indivisible."""
    shape = getattr(leaf, "shape", ())
    if getattr(leaf, "ndim", 0) == 0 or np.prod(shape) < min_size:
        return P()
    candidates = [d for d in range(len(shape)) if shape[d] % n == 0]
    if not candidates:
        return P()
    dim = max(candidates, key=lambda d: shape[d])
    spec = [None] * len(shape)
    spec[dim] = axis_name
    return P(*spec)


def fsdp_sharding(
    mesh: Mesh,
    tree,
    *,
    axis_name: str = AXIS_DATA,
    min_size: int = 1024,
):
    """ZeRO-3-style layout for a state pytree (params or a whole
    ``ModelState`` — Adam moments mirror the param structure, so mapping
    leaves covers them identically).

    Every float leaf with ≥ ``min_size`` elements is sharded along its
    largest ``axis_name``-divisible dimension; the rest replicate.  Returns
    a pytree of ``NamedSharding`` matching ``tree``.
    """
    n = mesh.shape[axis_name]

    def shard_for(leaf):
        return NamedSharding(mesh, _leaf_spec(leaf, n, axis_name, min_size))

    return jax.tree.map(shard_for, tree)


def zero1_sharding(
    mesh: Mesh,
    state,
    *,
    axis_name: str = AXIS_DATA,
    min_size: int = 1024,
):
    """ZeRO-1-style weight-update sharding: parameters stay REPLICATED
    (forward/backward identical to plain DP — no per-layer all-gathers),
    only the optimizer state shards over the data axis.

    The XLA-native form of "Automatic Cross-Replica Sharding of Weight
    Update in Data-Parallel Training" (arXiv:2004.13336, the technique
    ZeRO-1 popularized): with Adam moments laid out sharded and gradients
    replicated after the all-reduce, the SPMD partitioner computes each
    moment/update on its owning shard only and all-gathers the updated
    parameters once per step — optimizer memory drops by the data-axis
    size (Adam: 2/3 of a replicated f32 state) for one extra
    param-sized all-gather, with zero change to the step function.

    Middle rung of the DP memory ladder: plain DP (everything
    replicated) → ``zero1_sharding`` (opt sharded) → :func:`fsdp_sharding`
    (params + moments sharded, ZeRO-3).  Not composable with
    ``grad_reduce_dtype`` (that path requires a pure-DP replicated
    state, and validates so).

    ``state``: a ``ModelState``; returns a matching sharding pytree.
    """
    from tpudist.train.step import ModelState

    repl = jax.tree.map(
        lambda _: NamedSharding(mesh, P()), state.params)
    opt = fsdp_sharding(mesh, state.opt_state, axis_name=axis_name,
                        min_size=min_size)
    return ModelState(params=repl, opt_state=opt)


def overlap_fsdp_mlp(
    mesh: Mesh,
    *,
    axis_name: str = AXIS_DATA,
    overlap: str | None = None,
    activation=None,
):
    """Overlapped FSDP layer compute for the transformer MLP — the
    explicit twin of the layout-only path.

    Under :func:`fsdp_sharding` the FFN kernels land ``wi: [d, ff/n]``
    (column shard — ``ff`` is the largest dim) and ``wo: [ff/n, d]``
    (row shard), and the XLA partitioner inserts a monolithic all-gather
    of each before the matmul that consumes it — exposed wire time.
    This builder returns an ``mlp_fn(params, x) -> y`` for
    :class:`tpudist.models.transformer.Block`'s injection seam (the
    ``attention_fn`` pattern: the closure carries its own ``shard_map``)
    that consumes the SHARDED kernels directly and pipelines the gather
    into the matmuls chunk-by-chunk over ``lax.ppermute``
    (:mod:`tpudist.parallel.overlap`): the ``wi`` column gather
    assembles output columns (bit-exact), the ``wo`` contraction gather
    accumulates partial products (documented reassociation bound).  No
    all-gather of either kernel appears in the lowered HLO — the audit
    (``benchmarks/comm_audit.py`` ``fsdp_overlap_*`` regimes) asserts
    it structurally.

    ``params``: ``{"wi": [d, ff], "wo": [ff, d]}`` global kernels;
    ``x: [batch, seq, d]`` with batch sharded over ``axis_name``.
    Returns ``None`` when the resolved mode is off, so call sites can
    pass the result straight to ``create_transformer(mlp_fn=...)`` and
    keep the byte-identical dense path by default.

    ``activation`` defaults to the Block's ``gelu``.
    """
    from tpudist.parallel.overlap import (ag_matmul, compat_shard_map,
                                          overlap_mode)

    mode = overlap_mode(overlap)
    if mode == "off":
        return None
    from jax.sharding import PartitionSpec as P

    act = activation if activation is not None else jax.nn.gelu

    def body(params, x):
        b_loc, s, d = x.shape
        t = x.reshape(b_loc * s, d)
        h = ag_matmul(t, params["wi"], axis_name=axis_name, mode=mode,
                      gather="rhs")
        h = act(h)
        y = ag_matmul(h, params["wo"], axis_name=axis_name, mode=mode,
                      gather="contract")
        return y.reshape(b_loc, s, d).astype(x.dtype)

    param_specs = {"wi": P(None, axis_name), "wo": P(axis_name, None)}
    sharded = compat_shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, P(axis_name, None, None)),
        out_specs=P(axis_name, None, None),
    )

    def mlp_fn(params, x):
        return sharded(params, x)

    # Introspection tags (mirrors attention_fn's .window/.supports_gqa
    # convention): which pipeline this closure runs, for guards/tests.
    mlp_fn.overlap = mode
    mlp_fn.axis_name = axis_name
    return mlp_fn


def merge_shardings(primary, fallback):
    """Leaf-wise composition: use ``primary``'s spec unless it is fully
    replicated, else ``fallback``'s — e.g. TP specs where they exist, FSDP
    for everything TP leaves replicated."""

    def pick(p, f):
        # "replicated" includes rank-explicit spellings: P(None, None) etc.
        replicated = all(axis is None for axis in tuple(p.spec))
        return f if replicated else p

    return jax.tree.map(pick, primary, fallback)


def state_bytes_per_device(tree, sharding) -> int:
    """Analytic per-device bytes of ``tree`` under ``sharding`` — the
    memory-accounting companion (replicated leaves count full size, sharded
    leaves their shard)."""
    total = 0
    for leaf, sh in zip(jax.tree.leaves(tree), jax.tree.leaves(
            sharding, is_leaf=lambda x: isinstance(x, NamedSharding))):
        size = int(np.prod(getattr(leaf, "shape", ()) or (1,)))
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 4)
        div = 1
        for axis in jax.tree.leaves(tuple(sh.spec)):
            if axis is not None:
                div *= sh.mesh.shape[axis]
        total += size * itemsize // div
    return total
