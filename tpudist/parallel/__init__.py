"""Parallelism building blocks: DP (shard_map formulation), tensor parallel,
pipeline, ring-attention sequence parallel, MoE expert parallel.

Populated incrementally; the pjit DP formulation lives in
``tpudist.train.step`` (parameters replicated, batch sharded — XLA inserts
the gradient all-reduce).
"""
