"""Parallelism building blocks beyond plain data parallelism.

The pjit DP formulation (parameters replicated, batch sharded, XLA inserts
the gradient all-reduce) lives in ``tpudist.train.step``; the 2-stage
model-split parity shape in ``tpudist.models.split_mlp``.  This package
holds the scalable strategies on the 4-axis mesh
(``tpudist.runtime.mesh``):

- :mod:`ring_attention` — sequence/context parallelism (``seq`` axis,
  incl. the zigzag causal-balanced layout — every (device, hop) costs the
  same two half-chunk blocks):
  blockwise attention with K/V rotating over ICI via ``ppermute``.
- :mod:`tensor_parallel` — Megatron-style column/row linear pairs
  (``model`` axis), both pjit-spec and explicit-``psum`` forms.
- :mod:`pipeline` — microbatched GPipe schedule (``stage`` axis) with
  activations hopping the ring inside one jitted ``lax.scan``.
- :mod:`moe` — capacity-based top-1 expert parallelism with a single
  fused ``all_to_all`` each way (``model`` axis as the expert group).
- :mod:`fsdp` — ZeRO-3-style fully-sharded state layout over the ``data``
  axis (XLA inserts the all-gather/reduce-scatter pair).
"""

from tpudist.parallel.ring_attention import (  # noqa: F401
    make_zigzag_lm_loss,
    make_zigzag_ring_attention,
    ring_attention_shard_zigzag,
    zigzag_indices,
    attention_reference,
    make_ring_attention,
    ring_attention_shard,
)
from tpudist.parallel.tensor_parallel import (  # noqa: F401
    column_spec,
    init_mlp_params,
    make_tp_mlp,
    mlp_param_sharding,
    row_spec,
    tp_mlp_overlap_shard,
    tp_mlp_shard,
)
from tpudist.parallel.overlap import (  # noqa: F401
    OVERLAP_MODES,
    OVERLAP_SCOPE,
    ag_matmul,
    compat_shard_map,
    matmul_rs,
    overlap_mode,
)
from tpudist.parallel.pipeline import (  # noqa: F401
    make_pipeline,
    pipeline_1f1b_shard,
    pipeline_shard,
)
from tpudist.parallel.pipeline_interleaved import (  # noqa: F401
    deinterleave_block_params,
    interleave_block_params,
    interleaved_schedule,
    pipeline_interleaved_shard,
)
from tpudist.parallel.pipeline_lm import (  # noqa: F401
    make_pp_lm_apply,
    make_pp_lm_train_step,
    pp_state_sharding,
    stack_block_params,
    stack_block_params_interleaved,
    unstack_block_params,
)
from tpudist.parallel.moe import MoEStats, make_moe, moe_shard  # noqa: F401
from tpudist.parallel.fsdp import (  # noqa: F401
    fsdp_sharding,
    merge_shardings,
    overlap_fsdp_mlp,
    state_bytes_per_device,
    zero1_sharding,
)
