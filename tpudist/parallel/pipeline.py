"""Pipeline parallelism: microbatched GPipe-style schedule over the
``stage`` mesh axis.

The reference's only model parallelism is a manual 2-stage vertical split
with the activation hand-carried between two GPUs inside ``forward``
(``demo_one_model_multi_gpu.py:36-42``) — no microbatching, no schedule.
The TPU-native generalization here runs N stages on N devices with
``lax.ppermute`` moving activations stage-to-stage over ICI and a rotating
microbatch schedule, all inside one jitted ``shard_map``:

- each device holds ONE stage's params (sharded on the ``stage`` axis);
- the loop runs ``num_microbatches + num_stages - 1`` ticks (pipeline
  fill + drain); at every tick each device applies its stage to the
  activation it holds, then the activations rotate one hop;
- compiler-friendly: the tick loop is a ``lax.scan`` over stacked
  microbatches, static shapes throughout, no data-dependent control flow;
- differentiable end-to-end (ppermute transposes to the reverse ring), so
  the same code trains — unlike hand-written send/recv schedules.

For the reference's exact 2-stage shape (parity), see
``tpudist.models.split_mlp`` which expresses it as layer sharding instead;
this module is the scalable schedule.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.runtime.mesh import AXIS_STAGE

# StageFn: (stage_params, activation [micro_batch, d]) -> activation
StageFn = Callable[[dict, jax.Array], jax.Array]


def pipeline_shard(
    stage_params,
    x_microbatches: jax.Array,
    *,
    stage_fn: StageFn,
    axis_name: str = AXIS_STAGE,
    remat: bool = False,
) -> jax.Array:
    """Shard-local GPipe body (call inside ``shard_map``).

    ``stage_params``: this device's stage weights, arriving as a
    size-1-leading-axis block of the ``[n_stages, ...]`` stack (shard_map
    keeps the sharded dim).  ``x_microbatches``:
    ``[num_micro, micro_size, d]`` — the full input lives on stage 0; other
    stages ignore their copy (shard_map replicates it when the caller
    passes ``P(None, ...)``; pass it sharded over stages to save memory and
    only stage 0's block is read).

    Returns ``[num_micro, micro_size, d]`` — valid on the LAST stage,
    zeros elsewhere.  Callers gather with a stage-axis out_spec and slice
    the last stage's block (see :func:`make_pipeline`): XLA then moves one
    stage's data instead of all-reducing the whole ``n_stages`` stack,
    which is what a ``psum`` broadcast would do.
    """
    stage_params = jax.tree.map(lambda a: a[0], stage_params)
    if remat:
        # Recompute each tick's stage forward during the backward instead
        # of stashing its internals: per-device activation memory drops to
        # the tick *boundaries* the scan already carries — the memory
        # property a hand-scheduled 1F1B buys, obtained compiler-side.
        stage_fn = jax.checkpoint(stage_fn)
    n_stages = lax.axis_size(axis_name)
    my_stage = lax.axis_index(axis_name)
    num_micro = x_microbatches.shape[0]
    micro_shape = x_microbatches.shape[1:]
    total_ticks = num_micro + n_stages - 1

    # Shift perm: stage i -> i+1 (last stage's output falls off the end).
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        state, outputs = carry  # state: activation this device holds
        # Stage 0 feeds a fresh microbatch while any remain; other stages
        # use what arrived from the left neighbor.
        feed_idx = jnp.minimum(t, num_micro - 1)
        fresh = lax.dynamic_index_in_dim(
            x_microbatches, feed_idx, axis=0, keepdims=False
        )
        inp = jnp.where(my_stage == 0, fresh, state)
        out = stage_fn(stage_params, inp)

        # Last stage banks its result for microbatch (t - n_stages + 1).
        bank_idx = t - (n_stages - 1)
        is_valid = jnp.logical_and(my_stage == n_stages - 1, bank_idx >= 0)
        outputs = lax.cond(
            is_valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out, jnp.maximum(bank_idx, 0), axis=0
            ),
            lambda o: o,
            outputs,
        )
        state = lax.ppermute(out, axis_name, perm)
        return (state, outputs), None

    init_state = jnp.zeros(micro_shape, x_microbatches.dtype)
    init_out = jnp.zeros((num_micro,) + micro_shape, x_microbatches.dtype)
    (_, outputs), _ = lax.scan(
        tick, (init_state, init_out), jnp.arange(total_ticks)
    )
    return outputs


def make_pipeline(
    mesh: Mesh,
    stage_fn: StageFn,
    *,
    axis_name: str = AXIS_STAGE,
    num_microbatches: int = 4,
    remat: bool = False,
):
    """Jitted global-view pipeline.

    ``stage_params`` arrive with a leading stage axis (``[n_stages, ...]``,
    sharded over ``axis_name``); input ``x: [batch, d]`` is split into
    ``num_microbatches`` equal microbatches (batch must divide evenly —
    the reference's equal-batch contract, ``demo.py:113``).
    """

    def global_fn(stage_params, x):
        num_micro = num_microbatches
        micro = x.shape[0] // num_micro
        xm = x.reshape((num_micro, micro) + x.shape[1:])

        def body(sp, xmb):
            return pipeline_shard(
                sp, xmb, stage_fn=stage_fn, axis_name=axis_name, remat=remat
            )[None]

        # Leading stage axis on the output; slicing the last block makes
        # XLA move one stage's data (a broadcast from the final stage)
        # instead of all-reducing zeros from every other stage.
        out = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis_name), P()),
            out_specs=P(axis_name),
            check_vma=False,  # inputs arrive replicated; ppermute varies them
        )(stage_params, xm)
        out = out[-1]
        return out.reshape((num_micro * micro,) + out.shape[2:])

    return jax.jit(global_fn)
