"""Pipeline parallelism: microbatched GPipe-style schedule over the
``stage`` mesh axis.

The reference's only model parallelism is a manual 2-stage vertical split
with the activation hand-carried between two GPUs inside ``forward``
(``demo_one_model_multi_gpu.py:36-42``) — no microbatching, no schedule.
The TPU-native generalization here runs N stages on N devices with
``lax.ppermute`` moving activations stage-to-stage over ICI and a rotating
microbatch schedule, all inside one jitted ``shard_map``:

- each device holds ONE stage's params (sharded on the ``stage`` axis);
- the loop runs ``num_microbatches + num_stages - 1`` ticks (pipeline
  fill + drain); at every tick each device applies its stage to the
  activation it holds, then the activations rotate one hop;
- compiler-friendly: the tick loop is a ``lax.scan`` over stacked
  microbatches, static shapes throughout, no data-dependent control flow;
- differentiable end-to-end (ppermute transposes to the reverse ring), so
  the same code trains — unlike hand-written send/recv schedules.

For the reference's exact 2-stage shape (parity), see
``tpudist.models.split_mlp`` which expresses it as layer sharding instead;
this module is the scalable schedule.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.parallel.overlap import (compat_axis_size,
                                     compat_shard_map)
from tpudist.runtime.mesh import AXIS_STAGE

# StageFn: (stage_params, activation [micro_batch, d]) -> activation
StageFn = Callable[[dict, jax.Array], jax.Array]


# Substring match: this JAX lowers pmean/psum to `psum_invariant`, and
# names have shifted across versions (psum/psum2/psum_invariant), so
# matching exact names would silently stop detecting anything on upgrade.
_COLLECTIVE_PRIM_SUBSTRINGS = (
    "psum", "pmean", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "pgather",
)


def _collectives_in_jaxpr(jaxpr, found: set) -> None:
    """Recursively collect collective primitive names in ``jaxpr``
    (descending into call/scan/cond sub-jaxprs via eqn params)."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if any(s in name for s in _COLLECTIVE_PRIM_SUBSTRINGS):
            found.add(name)
        for v in eqn.params.values():
            for cand in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(cand, "jaxpr", None)
                if inner is not None:
                    _collectives_in_jaxpr(inner, found)
                elif hasattr(cand, "eqns"):
                    _collectives_in_jaxpr(cand, found)


def head_grad_branches(loss_fn):
    """``(head, head_zeros)`` cond branches for the vocab head: value and
    grad of ``loss_fn(out_params, activation, aux)`` vs shape-matched
    zeros.  Shared by both hand-scheduled pipelines so only the device
    holding the last global stage's fresh activation pays head FLOPs.

    HARD REQUIREMENT on ``loss_fn``: it must be collective-free (no
    psum/pmean/ppermute).  It runs inside a ``lax.cond`` whose predicate
    VARIES per device — a collective in the true branch would be executed
    by a subset of the mesh and deadlock at runtime (``check_vma=False``
    on the wrapping shard_maps means nothing catches it at trace time).
    Reduce over the data axis AFTER the pipeline call, as
    ``pipeline_1f1b_shard``'s ``data_axis`` handling does.

    The contract is ENFORCED at trace time: the first trace of ``head``
    scans ``loss_fn``'s jaxpr for collective primitives and raises
    ``ValueError`` naming them — without this, a user loss containing a
    ``pmean`` would hang the whole mesh at runtime with no diagnostic."""
    _checked = []  # once per head_grad_branches() instance

    def _assert_collective_free(args):
        def vg(a):
            return jax.value_and_grad(loss_fn, argnums=(0, 1))(*a)

        try:
            jaxpr = jax.make_jaxpr(vg)(args).jaxpr
        except Exception:
            return  # never let the guard break a traceable loss_fn
        found: set = set()
        _collectives_in_jaxpr(jaxpr, found)
        if found:
            raise ValueError(
                "head_grad_branches: loss_fn contains collective "
                f"primitive(s) {sorted(found)}. The vocab head runs inside "
                "a lax.cond whose predicate varies per device, so a "
                "collective here is executed by only a subset of the mesh "
                "and deadlocks at runtime. Make loss_fn collective-free "
                "and reduce over the data axis AFTER the pipeline call "
                "(see pipeline_1f1b_shard's data_axis handling)."
            )

    def head(args):
        if not _checked:
            _assert_collective_free(args)
            _checked.append(True)
        out_p, a_out, aux_m = args
        return jax.value_and_grad(loss_fn, argnums=(0, 1))(
            out_p, a_out, aux_m)

    def head_zeros(args):
        # trace-time only — eval_shape does no FLOPs
        shapes = jax.eval_shape(head, args)
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    return head, head_zeros


def pipeline_shard(
    stage_params,
    x_microbatches: jax.Array,
    *,
    stage_fn: StageFn,
    axis_name: str = AXIS_STAGE,
    remat: bool = False,
) -> jax.Array:
    """Shard-local GPipe body (call inside ``shard_map``).

    ``stage_params``: this device's stage weights, arriving as a
    size-1-leading-axis block of the ``[n_stages, ...]`` stack (shard_map
    keeps the sharded dim).  ``x_microbatches``:
    ``[num_micro, micro_size, d]`` — the full input lives on stage 0; other
    stages ignore their copy (shard_map replicates it when the caller
    passes ``P(None, ...)``; pass it sharded over stages to save memory and
    only stage 0's block is read).

    Returns ``[num_micro, micro_size, d]`` — valid on the LAST stage,
    zeros elsewhere.  Callers gather with a stage-axis out_spec and slice
    the last stage's block (see :func:`make_pipeline`): XLA then moves one
    stage's data instead of all-reducing the whole ``n_stages`` stack,
    which is what a ``psum`` broadcast would do.
    """
    stage_params = jax.tree.map(lambda a: a[0], stage_params)
    if remat:
        # Recompute each tick's stage forward during the backward instead
        # of stashing its internals: per-device activation memory drops to
        # the tick *boundaries* the scan already carries — the memory
        # property a hand-scheduled 1F1B buys, obtained compiler-side.
        stage_fn = jax.checkpoint(stage_fn)
    n_stages = compat_axis_size(axis_name)
    my_stage = lax.axis_index(axis_name)
    num_micro = x_microbatches.shape[0]
    micro_shape = x_microbatches.shape[1:]
    total_ticks = num_micro + n_stages - 1

    # Shift perm: stage i -> i+1 (last stage's output falls off the end).
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        state, outputs = carry  # state: activation this device holds
        # Stage 0 feeds a fresh microbatch while any remain; other stages
        # use what arrived from the left neighbor.
        feed_idx = jnp.minimum(t, num_micro - 1)
        fresh = lax.dynamic_index_in_dim(
            x_microbatches, feed_idx, axis=0, keepdims=False
        )
        inp = jnp.where(my_stage == 0, fresh, state)
        out = stage_fn(stage_params, inp)

        # Last stage banks its result for microbatch (t - n_stages + 1).
        bank_idx = t - (n_stages - 1)
        is_valid = jnp.logical_and(my_stage == n_stages - 1, bank_idx >= 0)
        outputs = lax.cond(
            is_valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out, jnp.maximum(bank_idx, 0), axis=0
            ),
            lambda o: o,
            outputs,
        )
        state = lax.ppermute(out, axis_name, perm)
        return (state, outputs), None

    init_state = jnp.zeros(micro_shape, x_microbatches.dtype)
    init_out = jnp.zeros((num_micro,) + micro_shape, x_microbatches.dtype)
    (_, outputs), _ = lax.scan(
        tick, (init_state, init_out), jnp.arange(total_ticks)
    )
    return outputs


def pipeline_1f1b_shard(
    stage_params,
    out_params,
    x_microbatches: jax.Array,
    aux_microbatches: jax.Array,
    *,
    stage_fn: StageFn,
    loss_fn,
    axis_name: str = AXIS_STAGE,
    data_axis=None,
):
    """Shard-local 1F1B schedule: forward AND backward in ONE scan, with
    per-stage activation recompute and an O(num_stages) residual buffer.

    GPipe (:func:`pipeline_shard` + autodiff) runs all ``M`` forwards, then
    lets autodiff replay all ``M`` backwards — every microbatch's residuals
    are live at the phase boundary, so peak memory grows with ``M``.  This
    schedule hand-interleaves them instead, which autodiff cannot be asked
    to do: backward of microbatch ``m`` starts as soon as the loss for
    ``m`` exists, so at most ``2·(S−1)+1`` stage-input activations are ever
    held per device — **constant in M**.  That unlocks the 1F1B trade:
    raise ``M`` to amortize the pipeline bubble without activation memory
    growing with it (the schedule of Narayanan et al.'s PipeDream-Flush /
    Megatron's non-interleaved 1F1B, formulated SPMD-uniformly).

    Timeline (0-indexed tick ``t``, stage ``s``, ``S`` stages, ``M``
    microbatches; each tick every device runs one fwd unit and one
    recompute+bwd unit, ``jnp.where``-gated like the GPipe loop):

    - forward of micro ``m`` on stage ``s`` at tick ``t = s + m``;
    - the LAST stage computes the microbatch loss and its cotangent the
      same tick its forward lands (``loss_fn`` grad) and immediately
      backwards it — 1F1B's defining move;
    - backward of micro ``m`` on stage ``s`` at tick ``2(S−1) − s + m``
      (cotangents hop right→left on the reverse ring each tick);
    - total ticks ``M + 2(S−1)``; stage-input residuals live in a ring
      buffer of depth ``2S − 1``, indexed ``m mod (2S−1)`` (lifetime of a
      residual is ``2(S−1−s)`` ticks < depth, so live slots never collide).

    ``stage_params``: this device's ``[1, ...]`` block of the stage stack.
    ``out_params``: replicated params consumed by ``loss_fn`` (e.g. the LM
    final-norm + head); their gradient is accumulated on the last stage
    and ``psum``-replicated.  ``loss_fn(out_params, act, aux) -> scalar``
    maps the last stage's activation + per-micro aux (e.g. target tokens)
    to the microbatch loss.  Backward recomputes each stage forward from
    its saved INPUT (stage-granular rematerialization), so no
    ``jax.checkpoint`` is needed — 1F1B implies it.

    Head cost (r3 advisor finding, resolved): the head — the full
    vocab-projection loss, forward and backward via ``value_and_grad`` —
    runs under ``lax.cond`` on ``my_stage == last AND fwd_valid``.  Under
    ``shard_map`` each device evaluates the predicate with its OWN axis
    index at runtime, so this is a true per-device branch (NOT the
    both-branches-execute degeneration ``cond`` suffers under ``vmap``):
    non-last stages — and the last stage's warmup/drain ticks — run the
    zero-cost false branch, so the step pays exactly ``M`` head
    evaluations total.  Divergent control flow is safe only because
    ``loss_fn`` MUST be collective-free — see
    :func:`head_grad_branches` for the contract.

    Returns ``(loss_sum, stage_grads, out_grads, dx_microbatches)`` —
    all UNNORMALIZED sums over this shard's microbatches (caller divides
    by ``M`` and mean-reduces over ``data_axis``): ``loss_sum`` and
    ``out_grads`` psum-replicated over the stage axis, ``stage_grads``
    carrying the ``[1, ...]`` leading axis for a ``P(stage)`` out_spec,
    ``dx_microbatches`` the cotangent w.r.t. ``x_microbatches`` (stage 0's
    contribution, psum-replicated).
    """
    p = jax.tree.map(lambda a: a[0], stage_params)
    n_stages = compat_axis_size(axis_name)
    my_stage = lax.axis_index(axis_name)
    last = n_stages - 1
    num_micro = x_microbatches.shape[0]
    micro_shape = x_microbatches.shape[1:]
    depth = 2 * n_stages - 1
    total_ticks = num_micro + 2 * (n_stages - 1)

    perm_fwd = [(i, i + 1) for i in range(n_stages - 1)]
    perm_bwd = [(i + 1, i) for i in range(n_stages - 1)]

    head, head_zeros = head_grad_branches(loss_fn)

    def fwd_bwd(carry, t):
        (act_state, cot_state, ring, dx_bank,
         loss_acc, sg_acc, og_acc) = carry

        # ---- forward unit: micro m_f = t - s ----
        m_f = t - my_stage
        fwd_valid = jnp.logical_and(m_f >= 0, m_f < num_micro)
        m_f_c = jnp.clip(m_f, 0, num_micro - 1)
        fresh = lax.dynamic_index_in_dim(x_microbatches, m_f_c, 0,
                                         keepdims=False)
        a_in = jnp.where(my_stage == 0, fresh, act_state)
        a_out = stage_fn(p, a_in)

        # save the stage INPUT (backward recomputes from it); a dead slot
        # keeps its old value so live residuals are never clobbered
        slot = jnp.mod(m_f_c, depth)
        old = lax.dynamic_index_in_dim(ring, slot, 0, keepdims=False)
        ring = lax.dynamic_update_index_in_dim(
            ring, jnp.where(fwd_valid, a_in, old), slot, 0)

        # last stage: loss + its cotangent for THIS micro, this tick —
        # a true runtime branch; non-last stages skip the head entirely
        # (see the docstring's head-cost note).  Predicate includes
        # fwd_valid: the last stage's warmup/drain ticks carry garbage
        # activations whose head results are fully masked anyway — safe
        # to skip because on the last stage the backward of micro m runs
        # the SAME tick as its forward (2(S-1)-(S-1)+m = (S-1)+m), so
        # d_act is never consumed on a tick the head skipped.
        aux_m = lax.dynamic_index_in_dim(aux_microbatches, m_f_c, 0,
                                         keepdims=False)
        on_last = my_stage == last
        take_loss = jnp.logical_and(on_last, fwd_valid)
        (l_m, lgrads) = lax.cond(
            take_loss, head, head_zeros, (out_params, a_out, aux_m))
        d_og, d_act = lgrads
        loss_acc = loss_acc + jnp.where(take_loss, l_m, 0.0)
        og_acc = jax.tree.map(
            lambda acc, g: acc + jnp.where(take_loss, g, 0.0), og_acc, d_og)

        # ---- backward unit: micro m_b = t - 2(S-1) + s ----
        m_b = t - 2 * (n_stages - 1) + my_stage
        bwd_valid = jnp.logical_and(m_b >= 0, m_b < num_micro)
        m_b_c = jnp.clip(m_b, 0, num_micro - 1)
        a_saved = lax.dynamic_index_in_dim(ring, jnp.mod(m_b_c, depth), 0,
                                           keepdims=False)
        cot_in = jnp.where(on_last, d_act, cot_state)
        _, stage_vjp = jax.vjp(stage_fn, p, a_saved)
        dp, da = stage_vjp(cot_in)
        sg_acc = jax.tree.map(
            lambda acc, g: acc + jnp.where(bwd_valid, g, 0.0), sg_acc, dp)
        take_dx = jnp.logical_and(my_stage == 0, bwd_valid)
        old_dx = lax.dynamic_index_in_dim(dx_bank, m_b_c, 0, keepdims=False)
        dx_bank = lax.dynamic_update_index_in_dim(
            dx_bank, jnp.where(take_dx, da, old_dx), m_b_c, 0)

        act_state = lax.ppermute(a_out, axis_name, perm_fwd)
        cot_state = lax.ppermute(da, axis_name, perm_bwd)
        return (act_state, cot_state, ring, dx_bank,
                loss_acc, sg_acc, og_acc), None

    dtype = x_microbatches.dtype
    zeros_g = functools.partial(jax.tree.map, jnp.zeros_like)
    init = (
        jnp.zeros(micro_shape, dtype),                  # act_state
        jnp.zeros(micro_shape, dtype),                  # cot_state
        jnp.zeros((depth,) + micro_shape, dtype),       # residual ring
        jnp.zeros((num_micro,) + micro_shape, dtype),   # dx bank
        jnp.zeros((), jnp.float32),                     # loss sum
        zeros_g(p),                                     # stage grads
        zeros_g(out_params),                            # out grads
    )
    (_, _, _, dx_bank, loss_acc, sg_acc, og_acc), _ = lax.scan(
        fwd_bwd, init, jnp.arange(total_ticks))

    loss_sum = lax.psum(loss_acc, axis_name)
    og_sum = jax.tree.map(lambda g: lax.psum(g, axis_name), og_acc)
    dx_sum = lax.psum(dx_bank, axis_name)
    if data_axis is not None:
        # Batch is also sharded: grads/loss average over the data axis
        # (equal shard sizes — the reference's equal-batch contract).
        loss_sum = lax.pmean(loss_sum, data_axis)
        og_sum = jax.tree.map(lambda g: lax.pmean(g, data_axis), og_sum)
        sg_acc = jax.tree.map(lambda g: lax.pmean(g, data_axis), sg_acc)
    stage_grads = jax.tree.map(lambda g: g[None], sg_acc)
    return loss_sum, stage_grads, og_sum, dx_sum


def make_pipeline(
    mesh: Mesh,
    stage_fn: StageFn,
    *,
    axis_name: str = AXIS_STAGE,
    num_microbatches: int = 4,
    remat: bool = False,
):
    """Jitted global-view pipeline.

    ``stage_params`` arrive with a leading stage axis (``[n_stages, ...]``,
    sharded over ``axis_name``); input ``x: [batch, d]`` is split into
    ``num_microbatches`` equal microbatches (batch must divide evenly —
    the reference's equal-batch contract, ``demo.py:113``).
    """

    def global_fn(stage_params, x):
        num_micro = num_microbatches
        micro = x.shape[0] // num_micro
        xm = x.reshape((num_micro, micro) + x.shape[1:])

        def body(sp, xmb):
            return pipeline_shard(
                sp, xmb, stage_fn=stage_fn, axis_name=axis_name, remat=remat
            )[None]

        # Leading stage axis on the output; slicing the last block makes
        # XLA move one stage's data (a broadcast from the final stage)
        # instead of all-reducing zeros from every other stage.
        out = compat_shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis_name), P()),
            out_specs=P(axis_name),
        )(stage_params, xm)
        out = out[-1]
        return out.reshape((num_micro * micro,) + out.shape[2:])

    return jax.jit(global_fn)
