"""Expert parallelism: top-k routed MoE (k=1 Switch, k>1 Mixtral/GShard)
with ``all_to_all`` token exchange over the ``model`` (expert) mesh axis.

Absent from the reference (SURVEY.md §2.4: EP "not required for parity");
provided as the TPU-native extension.  Design, TPU-first:

- **capacity-based dispatch**: every device sends exactly
  ``capacity`` token slots to every expert — static shapes, no
  data-dependent gathers, so XLA can tile the expert matmuls on the MXU;
  overflow assignments are dropped (standard Switch-Transformer
  semantics) and their outputs fall back to zero, surfaced via the
  returned stats.
- **one `lax.all_to_all` each way**: dispatch and return ride a single
  fused ICI collective rather than per-expert sends.
- differentiable: routing probabilities multiply the combined output
  (straight-through on the top-k route), so router + experts train; the
  Switch/GShard balance auxiliary rides ``MoEStats``.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.parallel.overlap import (compat_axis_size,
                                     compat_shard_map)
from tpudist.runtime.mesh import AXIS_MODEL

# ExpertFn: (expert_params, tokens [slots, d]) -> [slots, d]
ExpertFn = Callable[[dict, jax.Array], jax.Array]


class MoEStats(NamedTuple):
    """Per-shard routing observability (host-side metrics material) plus
    the differentiable load-balancing auxiliary loss."""

    # NOTE: at k>1 the fractions below are over the k·tokens ASSIGNMENTS,
    # not over tokens.
    dropped_fraction: jax.Array  # scalar: assignments that overflowed capacity
    expert_load: jax.Array  # [n_experts]: fraction of assignments per expert
    balance_loss: jax.Array  # scalar: Switch/GShard aux loss (1.0 = uniform)


def _topk_dispatch(router_logits, n_experts, capacity, k=1):
    """Build the [tokens, experts, capacity] dispatch/combine tensors for
    top-``k`` routing.  Routing probabilities are computed in f32 whatever
    the compute dtype (argmax ties and gate scales are precision-sensitive).

    ``k=1``: Switch semantics — the raw top probability gates the output.
    ``k>1``: Mixtral/GShard semantics — the k gates renormalize to sum 1.
    Capacity queues fill in choice-major priority (every token's first
    choice is placed before any second choice), the standard GShard order.

    The returned ``balance_loss`` is the Switch §2.2 / GShard auxiliary:
    ``n_experts · Σ_e f_e · P_e`` with ``f_e`` the fraction of assignments
    routed to expert *e* and ``P_e`` its mean router probability — 1.0 at
    perfect balance, differentiable through ``P_e``.
    """
    t = router_logits.shape[0]
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    gate_vals, expert_idx = lax.top_k(probs, k)  # [tokens, k]
    if k > 1:
        gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # Choice-major flattening: [k·tokens] with all first choices leading.
    flat_idx = expert_idx.T.reshape(-1)
    one_hot = jax.nn.one_hot(flat_idx, n_experts, dtype=jnp.int32)
    pos_in_expert = jnp.cumsum(one_hot, axis=0) * one_hot - one_hot
    pos = jnp.sum(pos_in_expert, axis=-1)  # [k·tokens]
    kept = pos < capacity

    disp_flat = (
        one_hot[:, :, None].astype(jnp.float32)
        * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :]
        * kept[:, None, None]
    )  # [k·tokens, experts, capacity]
    disp_kt = disp_flat.reshape(k, t, n_experts, capacity)
    dispatch = jnp.sum(disp_kt, axis=0)  # distinct experts per token: 0/1
    combine = jnp.einsum("ktec,tk->tec", disp_kt, gate_vals)

    load = jnp.mean(one_hot.astype(jnp.float32), axis=0)  # f_e over choices
    balance = n_experts * jnp.sum(load * jnp.mean(probs, axis=0))
    stats = MoEStats(
        dropped_fraction=1.0 - jnp.mean(kept.astype(jnp.float32)),
        expert_load=load,
        balance_loss=balance,
    )
    return dispatch, combine, stats


def moe_shard(
    params: dict,
    x: jax.Array,
    *,
    expert_fn: ExpertFn,
    capacity_factor: float = 1.25,
    axis_name: str = AXIS_MODEL,
    k: int = 1,
):
    """Shard-local MoE body (call inside ``shard_map``).

    ``params = {'router': [d, n_experts], 'experts': pytree with leading
    local-expert axis}``; ``x: [local_tokens, d]``.  One expert per device
    (n_experts == axis size); ``k`` routes each token to its top-k experts
    (capacity scales with k so the fair share per expert is unchanged).
    """
    n_experts = compat_axis_size(axis_name)
    tokens = x.shape[0]
    capacity = int(capacity_factor * k * tokens / n_experts + 0.5)

    dispatch, combine, stats = _topk_dispatch(
        x @ params["router"], n_experts, capacity, k=k
    )
    # [tokens, experts, cap] × [tokens, d] -> [experts, cap, d].  The f32
    # dispatch/combine masks are cast to the compute dtype so the einsums
    # (and the expert matmuls they feed) stay on the bf16 MXU path.
    expert_inputs = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    # Exchange: each device keeps rows for ITS expert from every peer.
    # -> [peers, cap, d] on each device (split experts, concat peers).
    expert_inputs = lax.all_to_all(
        expert_inputs, axis_name, split_axis=0, concat_axis=0
    )
    local_expert = jax.tree.map(lambda a: a[0], params["experts"])
    expert_out = expert_fn(
        local_expert, expert_inputs.reshape(-1, x.shape[-1])
    ).reshape(expert_inputs.shape)
    # Return trip: rows go back to their source device.
    expert_out = lax.all_to_all(expert_out, axis_name, split_axis=0, concat_axis=0)
    out = jnp.einsum("tec,ecd->td", combine.astype(expert_out.dtype),
                     expert_out)
    # Stats become job-global means so every shard returns the same value
    # (replicated out-spec) — the host logs them off the compiled path, the
    # reference's metric-reduction discipline (SURVEY.md §5.5).
    stats = MoEStats(*(lax.pmean(s, axis_name) for s in stats))
    return out, stats


def make_moe(
    mesh: Mesh,
    expert_fn: ExpertFn,
    *,
    axis_name: str = AXIS_MODEL,
    batch_axis: str | None = None,
    capacity_factor: float = 1.25,
    k: int = 1,
):
    """Jitted global-view MoE layer over ``mesh``.

    ``params['experts']`` arrives stacked ``[n_experts, ...]`` sharded over
    ``axis_name``; ``x: [tokens, d]`` sharded over ``batch_axis`` (or
    replicated).  ``k`` selects top-k routing.  Returns ``(y, MoEStats)``
    with job-global stats (``balance_loss`` stays differentiable).
    """
    def body(params, x):
        out, stats = moe_shard(
            params, x,
            expert_fn=expert_fn,
            capacity_factor=capacity_factor,
            axis_name=axis_name,
            k=k,
        )
        if batch_axis is not None:
            stats = MoEStats(*(lax.pmean(s, batch_axis) for s in stats))
        return out, stats

    param_specs = {"router": P(), "experts": P(axis_name)}
    sharded = compat_shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, P(batch_axis, None)),
        out_specs=(P(batch_axis, None), MoEStats(P(), P(), P())),
    )
    return jax.jit(sharded)
