"""Expert parallelism: top-1 switch-routing MoE with ``all_to_all``
token exchange over the ``model`` (expert) mesh axis.

Absent from the reference (SURVEY.md §2.4: EP "not required for parity");
provided as the TPU-native extension.  Design, TPU-first:

- **capacity-based dispatch**: every device sends exactly
  ``capacity`` token slots to every expert — static shapes, no
  data-dependent gathers, so XLA can tile the expert matmuls on the MXU;
  overflow tokens are dropped (standard Switch-Transformer semantics) and
  their outputs fall back to zero, surfaced via the returned stats.
- **one `lax.all_to_all` each way**: dispatch and return ride a single
  fused ICI collective rather than per-expert sends.
- differentiable: routing probabilities multiply the combined output
  (straight-through on the argmax route), so router + experts train.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.runtime.mesh import AXIS_MODEL

# ExpertFn: (expert_params, tokens [slots, d]) -> [slots, d]
ExpertFn = Callable[[dict, jax.Array], jax.Array]


class MoEStats(NamedTuple):
    """Per-shard routing observability (host-side metrics material)."""

    dropped_fraction: jax.Array  # scalar: tokens that overflowed capacity
    expert_load: jax.Array  # [n_experts]: fraction routed to each expert


def _one_hot_dispatch(router_logits, n_experts, capacity):
    """Build the [tokens, experts, capacity] dispatch/combine tensors.
    Routing probabilities are computed in f32 whatever the compute dtype
    (argmax ties and gate scales are precision-sensitive)."""
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [tokens]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    expert_1h = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)
    # Position of each token within its expert's queue (prefix count).
    pos_in_expert = jnp.cumsum(expert_1h, axis=0) * expert_1h - expert_1h
    pos = jnp.sum(pos_in_expert, axis=-1)  # [tokens]
    kept = pos < capacity

    dispatch = (
        expert_1h[:, :, None].astype(jnp.float32)
        * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :]
        * kept[:, None, None]
    )  # [tokens, experts, capacity]
    combine = dispatch * gate[:, None, None]
    stats = MoEStats(
        dropped_fraction=1.0 - jnp.mean(kept.astype(jnp.float32)),
        expert_load=jnp.mean(expert_1h.astype(jnp.float32), axis=0),
    )
    return dispatch, combine, stats


def moe_shard(
    params: dict,
    x: jax.Array,
    *,
    expert_fn: ExpertFn,
    capacity_factor: float = 1.25,
    axis_name: str = AXIS_MODEL,
):
    """Shard-local MoE body (call inside ``shard_map``).

    ``params = {'router': [d, n_experts], 'experts': pytree with leading
    local-expert axis}``; ``x: [local_tokens, d]``.  One expert per device
    (n_experts == axis size); generalizing to k experts/device only changes
    the reshape arithmetic.
    """
    n_experts = lax.axis_size(axis_name)
    tokens = x.shape[0]
    capacity = int(capacity_factor * tokens / n_experts + 0.5)

    dispatch, combine, stats = _one_hot_dispatch(
        x @ params["router"], n_experts, capacity
    )
    # [tokens, experts, cap] × [tokens, d] -> [experts, cap, d].  The f32
    # dispatch/combine masks are cast to the compute dtype so the einsums
    # (and the expert matmuls they feed) stay on the bf16 MXU path.
    expert_inputs = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    # Exchange: each device keeps rows for ITS expert from every peer.
    # -> [peers, cap, d] on each device (split experts, concat peers).
    expert_inputs = lax.all_to_all(
        expert_inputs, axis_name, split_axis=0, concat_axis=0
    )
    local_expert = jax.tree.map(lambda a: a[0], params["experts"])
    expert_out = expert_fn(
        local_expert, expert_inputs.reshape(-1, x.shape[-1])
    ).reshape(expert_inputs.shape)
    # Return trip: rows go back to their source device.
    expert_out = lax.all_to_all(expert_out, axis_name, split_axis=0, concat_axis=0)
    out = jnp.einsum("tec,ecd->td", combine.astype(expert_out.dtype),
                     expert_out)
    # Stats become job-global means so every shard returns the same value
    # (replicated out-spec) — the host logs them off the compiled path, the
    # reference's metric-reduction discipline (SURVEY.md §5.5).
    stats = MoEStats(*(lax.pmean(s, axis_name) for s in stats))
    return out, stats


def make_moe(
    mesh: Mesh,
    expert_fn: ExpertFn,
    *,
    axis_name: str = AXIS_MODEL,
    batch_axis: str | None = None,
    capacity_factor: float = 1.25,
):
    """Jitted global-view MoE layer over ``mesh``.

    ``params['experts']`` arrives stacked ``[n_experts, ...]`` sharded over
    ``axis_name``; ``x: [tokens, d]`` sharded over ``batch_axis`` (or
    replicated).  Returns ``(y, MoEStats)`` with per-shard stats.
    """
    def body(params, x):
        out, stats = moe_shard(
            params, x,
            expert_fn=expert_fn,
            capacity_factor=capacity_factor,
            axis_name=axis_name,
        )
        if batch_axis is not None:
            stats = MoEStats(*(lax.pmean(s, batch_axis) for s in stats))
        return out, stats

    param_specs = {"router": P(), "experts": P(axis_name)}
    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, P(batch_axis, None)),
        out_specs=(P(batch_axis, None), MoEStats(P(), P())),
        check_vma=False,
    )
    return jax.jit(sharded)
