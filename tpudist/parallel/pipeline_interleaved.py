"""Interleaved (virtual-stage) 1F1B pipeline schedule.

:mod:`tpudist.parallel.pipeline` gives two schedules: GPipe (autodiff
backward, O(M) residuals) and non-interleaved 1F1B (O(S) residuals).
Both pay the same pipeline-fill bubble: ~2·(D−1) full-stage units per
step on D devices.  This module adds the Megatron-style interleaved
schedule (Narayanan et al. 2021): each device holds ``V`` depth-strided
model chunks (device ``d`` owns global stages ``{c·D + d}``), so a
microbatch makes ``V`` laps around the device ring through chunks 1/V
the size — the fill/drain bubble shrinks ~÷V at the cost of ~V× more
(but V× smaller) activation hops.

TPU-first formulation — everything is ONE jitted ``lax.scan`` inside one
``shard_map``, no data-dependent control flow:

- the schedule is computed AT TRACE TIME by a Python discrete-event
  simulator (:func:`interleaved_schedule`) implementing warmup-capped
  1F1B: per tick each device runs (at most) one forward unit and one
  backward unit (the pair-tick convention of ``pipeline_1f1b_shard``),
  chosen by static readiness, with per-chunk in-flight bounded by the
  residual lifetime and per-device in-flight by Megatron's interleaved
  warmup depth ``(V−1)·D + 2(D−d)`` — residual memory stays O(V·D),
  constant in the microbatch count, like non-interleaved 1F1B (at V=1
  the simulator reproduces that schedule's canonical timeline exactly);
- the resulting per-tick (unit, operand) choices are baked into
  ``[T, D]`` integer tables the scan body indexes with
  ``lax.axis_index`` — SPMD-uniform, fully static to XLA;
- activation residuals and in-flight cotangents live in fixed-depth
  banks whose slots are assigned by OFFLINE interval allocation over the
  static schedule (lifetime [first-write, last-read]; reads precede
  writes within a tick, so a slot frees the tick its last read lands);
- activations hop right and cotangents hop left every tick with a full
  ``lax.ppermute`` ring (wrap included: leaving device D−1 re-enters
  device 0 one chunk deeper); receive-side masking keeps it uniform;
- backward recomputes each chunk's forward from the saved chunk INPUT
  (stage-granular remat), exactly like the non-interleaved schedule.

The head-cost note from ``pipeline_1f1b_shard`` applies unchanged:
``loss_fn`` (the vocab head) runs under a true per-device ``lax.cond``
branch, so only the device holding the last global stage's fresh
activation pays head FLOPs at any tick.

Reference lineage: the reference repo has no pipeline schedules at all
(its only model parallelism is the manual 2-stage split,
``demo_one_model_multi_gpu.py:17-42``); this is capability surplus
motivated by its multi-node scaling story.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpudist.parallel.pipeline import head_grad_branches
from tpudist.parallel.overlap import compat_axis_size
from tpudist.runtime.mesh import AXIS_STAGE

_INF = 10**9


def _fwd_order(D: int, V: int, M: int):
    """Per-device forward unit order: groups of D microbatches, each
    group walked through the V local chunks (Megatron's grouping)."""
    return [(m, c)
            for g0 in range(0, M, D)
            for c in range(V)
            for m in range(g0, g0 + D)]


def _bwd_order(D: int, V: int, M: int):
    return [(m, c)
            for g0 in range(0, M, D)
            for c in range(V - 1, -1, -1)
            for m in range(g0, g0 + D)]


def _alloc_slots(intervals):
    """Offline interval register allocation.

    ``intervals``: ``[(write_tick, last_read_tick, key), ...]``.  Returns
    ``(assignment dict key->slot, depth)``.  A slot is reusable from its
    last read tick onward because the scan body performs ALL bank reads
    before any bank write within a tick."""
    assign = {}
    free: list = []  # heap of (available_from_tick, slot)
    next_slot = 0
    for w, r, key in sorted(intervals, key=lambda iv: (iv[0], iv[1])):
        if free and free[0][0] <= w:
            _, slot = heapq.heappop(free)
        else:
            slot, next_slot = next_slot, next_slot + 1
        assign[key] = slot
        heapq.heappush(free, (r, slot))
    return assign, max(next_slot, 1)


@dataclass(frozen=True)
class InterleavedSchedule:
    """Static schedule tables, all ``[total_ticks, n_dev]`` int32."""

    n_dev: int
    n_chunks: int
    n_micro: int
    total_ticks: int
    act_depth: int
    cot_depth: int
    tables: dict = field(repr=False)

    @property
    def bubble_ticks(self) -> int:
        """Ticks beyond the per-device useful work (M·V units)."""
        return self.total_ticks - self.n_micro * self.n_chunks


def interleaved_schedule(n_dev: int, n_chunks: int,
                         n_micro: int) -> InterleavedSchedule:
    """Simulate warmup-capped interleaved 1F1B and bake the tables.

    Raises if the microbatch count does not divide into device-sized
    groups (``M % D != 0``, the Megatron grouping constraint) or if the
    simulation fails to converge (a schedule bug, not a user error).
    """
    D, V, M = n_dev, n_chunks, n_micro
    if M % D:
        raise ValueError(f"num_microbatches {M} must be a multiple of the "
                         f"pipeline width {D} for the interleaved schedule")
    S = D * V
    fq = _fwd_order(D, V, M)
    bq = _bwd_order(D, V, M)
    n_units = M * V
    # Forward admission is bounded two ways (each tick runs one fwd AND
    # one bwd unit, the pair-tick convention of pipeline_1f1b_shard):
    # per chunk, in-flight <= residual lifetime 2(S-1-g)+1 — the same
    # bound the non-interleaved ring depth encodes, so V=1 reproduces its
    # no-stall timeline exactly; per device, total in-flight <=
    # (V-1)·D + 2(D-d) — the Megatron interleaved warmup depth, keeping
    # residual memory O(V·D), constant in M.  A too-small device cap
    # deadlocks the sim; retry with slack and fail loudly if it persists.
    for slack in range(0, 4):
        dev_cap = [(V - 1) * D + 2 * (D - d) + slack for d in range(D)]
        sim = _simulate(D, V, S, M, fq, bq, n_units, dev_cap)
        if sim is not None:
            break
    else:
        raise RuntimeError("interleaved schedule simulation did not "
                           f"converge for D={D} V={V} M={M}")
    fwd_done, bwd_done, fwd_events, bwd_events, T = sim

    # ---- offline slot allocation ----
    act_iv = {d: [] for d in range(D)}   # consumer-keyed activation slots
    cot_iv = {d: [] for d in range(D)}   # consumer-keyed cotangent slots
    for (t, d, m, c) in fwd_events:
        g = c * D + d
        if g < S - 1:
            rd, cc = (g + 1) % D, (g + 1) // D
            act_iv[rd].append((t, bwd_done[(rd, m, cc)], (m, cc)))
        else:
            # loss cotangent, produced on-device at the fwd tick
            cot_iv[d].append((t, bwd_done[(d, m, c)], (m, c)))
    for (t, d, m, c) in bwd_events:
        g = c * D + d
        if g > 0:
            pd, pc = (g - 1) % D, (g - 1) // D
            cot_iv[pd].append((t, bwd_done[(pd, m, pc)], (m, pc)))
    act_assign, cot_assign = {}, {}
    act_depth = cot_depth = 1
    for d in range(D):
        a, da = _alloc_slots(act_iv[d])
        k, dk = _alloc_slots(cot_iv[d])
        act_assign[d], cot_assign[d] = a, k
        act_depth, cot_depth = max(act_depth, da), max(cot_depth, dk)

    # ---- tables ----
    def tab():
        return np.zeros((T, D), np.int32)

    t_ = {name: tab() for name in (
        "fwd_valid", "fwd_m", "fwd_c", "fwd_from_x", "fwd_slot",
        "take_loss", "loss_cot_valid", "loss_cot_slot",
        "act_recv_valid", "act_recv_slot",
        "bwd_valid", "bwd_m", "bwd_c", "bwd_from_x", "bwd_act_slot",
        "bwd_cot_slot", "take_dx",
        "cot_recv_valid", "cot_recv_slot",
    )}
    for (t, d, m, c) in fwd_events:
        g = c * D + d
        t_["fwd_valid"][t, d] = 1
        t_["fwd_m"][t, d] = m
        t_["fwd_c"][t, d] = c
        if g == 0:
            t_["fwd_from_x"][t, d] = 1
        else:
            t_["fwd_slot"][t, d] = act_assign[d][(m, c)]
        if g == S - 1:
            t_["take_loss"][t, d] = 1
            t_["loss_cot_valid"][t, d] = 1
            t_["loss_cot_slot"][t, d] = cot_assign[d][(m, c)]
        else:
            rd, cc = (g + 1) % D, (g + 1) // D
            t_["act_recv_valid"][t, rd] = 1
            t_["act_recv_slot"][t, rd] = act_assign[rd][(m, cc)]
    for (t, d, m, c) in bwd_events:
        g = c * D + d
        t_["bwd_valid"][t, d] = 1
        t_["bwd_m"][t, d] = m
        t_["bwd_c"][t, d] = c
        t_["bwd_cot_slot"][t, d] = cot_assign[d][(m, c)]
        if g == 0:
            t_["bwd_from_x"][t, d] = 1
            t_["take_dx"][t, d] = 1
        else:
            t_["bwd_act_slot"][t, d] = act_assign[d][(m, c)]
        if g > 0:
            pd, pc = (g - 1) % D, (g - 1) // D
            t_["cot_recv_valid"][t, pd] = 1
            t_["cot_recv_slot"][t, pd] = cot_assign[pd][(m, pc)]
    return InterleavedSchedule(
        n_dev=D, n_chunks=V, n_micro=M, total_ticks=T,
        act_depth=act_depth, cot_depth=cot_depth, tables=t_)


def _simulate(D, V, S, M, fq, bq, n_units, dev_cap):
    """One capped-greedy pass; returns None on deadlock."""
    fwd_done, bwd_done = {}, {}
    fi, bi = [0] * D, [0] * D
    chunk_fly = {(d, c): 0 for d in range(D) for c in range(V)}
    fwd_events, bwd_events = [], []
    bound = 8 * S + 4 * n_units + 64
    t = 0
    while any(fi[d] < n_units or bi[d] < n_units for d in range(D)):
        if t > bound:
            return None
        progressed = False
        plan_f = []
        for d in range(D):
            if fi[d] >= n_units or (fi[d] - bi[d]) >= dev_cap[d]:
                continue
            m, c = fq[fi[d]]
            g = c * D + d
            # +2, not +1: the fwd plan runs before the same tick's bwd
            # plan, so the counter still includes a unit whose backward
            # retires THIS tick (the F half of an F+B pair-tick must not
            # be blocked by it).  True residual memory is measured by the
            # offline allocator from actual lifetimes, not this cap.
            if chunk_fly[(d, c)] >= 2 * (S - 1 - g) + 2:
                continue
            if g == 0:
                ready = True
            else:
                pd, pc = (g - 1) % D, (g - 1) // D
                ready = fwd_done.get((pd, m, pc), _INF) <= t - 1
            if ready:
                plan_f.append((d, m, c))
        for d, m, c in plan_f:
            fwd_done[(d, m, c)] = t
            fi[d] += 1
            chunk_fly[(d, c)] += 1
            fwd_events.append((t, d, m, c))
            progressed = True
        plan_b = []
        for d in range(D):
            if bi[d] >= n_units:
                continue
            m, c = bq[bi[d]]
            g = c * D + d
            if g == S - 1:
                ready = fwd_done.get((d, m, c), _INF) <= t - 1
            else:
                sd, sc = (g + 1) % D, (g + 1) // D
                ready = (bwd_done.get((sd, m, sc), _INF) <= t - 1
                         and fwd_done.get((d, m, c), _INF) <= t)
            if ready:
                plan_b.append((d, m, c))
        for d, m, c in plan_b:
            bwd_done[(d, m, c)] = t
            bi[d] += 1
            chunk_fly[(d, c)] -= 1
            bwd_events.append((t, d, m, c))
            progressed = True
        if not progressed:
            # The done-maps only grow when a unit commits, so a tick with
            # zero commits can never unblock a later tick: deadlock.
            return None
        t += 1
    return fwd_done, bwd_done, fwd_events, bwd_events, t


def interleave_block_params(stacked, n_dev: int):
    """Permute a ``[S_total, ...]`` stage stack into the device-major
    interleaved layout: position ``j = d·V + c`` holds global stage
    ``c·D + d``, so sharding the leading axis ``P(stage)`` over D devices
    hands device ``d`` exactly its depth-strided chunks in local order."""
    s_total = jax.tree.leaves(stacked)[0].shape[0]
    if s_total % n_dev:
        raise ValueError(f"stage stack of {s_total} does not split over "
                         f"{n_dev} devices")
    v = s_total // n_dev
    perm = np.asarray([(j % v) * n_dev + j // v for j in range(s_total)])
    return jax.tree.map(lambda a: jnp.take(a, perm, axis=0), stacked)


def deinterleave_block_params(stacked, n_dev: int):
    """Inverse of :func:`interleave_block_params` (checkpoint interop)."""
    s_total = jax.tree.leaves(stacked)[0].shape[0]
    v = s_total // n_dev
    perm = np.asarray([(j % v) * n_dev + j // v for j in range(s_total)])
    inv = np.argsort(perm)
    return jax.tree.map(lambda a: jnp.take(a, inv, axis=0), stacked)


def pipeline_interleaved_shard(
    stage_params,
    out_params,
    x_microbatches: jax.Array,
    aux_microbatches: jax.Array,
    *,
    stage_fn,
    loss_fn,
    schedule: InterleavedSchedule,
    axis_name: str = AXIS_STAGE,
    data_axis=None,
):
    """Shard-local interleaved 1F1B body (call inside ``shard_map``).

    Same contract as :func:`tpudist.parallel.pipeline.pipeline_1f1b_shard`
    except ``stage_params`` arrives as this device's ``[V, ...]`` chunk
    stack (the :func:`interleave_block_params` layout sharded over
    ``axis_name``) and the schedule object carries the static tables.
    Returns ``(loss_sum, chunk_grads [V, ...], out_grads, dx_microbatches)``
    — unnormalized sums over this shard's microbatches, loss/out/dx
    psum-replicated over the stage axis.
    """
    D = schedule.n_dev
    V = schedule.n_chunks
    if compat_axis_size(axis_name) != D:
        raise ValueError(f"schedule built for {D} devices, axis "
                         f"{axis_name!r} has {compat_axis_size(axis_name)}")
    my = lax.axis_index(axis_name)
    num_micro = schedule.n_micro
    if x_microbatches.shape[0] != num_micro:
        raise ValueError(f"schedule built for {num_micro} microbatches, "
                         f"got {x_microbatches.shape[0]}")
    local_chunks = jax.tree.leaves(stage_params)[0].shape[0]
    if local_chunks != V:
        # Must be loud: dynamic_index_in_dim CLAMPS an out-of-range chunk
        # index, so a contiguous-layout state would otherwise train
        # silently on chunk 0's params with garbage gradients.
        raise ValueError(
            f"stage_params carry {local_chunks} chunks per device but the "
            f"schedule was built for n_chunks={V} — stack with "
            f"stack_block_params_interleaved(params, n_dev, n_chunks)")
    micro_shape = x_microbatches.shape[1:]
    dtype = x_microbatches.dtype

    ring_r = [(i, (i + 1) % D) for i in range(D)]
    ring_l = [((i + 1) % D, i) for i in range(D)]

    tabs = {k: jnp.asarray(v) for k, v in schedule.tables.items()}

    def chunk_p(c):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
            stage_params)

    # The vocab head runs under a true per-device runtime branch — only
    # the tick/device holding the last global stage's fresh activation
    # pays head FLOPs.  See head_grad_branches for the rationale and the
    # collective-free requirement on loss_fn.
    head, head_zeros = head_grad_branches(loss_fn)

    def tick(carry, rows):
        (act_bank, cot_bank, dx_bank, loss_acc, cg_acc, og_acc) = carry
        r = {k: jnp.take(v, my) for k, v in rows.items()}

        # ---- forward unit (reads banks, no writes yet) ----
        fm, fc = r["fwd_m"], r["fwd_c"]
        x_m = lax.dynamic_index_in_dim(x_microbatches, fm, 0, keepdims=False)
        a_bank = lax.dynamic_index_in_dim(act_bank, r["fwd_slot"], 0,
                                          keepdims=False)
        a_in = jnp.where(r["fwd_from_x"].astype(bool), x_m, a_bank)
        a_out = stage_fn(chunk_p(fc), a_in)

        aux_m = lax.dynamic_index_in_dim(aux_microbatches, fm, 0,
                                         keepdims=False)
        need_head = (r["take_loss"] | r["loss_cot_valid"]).astype(bool)
        (l_m, (d_og, d_act)) = lax.cond(
            need_head, head, head_zeros, (out_params, a_out, aux_m))
        take_loss = (r["take_loss"] & r["fwd_valid"]).astype(bool)
        loss_acc = loss_acc + jnp.where(take_loss, l_m, 0.0)
        og_acc = jax.tree.map(
            lambda acc, g: acc + jnp.where(take_loss, g, 0.0), og_acc, d_og)

        # ---- backward unit (reads banks BEFORE any write) ----
        bm, bc = r["bwd_m"], r["bwd_c"]
        bwd_valid = r["bwd_valid"].astype(bool)
        res_x = lax.dynamic_index_in_dim(x_microbatches, bm, 0,
                                         keepdims=False)
        res_bank = lax.dynamic_index_in_dim(act_bank, r["bwd_act_slot"], 0,
                                            keepdims=False)
        a_res = jnp.where(r["bwd_from_x"].astype(bool), res_x, res_bank)
        cot_in = lax.dynamic_index_in_dim(cot_bank, r["bwd_cot_slot"], 0,
                                          keepdims=False)
        _, chunk_vjp = jax.vjp(stage_fn, chunk_p(bc), a_res)
        dp, da = chunk_vjp(cot_in)
        cg_acc = jax.tree.map(
            lambda acc, g: lax.dynamic_update_index_in_dim(
                acc,
                lax.dynamic_index_in_dim(acc, bc, 0, keepdims=False)
                + jnp.where(bwd_valid, g, 0.0),
                bc, 0),
            cg_acc, dp)
        take_dx = (r["take_dx"].astype(bool) & bwd_valid)
        old_dx = lax.dynamic_index_in_dim(dx_bank, bm, 0, keepdims=False)
        dx_bank = lax.dynamic_update_index_in_dim(
            dx_bank, jnp.where(take_dx, da, old_dx), bm, 0)

        # ---- communication + bank writes (after ALL reads) ----
        a_msg = lax.ppermute(a_out, axis_name, ring_r)
        old_a = lax.dynamic_index_in_dim(act_bank, r["act_recv_slot"], 0,
                                         keepdims=False)
        act_bank = lax.dynamic_update_index_in_dim(
            act_bank,
            jnp.where(r["act_recv_valid"].astype(bool), a_msg, old_a),
            r["act_recv_slot"], 0)

        c_msg = lax.ppermute(da, axis_name, ring_l)
        # two cot writes can never share a tick+slot: the loss cot is
        # written by the last global stage at a fwd tick, recv cots by
        # the left hop of a bwd tick — distinct consumer units, and the
        # allocator keyed both on the consumer, so gate them in sequence.
        old_c = lax.dynamic_index_in_dim(cot_bank, r["cot_recv_slot"], 0,
                                         keepdims=False)
        cot_bank = lax.dynamic_update_index_in_dim(
            cot_bank,
            jnp.where(r["cot_recv_valid"].astype(bool), c_msg, old_c),
            r["cot_recv_slot"], 0)
        old_lc = lax.dynamic_index_in_dim(cot_bank, r["loss_cot_slot"], 0,
                                          keepdims=False)
        cot_bank = lax.dynamic_update_index_in_dim(
            cot_bank,
            jnp.where(r["loss_cot_valid"].astype(bool), d_act, old_lc),
            r["loss_cot_slot"], 0)

        return (act_bank, cot_bank, dx_bank, loss_acc, cg_acc, og_acc), None

    zeros_like_tree = lambda t: jax.tree.map(jnp.zeros_like, t)
    init = (
        jnp.zeros((schedule.act_depth,) + micro_shape, dtype),
        jnp.zeros((schedule.cot_depth,) + micro_shape, dtype),
        jnp.zeros((num_micro,) + micro_shape, dtype),
        jnp.zeros((), jnp.float32),
        jax.tree.map(lambda a: jnp.zeros_like(a), stage_params),
        zeros_like_tree(out_params),
    )
    (_, _, dx_bank, loss_acc, cg_acc, og_acc), _ = lax.scan(
        tick, init, tabs)

    loss_sum = lax.psum(loss_acc, axis_name)
    og_sum = jax.tree.map(lambda g: lax.psum(g, axis_name), og_acc)
    dx_sum = lax.psum(dx_bank, axis_name)
    if data_axis is not None:
        loss_sum = lax.pmean(loss_sum, data_axis)
        og_sum = jax.tree.map(lambda g: lax.pmean(g, data_axis), og_sum)
        cg_acc = jax.tree.map(lambda g: lax.pmean(g, data_axis), cg_acc)
    return loss_sum, cg_acc, og_sum, dx_sum


def format_timeline(schedule: InterleavedSchedule) -> str:
    """ASCII timeline of the schedule (one row per device, one column per
    tick, ``F<m>``/``B<m>``/``·``) — the at-a-glance view of warmup,
    steady 1F1B pairs, and drain.  ``python -m
    tpudist.parallel.pipeline_interleaved D V M`` prints it."""
    t = schedule.tables
    rows = []
    for d in range(schedule.n_dev):
        cells = []
        for tick in range(schedule.total_ticks):
            f = (f"F{t['fwd_m'][tick, d]}.{t['fwd_c'][tick, d]}"
                 if t["fwd_valid"][tick, d] else "")
            b = (f"B{t['bwd_m'][tick, d]}.{t['bwd_c'][tick, d]}"
                 if t["bwd_valid"][tick, d] else "")
            cells.append(f"{f}{'+' if f and b else ''}{b}" or "·")
        rows.append(f"dev{d}: " + " ".join(c.ljust(9) for c in cells))
    head = (f"D={schedule.n_dev} V={schedule.n_chunks} M={schedule.n_micro}"
            f"  ticks={schedule.total_ticks}"
            f" (bubble {schedule.bubble_ticks})"
            f"  act_bank={schedule.act_depth} cot_bank={schedule.cot_depth}")
    return "\n".join([head] + rows)


if __name__ == "__main__":  # pragma: no cover - debug CLI
    import sys as _sys

    d_, v_, m_ = (int(x) for x in _sys.argv[1:4])
    print(format_timeline(interleaved_schedule(d_, v_, m_)))
