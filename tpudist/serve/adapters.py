"""Host-side adapter accounting: the registry half of per-tenant LoRA.

The device half (:mod:`tpudist.models.lora`) is pure indirection — a
factor pool plus per-slot adapter ids gathered inside the compiled
programs.  WHICH pool block holds which named adapter is decided here,
on the host, and shipped into the programs as data (``aids`` into
``insert_batch``, ``SlotState.adapter_id`` everywhere else) — never as
shapes, so tenants loading, unloading, and churning adapters can't
recompile anything.  This is :class:`tpudist.serve.paged_alloc.
BlockAllocator`'s discipline applied to parameters:

- **whole-footprint admission**: one adapter = one block (its complete
  factor set across all layers/projections), reserved at
  :meth:`AdapterRegistry.load` — there is no partial residency;
- **refcounts**: a slot binding an adapter pins it
  (:meth:`acquire`/:meth:`release` — the engine calls these at
  admission/evict), so an in-use adapter's factors can never be
  evicted or overwritten mid-stream;
- **LRU eviction of cold adapters**: a load into a full pool evicts
  the least-recently-USED refcount-zero adapter (its block is zeroed
  on device by the engine — no cross-tenant weight leakage); if every
  block is hot the load fails loudly (:class:`AdapterPoolFull`);
- **deferred unload**: :meth:`unload` of an in-use adapter marks it —
  new requests reject ``adapter_missing`` immediately, the block frees
  (and zeroes) when the last bound lane evicts.

Thread contract: loads/unloads arrive from user threads while the
engine thread acquires/releases — one lock covers every mutation
(the registry is tiny; contention is nil next to a device dispatch).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple


class AdapterPoolFull(RuntimeError):
    """A load found no free block and no cold (refcount-zero) adapter
    to evict — every resident adapter is bound to a live lane."""


class AdapterMissingError(RuntimeError):
    """A lane needs an adapter the pool does not hold (raced unload, or
    a handoff/resume re-bind onto a pool that never loaded the name).
    The serving loops finish the request with reason
    ``"adapter_missing"`` instead of decoding base-model output the
    tenant did not ask for."""

    def __init__(self, name: str):
        super().__init__(
            f"adapter {name!r} is not resident in this pool — finish the "
            "request with reason 'adapter_missing', never silently serve "
            "base-model output")
        self.adapter = name


class AdapterRegistry:
    """name → pool block id, refcounts, LRU (module doc)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self._lock = threading.Lock()
        self._ids: Dict[str, int] = {}
        self._refs: Dict[str, int] = {}
        self._free: List[int] = list(range(num_blocks))
        #: cold adapters in last-use order (oldest first) — the LRU line
        self._lru: "OrderedDict[str, None]" = OrderedDict()
        self._pending_unload: set = set()
        #: names loaded but whose FACTORS are not yet written into the
        #: device pool (two-phase load): ``has``/``acquire`` refuse them
        #: until :meth:`activate` — without this, the engine thread
        #: could bind a freshly-published name and gather a zeroed (or,
        #: after an LRU evict, the VICTIM's) block before the user
        #: thread's factor write lands
        self._pending_load: set = set()
        #: RETIRED generations: block id → lanes still bound to an OLD
        #: factor set whose name was reloaded (``load`` after a deferred
        #: ``unload``) — released by ``(name, bid)``, freed+zeroed when
        #: the last lane evicts
        self._retired: Dict[int, int] = {}
        # lifetime counters (adapter_stats / serving report)
        self.loads = 0
        self.evicts = 0
        self.unloads = 0

    # -- inspection ---------------------------------------------------------

    @property
    def resident(self) -> int:
        return len(self._ids)

    def resident_names(self) -> List[str]:
        with self._lock:
            return sorted(self._ids)

    def has(self, name: str) -> bool:
        """Is ``name`` bindable by a NEW request right now (resident,
        factors written, not marked for unload)?"""
        with self._lock:
            return (name in self._ids
                    and name not in self._pending_unload
                    and name not in self._pending_load)

    def block_of(self, name: str) -> Optional[int]:
        with self._lock:
            return self._ids.get(name)

    def refcount(self, name: str) -> int:
        with self._lock:
            return self._refs.get(name, 0)

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "blocks_total": self.num_blocks,
                "resident": len(self._ids),
                "free_blocks": len(self._free),
                "in_use": sum(1 for r in self._refs.values() if r > 0),
                "pending_unload": len(self._pending_unload),
                "retired_blocks": len(self._retired),
                "loads": self.loads,
                "evicts": self.evicts,
                "unloads": self.unloads,
                "lanes_by_adapter": {n: r for n, r in self._refs.items()
                                     if r > 0},
            }

    # -- load / unload (user threads) ---------------------------------------

    def load(self, name: str) -> Tuple[int, Optional[Tuple[str, int]]]:
        """Reserve a block for ``name``: ``(block_id, evicted)`` where
        ``evicted`` is the ``(name, block_id)`` of the LRU cold adapter
        this load displaced (the caller zeroes that block on device
        BEFORE writing the new factors), or ``None``.  The name stays
        PENDING — invisible to ``has``/``acquire`` — until the caller
        writes the factors and calls :meth:`activate`, so a racing
        admission can never gather a half-loaded block.  A name whose
        unload is still deferred (lanes bound to the OLD factor set)
        reloads immediately: the old generation retires to block-id
        accounting and frees when its last lane evicts.  Raises
        :class:`AdapterPoolFull` when nothing can free and
        ``ValueError`` on a LIVE resident name (unload first — an
        in-place swap under bound lanes would change their streams
        mid-request)."""
        with self._lock:
            if name in self._ids:
                if name not in self._pending_unload:
                    raise ValueError(
                        f"adapter {name!r} is already loaded (unload it "
                        "first — swapping factors under bound lanes would "
                        "change their streams mid-request)")
                # deferred-unload reload: retire the old generation (its
                # lanes keep decoding the OLD block, released by id) and
                # load the new factor set fresh
                old_bid = self._ids.pop(name)
                self._retired[old_bid] = self._refs.pop(name, 0)
                self._lru.pop(name, None)
                self._pending_unload.discard(name)
            evicted = None
            if not self._free:
                if not self._lru:
                    raise AdapterPoolFull(
                        f"adapter pool full: all {self.num_blocks} blocks "
                        "bound to live lanes — nothing cold to evict")
                victim, _ = self._lru.popitem(last=False)
                bid = self._ids.pop(victim)
                self._refs.pop(victim, None)
                self._pending_unload.discard(victim)
                self._free.append(bid)
                self.evicts += 1
                evicted = (victim, bid)
            bid = self._free.pop(0)
            self._ids[name] = bid
            self._refs[name] = 0
            self._lru[name] = None  # cold until a lane binds it
            self._pending_load.add(name)
            self.loads += 1
            return bid, evicted

    def activate(self, name: str) -> None:
        """Publish a loaded name (its factors are now in the device
        pool) — the second half of the two-phase load."""
        with self._lock:
            self._pending_load.discard(name)

    def unload(self, name: str) -> Optional[Tuple[bool, int]]:
        """Drop ``name``: ``(freed_now, block_id)`` — ``freed_now``
        False means lanes still hold it (the block frees when the last
        one evicts; new requests already reject).  ``None`` when the
        name was never resident."""
        with self._lock:
            bid = self._ids.get(name)
            if bid is None:
                return None
            self.unloads += 1
            if self._refs.get(name, 0) > 0:
                self._pending_unload.add(name)
                self._lru.pop(name, None)
                return False, bid
            self._drop_locked(name)
            return True, bid

    def _drop_locked(self, name: str) -> None:
        bid = self._ids.pop(name)
        self._refs.pop(name, None)
        self._lru.pop(name, None)
        self._pending_unload.discard(name)
        self._pending_load.discard(name)
        self._free.append(bid)

    # -- bind / unbind (engine thread) --------------------------------------

    def acquire(self, name: str) -> Optional[int]:
        """Pin ``name`` for one lane: its block id, or ``None`` when it
        is not bindable (missing, factors still pending, or marked for
        unload) — the caller finishes the request ``adapter_missing``."""
        with self._lock:
            if name not in self._ids or name in self._pending_unload \
                    or name in self._pending_load:
                return None
            self._refs[name] = self._refs.get(name, 0) + 1
            self._lru.pop(name, None)  # hot while any lane holds it
            return self._ids[name]

    def release(self, name: str, bid: int) -> Optional[int]:
        """Unpin one lane's hold on ``(name, bid)`` — the bid
        disambiguates a RETIRED generation (the name was reloaded while
        this lane decoded the old factors) from the current one.
        Returns the block id to ZERO on device when this release freed
        the block (a deferred unload or retired generation completing),
        else ``None``."""
        with self._lock:
            if self._ids.get(name) != bid:
                # retired generation: id-keyed accounting
                refs = max(0, self._retired.get(bid, 0) - 1)
                if refs > 0:
                    self._retired[bid] = refs
                    return None
                self._retired.pop(bid, None)
                self._free.append(bid)
                return bid
            refs = max(0, self._refs.get(name, 0) - 1)
            self._refs[name] = refs
            if refs > 0:
                return None
            if name in self._pending_unload:
                self._drop_locked(name)
                return bid
            # cold: joins the LRU line (newest last)
            self._lru[name] = None
            self._lru.move_to_end(name)
            return None
