"""Slot-based continuous-batching decode engine.

The training side of the repo compiles ONE program and feeds it
fixed-shape batches; this module applies the same discipline to serving.
The engine owns ``num_slots`` independent KV-cache lanes (the vmapped
slot-decode primitives of :func:`tpudist.models.make_slot_decode`) and a
small set of host-side cursors; every device interaction is one of four
compiled programs — ``prefill``, ``insert_from``, ``evict``,
``decode_step`` — whose shapes never depend on a request, so concurrent
requests with arbitrary prompt/output lengths join and leave a running
batch with zero recompilation (iteration-level / continuous batching,
arXiv:2509.07003's consistent-tensor-programming regime applied to
inference).

Division of labor: the engine is the DEVICE half — slots, caches,
cursors, token emission.  Queueing, admission, deadlines, and threads
live in :mod:`tpudist.serve.scheduler` / :mod:`tpudist.serve.server`;
the engine is single-threaded by contract (exactly one caller drives
``insert_batch``/``step``/``evict``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpudist.models.generate import make_slot_decode


class SlotEngine:
    """``num_slots`` KV-cache lanes + host cursors over one compiled step.

    Per slot the engine tracks (host-side numpy — the device round-trip
    per iteration is the emitted-token fetch, nothing else):

    - ``active[s]`` — lane occupied;
    - ``last_tok[s]`` — the token the next decode step consumes;
    - ``pos[s]`` — filled cache positions (``plen`` after prefill, +1 per
      decode step); the lane's budget guard is ``pos < max_len``;
    - ``counts[s]`` — tokens emitted so far (also the per-request sampling
      stream index, see ``SlotDecode.sample``);
    - ``temps[s]`` / ``keys[s]`` — per-request sampling config.
    """

    def __init__(self, module, params, *, num_slots: int = 4,
                 prefill_pad: Optional[int] = None):
        if prefill_pad is None:
            prefill_pad = min(int(module.max_len), 64)
        self.module = module
        self.max_len = int(module.max_len)
        self.fns = make_slot_decode(module, params, num_slots, prefill_pad)
        self.num_slots = num_slots
        self.prefill_pad = prefill_pad
        self.cache = self.fns.init_slots()
        self.active = np.zeros(num_slots, bool)
        self.last_tok = np.zeros(num_slots, np.int32)
        self.pos = np.zeros(num_slots, np.int32)
        self.counts = np.zeros(num_slots, np.int32)
        self.temps = np.zeros(num_slots, np.float32)
        self.keys = np.zeros((num_slots, 2), np.uint32)

    # -- inspection ---------------------------------------------------------

    def free_slots(self) -> List[int]:
        return [s for s in range(self.num_slots) if not self.active[s]]

    @property
    def num_active(self) -> int:
        return int(self.active.sum())

    @property
    def occupancy(self) -> float:
        """Busy fraction of the batch — the gauge the telemetry report
        aggregates (an engine decoding one request at 8 slots wastes 7/8
        of every step's HBM sweep)."""
        return self.num_active / self.num_slots

    def compile_counts(self) -> Dict[str, int]:
        """Jit-cache sizes of the compiled primitives — the "no
        recompilation under load" oracle the slow-lane test pins down."""
        out = {}
        for name in ("prefill", "insert_from", "evict", "decode_step"):
            fn = getattr(self.fns, name)
            size = getattr(fn, "_cache_size", None)
            out[name] = int(size()) if callable(size) else -1
        return out

    # -- lifecycle of a request -------------------------------------------

    def check_budget(self, prompt_len: int, max_new: int) -> Optional[str]:
        """``None`` if a request fits, else the rejection reason — the ONE
        budget rule admission control and the engine agree on."""
        if prompt_len < 1:
            return "empty_prompt"
        if prompt_len > self.prefill_pad:
            return (f"prompt_too_long: {prompt_len} > prefill_pad "
                    f"{self.prefill_pad}")
        if max_new < 1:
            return "max_new_must_be_positive"
        if prompt_len + max_new > self.max_len:
            return (f"budget_exceeded: prompt {prompt_len} + max_new "
                    f"{max_new} > max_len {self.max_len}")
        return None

    def insert_batch(
        self,
        items: Sequence[Tuple[int, np.ndarray, float, int]],
    ) -> Dict[int, int]:
        """Prefill up to ``num_slots`` requests in ONE compiled call and
        scatter each into its slot.  ``items``: ``(slot, prompt_1d_int32,
        temperature, seed)`` per request.  Returns ``slot → first
        generated token`` (drawn from the post-prompt logits, so a
        ``max_new == 1`` request is complete without any decode step)."""
        if not items:
            return {}
        if len(items) > self.num_slots:
            raise ValueError(
                f"insert_batch of {len(items)} > num_slots {self.num_slots}")
        import jax.numpy as jnp

        prompts = np.zeros((self.num_slots, self.prefill_pad), np.int32)
        plens = np.zeros(self.num_slots, np.int32)
        keys = np.zeros((self.num_slots, 2), np.uint32)
        temps = np.zeros(self.num_slots, np.float32)
        for j, (slot, prompt, temperature, seed) in enumerate(items):
            if self.active[slot]:
                raise ValueError(f"slot {slot} is occupied")
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            reason = self.check_budget(len(prompt), 1)
            if reason is not None:
                raise ValueError(reason)
            prompts[j, : len(prompt)] = prompt
            plens[j] = len(prompt)
            keys[j] = _seed_key(seed)
            temps[j] = temperature
        caches, last_logits = self.fns.prefill(
            jnp.asarray(prompts), jnp.asarray(plens))
        firsts = np.asarray(self.fns.sample(
            last_logits, jnp.asarray(keys), jnp.asarray(temps),
            jnp.zeros(self.num_slots, jnp.int32)))
        out: Dict[int, int] = {}
        for j, (slot, prompt, temperature, seed) in enumerate(items):
            self.cache = self.fns.insert_from(self.cache, caches, j, slot)
            self.active[slot] = True
            self.last_tok[slot] = firsts[j]
            self.pos[slot] = plens[j]
            self.counts[slot] = 1
            self.temps[slot] = temperature
            self.keys[slot] = keys[j]
            out[int(slot)] = int(firsts[j])
        return out

    def step(self) -> Dict[int, int]:
        """One batched decode iteration over every active slot: consume
        each slot's ``last_tok``, emit the next token.  Returns ``slot →
        token`` for the active slots (callers stream/stop per request)."""
        if not self.active.any():
            return {}
        if (self.pos[self.active] >= self.max_len).any():
            # admission's budget rule makes this unreachable; a loud error
            # beats silently attending over a recycled cache ring.
            raise RuntimeError("active slot at max_len — admission budget "
                               "violated")
        import jax.numpy as jnp

        self.cache, toks = self.fns.decode_step(
            self.cache, jnp.asarray(self.last_tok), jnp.asarray(self.active),
            jnp.asarray(self.keys), jnp.asarray(self.temps),
            jnp.asarray(self.counts))
        toks = np.asarray(toks)
        out = {int(s): int(toks[s]) for s in np.nonzero(self.active)[0]}
        act = self.active
        self.last_tok[act] = toks[act]
        self.pos[act] += 1
        self.counts[act] += 1
        return out

    def evict(self, slot: int) -> None:
        """Free a lane: zero its cache (no K/V leakage into the next
        tenant's garbage window) and reset the host cursors."""
        import jax.numpy as jnp

        self.cache = self.fns.evict(self.cache, jnp.asarray(slot, jnp.int32))
        self.active[slot] = False
        self.last_tok[slot] = 0
        self.pos[slot] = 0
        self.counts[slot] = 0
        self.temps[slot] = 0.0
        self.keys[slot] = 0


def _seed_key(seed: int) -> np.ndarray:
    """A raw ``uint32[2]`` threefry key from an int seed — fetched to host
    once per request so the engine can pass all slots' keys as one array."""
    import jax

    return np.asarray(jax.random.PRNGKey(seed), np.uint32)
