"""Slot-based continuous-batching decode engine.

The training side of the repo compiles ONE program and feeds it
fixed-shape batches; this module applies the same discipline to serving,
and amortizes host work over token *blocks* instead of tokens.  The
engine owns ``num_slots`` independent KV-cache lanes plus a persistent
ON-DEVICE :class:`tpudist.models.SlotState` (the slot-decode primitives
of :func:`tpudist.models.make_slot_decode`); every device interaction is
one of four compiled programs — ``insert_batch``, ``prefill_extend``,
``decode_block``, ``evict`` — whose shapes never depend on a request, so
concurrent requests with arbitrary prompt/output lengths join and leave
a running batch with zero recompilation (iteration-level / continuous
batching, arXiv:2509.07003's consistent-tensor-programming regime
applied to inference).  ``decode_block`` alone compiles once per
power-of-two block size K (a handful of cache entries, pinned by test).

Hot-path accounting, per engine iteration:

- admission: ONE ``insert_batch`` dispatch prefills and scatters a whole
  admission batch (prompt chunks, seeds, temperatures uploaded once);
- chunked prefill: one ``prefill_extend`` dispatch per prefilling slot
  appends a ``prefill_pad``-sized prompt chunk at the slot's running
  offset — prompts up to ``max_len - max_new`` are admissible, and a
  long prompt stalls in-flight decode by at most one chunk per
  iteration;
- decode: ONE ``decode_block`` dispatch produces ``K×num_slots`` tokens
  with in-graph token feedback, then ONE D2H fetch of the block.  The
  host picks ``K = min(block, min remaining budget over active slots)``
  from its shadow cursors (bucketed down to a power of two), so a block
  never overshoots a length budget; early stops (EOS) are truncated
  post-hoc by the caller, wasting at most K - 1 speculated tokens;
- speculative decode (``spec_draft=``): the iteration becomes ONE
  ``draft_propose`` dispatch (K cheap draft steps) + ONE ``spec_verify``
  dispatch (the batched multi-token target pass + in-graph acceptance/
  rollback) + ONE packed D2H fetch — up to K+1 emitted tokens per lane
  per TARGET pass, the fewer-passes-per-token lever past the measured
  decode HBM roofline.  Per-lane budgets ride in as data, so mixed
  budgets clamp in-graph; ``decode_auto`` falls back to the plain block
  (draft-tracked, so the draft never desyncs) whenever speculation
  cannot help the iteration.

Division of labor: the engine is the DEVICE half — slots, caches, the
on-device state, token emission.  The host keeps *shadow* cursors
(occupied/decoding flags, pos/counts/budget) strictly for admission and
block-size decisions; device state is the truth the tokens come from.
Queueing, admission policy, deadlines, and threads live in
:mod:`tpudist.serve.scheduler` / :mod:`tpudist.serve.server`; the engine
is single-threaded by contract (exactly one caller drives
``start_batch``/``advance_prefill``/``decode_block``/``evict``).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpudist.models.generate import make_slot_decode
from tpudist.models.paged import PagedKVConfig
from tpudist.serve.paged_alloc import BlockAllocator

#: ``start_batch`` item: (slot, prompt_1d_int32, temperature, seed, max_new)
#: plus an optional 6th element — the prompt's prefix hash chain
#: (:func:`tpudist.serve.paged_alloc.hash_chain`, stamped at submit by the
#: scheduler) enabling shared-prefix block reuse on the paged engine —
#: an optional 7th — the request's speculative-decoding opt
#: (True/False; only meaningful on a spec engine, where a False lane
#: rides the same spec programs with acceptance forced to zero and its
#: tokens drawn on the plain per-request stream) — an optional 8th —
#: the lane's adapter NAME (None = base-only) — and an optional 9th —
#: the request's compiled :class:`tpudist.constrain.TokenGrammar`
#: (None = unconstrained; the engine binds it into the grammar pool).
InsertItem = Tuple[int, np.ndarray, float, int, int]


def _mesh_devices(mesh) -> int:
    """Device count a serve-mesh spec implies (1 = no mesh) — the
    n_devices input of the auto planner's workload."""
    dims = getattr(mesh, "dims", None)
    if dims is None:
        return 1
    try:
        d, m = dims
        return int(d) * int(m)
    except (TypeError, ValueError):
        return 1


def _pow2_floor(k: int) -> int:
    """Largest power of two ``<= k`` — the block-size bucketing rule that
    bounds ``decode_block``'s jit cache at ``log2(max_block) + 1``."""
    return 1 << (max(1, k).bit_length() - 1)


class SlotEngine:
    """``num_slots`` KV-cache lanes + host shadow cursors over the
    compiled slot-decode programs.

    Per slot the host shadows (numpy — admission/budget decisions only;
    the authoritative state lives on device):

    - ``occupied[s]`` — lane reserved (prefilling OR decoding);
    - ``decoding[s]`` — lane actively decoding (device ``active``);
    - ``pos[s]`` — filled cache positions; the budget guard is
      ``pos + K <= max_len``;
    - ``counts[s]`` — tokens emitted so far;
    - ``budget[s]`` — the request's ``max_new`` (feeds the block-size
      pick ``K = min(block, min(budget - counts))``).
    """

    def __init__(self, module, params, *, num_slots: int = 4,
                 prefill_pad: Optional[int] = None,
                 decode_block: Optional[int] = None,
                 paged: bool = False, kv_block: int = 16,
                 kv_blocks: Optional[int] = None, kv_int8: bool = False,
                 prefix_cache_blocks: int = 0,
                 mesh=None,
                 spec_draft=None, spec_k: int = 4,
                 attn_kernel: Optional[str] = None,
                 prefill_kernel: bool = False,
                 sample_kernel: bool = False,
                 fused_rope: bool = False,
                 lora_kernel: bool = False,
                 adapters: bool = False, adapter_blocks: int = 8,
                 adapter_rank: int = 8,
                 constrain=None, logprobs: int = 0,
                 auto: bool = False):
        #: measurement-driven planning (tpudist.plan): ``auto=True``
        #: scores the legal configs against the frozen bench artifacts
        #: and fills every performance knob the caller left at its
        #: default (an explicitly-pinned knob always wins).  The chosen
        #: plan lands here; InferenceServer.start() stamps it into
        #: telemetry as ``plan_selected``.
        self.plan = None
        if auto:
            from tpudist.plan import resolve_engine_auto

            chosen, self.plan = resolve_engine_auto(
                module, params, n_devices=_mesh_devices(mesh),
                num_slots=num_slots,
                spec_draft_layers=(spec_draft if isinstance(spec_draft, int)
                                   else None),
                user_kwargs=dict(
                    decode_block=decode_block, paged=paged,
                    kv_block=kv_block, kv_int8=kv_int8,
                    attn_kernel=attn_kernel,
                    prefill_kernel=prefill_kernel,
                    sample_kernel=sample_kernel, fused_rope=fused_rope,
                    spec_k=spec_k))
            decode_block = chosen.get("decode_block", decode_block)
            paged = chosen.get("paged", paged)
            kv_block = chosen.get("kv_block", kv_block)
            kv_int8 = chosen.get("kv_int8", kv_int8)
            attn_kernel = chosen.get("attn_kernel", attn_kernel)
            prefill_kernel = chosen.get("prefill_kernel", prefill_kernel)
            sample_kernel = chosen.get("sample_kernel", sample_kernel)
            fused_rope = chosen.get("fused_rope", fused_rope)
            spec_k = chosen.get("spec_k", spec_k)
        if prefill_pad is None:
            prefill_pad = min(int(module.max_len), 64)
        # -- decode attention path: "gather" (dense view per dispatch)
        # or "paged" (the Pallas paged-attention kernel — block table
        # walked in-kernel, bytes/token ∝ live KV).  Like every other
        # engine parameter this is env-free; the TPUDIST_SERVE_ATTN_KERNEL
        # knob is parsed ONCE by ServeConfig.from_env.
        if attn_kernel is None:
            attn_kernel = "gather"
        if attn_kernel not in ("gather", "paged"):
            raise ValueError(
                f"attn_kernel must be 'gather' or 'paged', got "
                f"{attn_kernel!r} (TPUDIST_SERVE_ATTN_KERNEL)")
        if attn_kernel == "paged" and not paged:
            raise ValueError(
                "attn_kernel='paged' walks the paged block pool in-kernel "
                "— it requires paged=True (TPUDIST_SERVE_PAGED)")
        self.attn_kernel = attn_kernel
        # -- the kernel family's other members (tpudist.ops): prefill
        # through the paged-prefill kernel (in-kernel KV block writes),
        # the fused sampling tail, fused RoPE+QKV, and the in-kernel
        # LoRA gather-matmul.  Env-free here like attn_kernel; the
        # TPUDIST_SERVE_{PREFILL_KERNEL,SAMPLE_KERNEL,FUSED_ROPE,
        # LORA_KERNEL} knobs parse once in ServeConfig.from_env.
        if prefill_kernel and not paged:
            raise ValueError(
                "prefill_kernel=True is the paged-prefill kernel — it "
                "requires paged=True (TPUDIST_SERVE_PREFILL_KERNEL)")
        if fused_rope and attn_kernel != "paged" and not prefill_kernel:
            raise ValueError(
                "fused_rope=True fuses RoPE+QKV on the kernel arms only "
                "— enable attn_kernel='paged' and/or prefill_kernel=True "
                "(TPUDIST_SERVE_FUSED_ROPE)")
        if lora_kernel and not adapters:
            raise ValueError(
                "lora_kernel=True is the in-kernel adapter gather-matmul "
                "— it requires adapters=True (TPUDIST_SERVE_LORA_KERNEL)")
        if lora_kernel and attn_kernel != "paged" and not prefill_kernel:
            raise ValueError(
                "lora_kernel=True rides the slot-batched kernel programs "
                "only — enable attn_kernel='paged' and/or "
                "prefill_kernel=True (TPUDIST_SERVE_LORA_KERNEL)")
        self.prefill_kernel = bool(prefill_kernel)
        self.sample_kernel = bool(sample_kernel)
        self.fused_rope = bool(fused_rope)
        self.lora_kernel = bool(lora_kernel)
        self.module = module
        self.max_len = int(module.max_len)
        # -- per-tenant adapters (tpudist.models.lora + serve.adapters):
        # a paged rank-r LoRA factor pool next to the KV pool, per-slot
        # adapter ids in SlotState, host registry deciding which block
        # holds which named adapter.  Env-free like every engine knob
        # (TPUDIST_SERVE_ADAPTERS* parse once in ServeConfig.from_env).
        self.adapters = None
        self.apool = None
        self.adapter_cfg = None
        acfg = None
        if adapters:
            from tpudist.models import lora as _lora
            from tpudist.serve.adapters import AdapterRegistry

            if getattr(module, "n_experts", 0) > 0 \
                    or getattr(module, "mlp_fn", None) is not None:
                raise ValueError(
                    "adapters wrap the plain qkv/wi/wo Dense path — they "
                    "cannot compose with an MoE FFN or an injected mlp_fn")
            acfg = _lora.AdapterPoolConfig(
                num_blocks=max(1, int(adapter_blocks)),
                rank=max(1, int(adapter_rank)))
            self.adapter_cfg = acfg
            self.apool = _lora.init_adapter_pool(module, acfg)
            self._lora = _lora
            self.adapters = AdapterRegistry(acfg.num_blocks)
            #: host shadow: slot → bound ``(name, block_id)`` (None =
            #: base-only).  The NAME is what export_slot stamps into
            #: handoff/host-tier packages (ids are pool-local); the BID
            #: pins the exact factor GENERATION — a deferred-unload
            #: reload retires the old block, and this lane keeps
            #: decoding (and releasing) the one it bound
            self.slot_adapter: List[Optional[Tuple[str, int]]] = \
                [None] * num_slots
        # -- structured output (tpudist.constrain): a dense grammar
        # table pool [G+1, S_max, V] next to the adapter pool, per-slot
        # grammar block ids + automaton states in SlotState, host
        # registry deciding which compiled grammar occupies which block
        # (the adapter-pool discipline of PR 15 applied to grammars).
        # Block G is the sentinel identity row unconstrained lanes
        # index — every token allowed, next state 0 — so ONE program
        # serves mixed constrained/unconstrained batches.
        self.constrain_cfg = constrain
        self.grammars = None
        self.gpool = None
        #: host shadow: slot → bound ``(TokenGrammar, block_id)``
        #: (None = unconstrained).  The grammar object carries the
        #: serializable SOURCE export_slot stamps into handoff/park
        #: packages (block ids are pool-local, like adapter ids) and
        #: the host shadow automaton the server walks over delivered
        #: tokens.
        self.slot_grammar: List[Optional[Tuple[object, int]]] = \
            [None] * num_slots
        if constrain is not None:
            import jax.numpy as _jnp

            from tpudist.constrain import GrammarRegistry

            V = int(module.vocab)
            if len(constrain.vocab) != V:
                raise ValueError(
                    f"constrain vocab has {len(constrain.vocab)} entries, "
                    f"model vocab is {V}")
            self.grammars = GrammarRegistry(constrain.num_blocks)
            G, S = constrain.num_blocks, constrain.max_states
            # every block starts as the identity (all-True, next 0): a
            # never-written block decodes unconstrained instead of
            # sampling an all--inf row, and block G stays the sentinel
            # forever (binds only ever write blocks < G)
            self._gallow = _jnp.ones((G + 1, S, V), bool)
            self._gnext = _jnp.zeros((G + 1, S, V), _jnp.int32)
            self.gpool = (self._gallow, self._gnext)
        #: top-n logprobs width the decode/verify programs return per
        #: emitted token (0 = off).  An engine-wide compile-time width:
        #: per-request ``logprobs=n`` asks are a host-side slice of
        #: this n, so request churn never recompiles.
        self.n_lp = max(0, int(logprobs))
        if self.n_lp > int(module.vocab):
            raise ValueError(
                f"logprobs {self.n_lp} > vocab {int(module.vocab)}")
        # -- SPMD serving mesh (tpudist.serve.spmd): params + KV storage
        # get NamedShardings, SlotState/tables stay replicated, and the
        # SAME four programs run partitioned — shardings change, code
        # does not (the eager-SPMD consistency contract).
        self.mesh = None
        self.tp_overlap = "off"
        self._mesh_cfg = None
        cache_constraint = None
        if mesh is not None:
            from tpudist.serve import spmd

            cfg = (mesh if isinstance(mesh, spmd.ServeMeshConfig)
                   else spmd.ServeMeshConfig(shape=str(mesh)))
            self._mesh_cfg = cfg
            self.mesh = spmd.build_serve_mesh(cfg)
        if self.mesh is not None:
            from tpudist.serve import spmd

            self.tp_overlap = spmd.resolve_serve_overlap(self._mesh_cfg)
            overlap_on = self.tp_overlap != "off"
            if overlap_on and adapters:
                # the fused overlapped MLP hides the wi/wo seam the
                # adapter delta wraps — adapters keep the plain
                # column-sharded path (same rule as the MoE FFN below)
                overlap_on = False
                self.tp_overlap = "off"
            if overlap_on and getattr(module, "n_experts", 0) == 0:
                mlp_fn = spmd.serve_overlap_mlp_fn(
                    self.mesh, mode=self.tp_overlap)
                if mlp_fn is not None:
                    module = module.clone(mlp_fn=mlp_fn)
                    self.module = module
                else:
                    overlap_on = False
                    self.tp_overlap = "off"
            elif overlap_on:
                # MoE FFN owns the mlp seam; TP-shard the rest only
                overlap_on = False
                self.tp_overlap = "off"
            self._param_sharding = spmd.serve_param_sharding(
                self.mesh, params, overlap=overlap_on)
            self._spmd_param_stats = spmd.sharded_param_bytes(
                params, self._param_sharding)
            import jax as _jax

            params = _jax.device_put(params, self._param_sharding)

            def cache_constraint(tree):
                import jax as _j

                spec = (spmd.serve_paged_sharding(self.mesh, tree)
                        if hasattr(tree, "pool_k")
                        else spmd.serve_cache_sharding(self.mesh, tree))
                return _j.lax.with_sharding_constraint(tree, spec)

            def state_constraint(tree):
                import jax as _j

                return _j.lax.with_sharding_constraint(
                    tree, spmd.serve_state_sharding(self.mesh, tree))
        else:
            state_constraint = None
        self._cache_constraint = cache_constraint
        # -- speculative decoding (ROADMAP item 5): a small draft model
        # proposes K tokens per slot, the target verifies all of them in
        # ONE batched multi-token window pass — fewer target HBM sweeps
        # per emitted token, the only decode lever left past the
        # measured roofline.  ``spec_draft``: an int ties the target's
        # first N layers (zero extra params, tied_draft); a
        # ``(module, params)`` pair loads a separately-built draft
        # (e.g. serve_bench's distilled variant).
        self.spec = spec_draft is not None
        self.spec_k = max(1, int(spec_k))
        spec_pair = None
        if self.spec:
            from tpudist.models.generate import tied_draft

            if isinstance(spec_draft, int):
                spec_pair = tied_draft(module, params, spec_draft)
            else:
                d_mod, d_par = spec_draft
                if self.mesh is not None:
                    from tpudist.serve import spmd
                    import jax as _jax

                    d_par = _jax.device_put(
                        d_par, spmd.serve_spec_param_sharding(
                            self.mesh, d_par))
                spec_pair = (d_mod, d_par)
        #: the SERVING draft (module, params).  The params are runtime
        #: DATA to the compiled draft programs (passed as their last
        #: argument every dispatch), so :meth:`swap_draft` can replace
        #: them with a same-geometry distilled candidate without
        #: touching a single compiled program.
        self.draft_module = spec_pair[0] if spec_pair is not None else None
        self.draft_params = spec_pair[1] if spec_pair is not None else None
        #: completed hot-swaps (spec_stats surfaces it; the telemetry
        #: counter tpudist_draft_swaps_total is fed off the draft_swap
        #: event, not this shadow)
        self.draft_swaps = 0
        #: per-adapter acceptance accounting accumulated on the host
        #: from numbers spec_decode_block already syncs: adapter name →
        #: [accepted, drafted] (only lanes BOUND to a named adapter
        #: contribute; the per-adapter twin of the engine-wide
        #: n_spec_accepted / n_spec_drafted counters)
        self.spec_adapter_counts: Dict[str, List[int]] = {}
        self.alloc: Optional[BlockAllocator] = None
        if paged:
            kv_block = min(int(kv_block), self.max_len)
            if self.max_len % kv_block:
                raise ValueError(
                    f"kv_block {kv_block} must divide max_len {self.max_len}")
            if kv_blocks is None:
                # dense-equivalent capacity: the pool holds exactly what
                # the dense arena pinned; the win is raising num_slots at
                # this same byte budget
                kv_blocks = num_slots * (self.max_len // kv_block)
            self.paged_cfg: Optional[PagedKVConfig] = PagedKVConfig(
                num_blocks=int(kv_blocks), block_size=kv_block,
                quantized=bool(kv_int8))
            self.fns = make_slot_decode(module, params, num_slots,
                                        prefill_pad, paged=self.paged_cfg,
                                        cache_constraint=cache_constraint,
                                        state_constraint=state_constraint,
                                        spec=spec_pair,
                                        draft_constraint=cache_constraint,
                                        attn_kernel=attn_kernel,
                                        prefill_kernel=prefill_kernel,
                                        sample_kernel=sample_kernel,
                                        fused_rope=fused_rope,
                                        lora_kernel=lora_kernel,
                                        adapters=acfg,
                                        constrain=constrain,
                                        logprobs=self.n_lp)
            self.alloc = BlockAllocator(
                self.paged_cfg.num_blocks, kv_block, self.max_len,
                prefix_cache_blocks=prefix_cache_blocks)
        else:
            self.paged_cfg = None
            self.fns = make_slot_decode(module, params, num_slots,
                                        prefill_pad,
                                        cache_constraint=cache_constraint,
                                        state_constraint=state_constraint,
                                        spec=spec_pair,
                                        draft_constraint=cache_constraint,
                                        sample_kernel=sample_kernel,
                                        adapters=acfg,
                                        constrain=constrain,
                                        logprobs=self.n_lp)
        self.num_slots = num_slots
        self.prefill_pad = prefill_pad
        self.block = max(1, int(decode_block if decode_block else 8))
        self.state = self.fns.init_state()
        self.cache = self.fns.init_slots()
        self.dcache = self.fns.init_draft() if self.spec else None
        if self.mesh is not None:
            # place the fresh state/cache on their serving layout ONCE;
            # the programs' output constraint keeps it there through
            # every donated iteration
            import jax as _jax

            from tpudist.serve import spmd

            self.state = _jax.device_put(
                self.state, spmd.serve_state_sharding(self.mesh, self.state))
            self.cache = _jax.device_put(
                self.cache,
                spmd.serve_paged_sharding(self.mesh, self.cache)
                if self.alloc is not None
                else spmd.serve_cache_sharding(self.mesh, self.cache))
            if self.dcache is not None:
                self.dcache = _jax.device_put(
                    self.dcache,
                    spmd.serve_paged_sharding(self.mesh, self.dcache)
                    if self.alloc is not None
                    else spmd.serve_cache_sharding(self.mesh, self.dcache))
            if self.apool is not None:
                # factor pool sharded over `model` where its output
                # dims divide, else replicated — output byte-identical
                # at every mesh shape (serve_adapter_sharding's rule)
                self.apool = _jax.device_put(
                    self.apool,
                    spmd.serve_adapter_sharding(self.mesh, self.apool))
        self.occupied = np.zeros(num_slots, bool)
        self.decoding = np.zeros(num_slots, bool)
        self.pos = np.zeros(num_slots, np.int32)
        self.counts = np.zeros(num_slots, np.int32)
        self.budget = np.zeros(num_slots, np.int32)
        #: per-slot speculative opt (host shadow of the mask the spec
        #: programs take as data; True for every tenant unless its
        #: request opted out — a False lane rides the same programs with
        #: acceptance forced to zero)
        self.spec_on = np.ones(num_slots, bool)
        #: slot → (full prompt, next chunk offset) for prompts longer
        #: than one prefill chunk (the host-side half of chunked prefill)
        self._prefill_rest: Dict[int, Tuple[np.ndarray, int]] = {}
        #: high-water mark of concurrently occupied lanes — the paged
        #: capacity claim (N× slots at equal pool bytes) is only real if
        #: the lanes actually fill under load; serve_bench records this
        self.peak_occupied = 0
        # decode hot-path counters (the bench's dispatch/sync overhead
        # split reads these through ``decode_stats``).  Spec blocks fold
        # into these too (their draft+verify time is the device-busy
        # cost per emitted token), and additionally into the finer
        # ``spec_stats`` split below.
        self.n_decode_blocks = 0
        self.n_decode_tokens = 0
        #: sequential TARGET passes dispatched: a plain block of K fused
        #: steps counts K (one full-model pass per emitted token — the
        #: single-model latency floor), a speculative block counts 1
        #: (ONE batched verify pass emits up to K+1 tokens per lane —
        #: the passes-per-token lever itself)
        self.n_decode_steps = 0
        self.t_decode_dispatch_s = 0.0
        self.t_decode_sync_s = 0.0
        #: cumulative KV bytes the decode attention streamed, per the
        #: ACTIVE path's honest model (see _decode_kv_read_bytes) — the
        #: per-rung bytes/token column in serve_bench reads the delta
        self.kv_read_bytes_total = 0
        #: honest prefill traffic per path (_prefill_kv_bytes; the kv
        #: report's prefill rows): the kernel path charges prefix blocks
        #: walked + blocks its chunks cover, the gather path the dense
        #: lane views it materializes and the static commit span
        self.prefill_read_bytes_total = 0
        self.prefill_write_bytes_total = 0
        # speculative-decode counters (spec_stats)
        self.n_spec_blocks = 0
        self.n_spec_lane_passes = 0  # Σ active lanes over spec blocks
        self.n_spec_tokens = 0
        self.n_spec_accepted = 0
        self.n_spec_drafted = 0
        self.n_spec_rollbacks = 0
        self.t_spec_draft_s = 0.0
        self.t_spec_verify_s = 0.0
        self.t_spec_sync_s = 0.0
        # per-decode-block telemetry gauges must not rebuild the full
        # kv_stats() dict on the hot path: precompute the constants
        if self.fns.paged is not None:
            self._block_bytes = self.fns.paged.block_bytes
            self._dense_resident_bytes = 0
        else:
            self._block_bytes = 0
            self._dense_resident_bytes = int(
                num_slots * self.max_len * self._bytes_per_pos())

    # -- inspection ---------------------------------------------------------

    def free_slots(self) -> List[int]:
        return [s for s in range(self.num_slots) if not self.occupied[s]]

    def prefilling_slots(self) -> List[int]:
        """Slots holding a partially-prefilled prompt (occupied, not yet
        decoding)."""
        return sorted(self._prefill_rest)

    @property
    def num_active(self) -> int:
        """Decoding lanes (the device-busy count decode blocks run over)."""
        return int(self.decoding.sum())

    @property
    def num_occupied(self) -> int:
        return int(self.occupied.sum())

    @property
    def occupancy(self) -> float:
        """Busy fraction of the decode batch — the gauge the telemetry
        report aggregates (an engine decoding one request at 8 slots
        wastes 7/8 of every block's HBM sweep)."""
        return self.num_active / self.num_slots

    def compile_counts(self) -> Dict[str, int]:
        """Jit-cache sizes of the compiled primitives — the "no
        recompilation under load" oracle the slow-lane test pins down
        (``decode_block`` alone grows one entry per power-of-two block
        bucket actually used)."""
        out = {}
        names = ["insert_batch", "prefill_extend", "decode_block",
                 "evict", "export_lane", "import_lane"]
        if self.spec:
            names += ["draft_prefill", "draft_extend", "draft_evict",
                      "draft_propose", "spec_verify", "draft_track"]
        for name in names:
            fn = getattr(self.fns, name)
            size = getattr(fn, "_cache_size", None)
            out[name] = int(size()) if callable(size) else -1
        return out

    def decode_stats(self) -> Dict[str, float]:
        """Cumulative decode hot-path counters: blocks dispatched, tokens
        emitted, host time spent dispatching vs blocked on the D2H token
        fetch — the wall-TPOT vs device-busy-TPOT split in serve_bench."""
        return {
            "blocks": self.n_decode_blocks,
            "tokens": self.n_decode_tokens,
            "steps": self.n_decode_steps,
            "dispatch_s": self.t_decode_dispatch_s,
            "sync_s": self.t_decode_sync_s,
            "kv_read_bytes": self.kv_read_bytes_total,
        }

    def spec_stats(self) -> Dict[str, float]:
        """Speculative-decode counters: blocks, emitted tokens, drafted
        vs accepted (→ ``accepted_per_pass`` = tokens/blocks, acceptance
        rate = accepted/drafted), rollback events (a verify that
        rejected at least one drafted token), and the draft/verify/fetch
        wall split the telemetry report aggregates."""
        out = {
            "enabled": self.spec,
            "blocks": self.n_spec_blocks,
            "lane_passes": self.n_spec_lane_passes,
            "tokens": self.n_spec_tokens,
            "accepted": self.n_spec_accepted,
            "drafted": self.n_spec_drafted,
            "rollbacks": self.n_spec_rollbacks,
            # emitted tokens PER LANE per verify pass (1.0 = no better
            # than plain decode) — normalized by lane passes, not
            # blocks, so batch occupancy cannot masquerade as
            # acceptance (the telemetry report's per-lane metric)
            "accepted_per_pass": (
                self.n_spec_tokens / self.n_spec_lane_passes
                if self.n_spec_lane_passes else None),
            "acceptance_rate": (self.n_spec_accepted / self.n_spec_drafted
                                if self.n_spec_drafted else None),
            "draft_s": self.t_spec_draft_s,
            "verify_s": self.t_spec_verify_s,
            "sync_s": self.t_spec_sync_s,
            "spec_k": self.spec_k if self.spec else None,
            "draft_swaps": self.draft_swaps,
        }
        if self.spec and self.spec_adapter_counts:
            out["by_adapter"] = {
                name: {"accepted": a, "drafted": d,
                       "acceptance_rate": (a / d if d else None)}
                for name, (a, d) in sorted(self.spec_adapter_counts.items())}
        if self.spec:
            # draft KV residency: the "smaller pool" claim, quantified
            if self.fns.draft_paged is not None:
                out["draft_pool_bytes"] = self.fns.draft_paged.pool_bytes
            else:
                total = 0
                for val in self.dcache.values():
                    if isinstance(val, dict) and "k" in val and "v" in val:
                        total += 2 * val["k"].size * val["k"].dtype.itemsize
                out["draft_pool_bytes"] = int(total)
        return out

    def _bytes_per_pos(self) -> float:
        """Resident KV bytes per cached position.  Paged: pool bytes /
        pool positions (int8 + scales when quantized).  Dense: summed
        K+V row bytes over the slot cache's layers."""
        if self.fns.paged is not None:
            return self.fns.paged.bytes_per_pos
        total = 0
        for val in self.cache.values():
            if isinstance(val, dict) and "k" in val and "v" in val:
                # leaf [num_slots, 1, n_kv, max_len, dh]
                _, _, n_kv, _, dh = val["k"].shape
                total += 2 * n_kv * dh * val["k"].dtype.itemsize
        return float(total)

    def _decode_kv_read_bytes(self, pos0: np.ndarray, passes: int,
                              window_per_lane: int) -> int:
        """KV bytes the decode attention streams for one dispatch, per
        the ACTIVE path — the honest accounting the serving report's
        ``kv`` section quotes (the old formula charged live-KV on every
        path, under-charging the gather/dense arms whose dense view
        spans ``max_len`` regardless of cursors):

        - **paged kernel**: each of ``passes`` attention passes walks
          each lane's LIVE blocks (whole blocks — the DMA unit) at the
          dispatch-start cursor ``pos0``, plus ``window`` window-buffer
          positions per lane per pass — bytes/token ∝ live KV;
        - **gather / dense**: every pass sweeps the full
          ``[num_slots, max_len]`` arena (the gathered view or the
          dense arena — inactive lanes compute too, fixed shapes), so
          bytes scale with pool geometry, which is exactly what the
          kernel exists to fix.

        ``passes`` = full attention sweeps (``k`` for a plain scan, 1
        for the fused verify); ``window_per_lane`` = total window-buffer
        positions one lane reads across the dispatch (``k(k+1)/2`` for
        the scan's growing window, ``k+1`` for the verify).  Window
        positions are charged at the COMPUTE dtype's per-position size
        — the buffer is unquantized even on an int8 pool.
        """
        bpp = self._bytes_per_pos()
        if self.attn_kernel == "paged":
            pg = self.fns.paged
            bs = self.paged_cfg.block_size
            live = ((pos0.astype(np.int64) + bs - 1) // bs) * bs
            window_bpp = (2 * len(pg.layers) * pg.n_kv * pg.dh
                          * np.dtype(pg.compute_dtype).itemsize)
            return int(passes * int(live.sum()) * bpp
                       + len(pos0) * window_per_lane * window_bpp)
        return int(passes * self.num_slots * self.max_len * bpp)

    def _prefill_kv_bytes(self, pos0: np.ndarray, clens: np.ndarray,
                          gather_lanes: int) -> Tuple[int, int]:
        """``(read, write)`` KV bytes one prefill dispatch streams, per
        the ACTIVE path — the prefill twin of :meth:`_decode_kv_read_bytes`
        (the serving report's ``kv`` prefill rows):

        - **kernel** (``prefill_kernel``): each lane walks its reused
          pool PREFIX in whole blocks (``ceil(pos0/bs)·bs`` positions —
          every lane of the batched program walks, including the
          bystander lanes of a one-hot chunk extend) and WRITES only
          the blocks its chunk ``[pos0, pos0+clen)`` actually covers —
          reads ∝ reused prefix, writes ∝ chunk;
        - **gather / dense**: the vmapped lane program materializes a
          ``max_len`` dense view per lane and the teacher-force scan
          re-streams it once per padded step (``(1 + pad) · max_len``
          positions per lane, all ``gather_lanes`` lanes — fixed
          shapes, inactive lanes compute too), and the commit scatters
          the full static ``_touch_count(pad)`` span (dense engine:
          the whole lane) regardless of the chunk length.
        """
        bpp = self._bytes_per_pos()
        pos0 = np.asarray(pos0, np.int64)
        clens = np.asarray(clens, np.int64)
        live = clens > 0
        if self.prefill_kernel:
            bs = self.paged_cfg.block_size
            pref = ((pos0 + bs - 1) // bs) * bs
            touched = np.where(
                live, (pos0 + clens - 1) // bs - pos0 // bs + 1, 0)
            return (int(pref.sum() * bpp),
                    int(touched.sum() * bs * bpp))
        pad = self.prefill_pad
        read = gather_lanes * (1 + pad) * self.max_len * bpp
        if self.alloc is not None:
            bs = self.paged_cfg.block_size
            T = min(self.max_len // bs, (max(1, pad) - 1) // bs + 2)
            write = int(live.sum()) * T * bs * bpp
        else:
            write = int(live.sum()) * self.max_len * bpp
        return int(read), int(write)

    def kv_stats(self) -> Dict[str, object]:
        """KV residency accounting — the serving report's capacity
        story.  ``bytes_resident`` is what actually pins HBM: the whole
        arena for the dense engine (every slot owns ``max_len`` positions
        whether it uses them or not), tenant-or-cache-held blocks for the
        paged engine.  ``bytes_per_pos`` is the bytes-per-token lever the
        int8 path halves-or-better."""
        bpp = self._bytes_per_pos()
        if self.alloc is None:
            total = self.num_slots * self.max_len * bpp
            return {
                "paged": False, "attn_kernel": self.attn_kernel,
                "prefill_kernel": self.prefill_kernel,
                "sample_kernel": self.sample_kernel,
                "fused_rope": self.fused_rope,
                "lora_kernel": self.lora_kernel,
                "prefill_read_bytes": self.prefill_read_bytes_total,
                "prefill_write_bytes": self.prefill_write_bytes_total,
                "quantized": False,
                "block_size": None, "blocks_total": None,
                "blocks_in_use": None, "blocks_free": None,
                "cached_blocks": None, "block_occupancy": None,
                "pool_bytes": int(total),
                "bytes_resident": int(total),  # dense pins it all
                "bytes_per_pos": bpp,
                "peak_occupied_slots": self.peak_occupied,
            }
        pg, al = self.fns.paged, self.alloc
        return {
            "paged": True, "attn_kernel": self.attn_kernel,
            "prefill_kernel": self.prefill_kernel,
            "sample_kernel": self.sample_kernel,
            "fused_rope": self.fused_rope,
            "lora_kernel": self.lora_kernel,
            "prefill_read_bytes": self.prefill_read_bytes_total,
            "prefill_write_bytes": self.prefill_write_bytes_total,
            "quantized": self.paged_cfg.quantized,
            "block_size": self.paged_cfg.block_size,
            "blocks_total": al.num_blocks,
            "blocks_in_use": al.blocks_in_use,
            "blocks_free": al.free_blocks,
            "cached_blocks": al.cached_blocks,
            "block_occupancy": al.blocks_in_use / al.num_blocks,
            "pool_bytes": pg.pool_bytes,
            "bytes_resident": al.blocks_in_use * pg.block_bytes,
            "bytes_per_pos": bpp,
            "peak_occupied_slots": self.peak_occupied,
            "prefix_hit_blocks": al.prefix_hit_blocks,
            "prefix_miss_blocks": al.prefix_miss_blocks,
            "prefix_hit_tokens": al.prefix_hit_tokens,
            "blocks_admitted_total": al.blocks_admitted_total,
            "blocks_released_total": al.blocks_released_total,
        }

    def kv_gauges(self) -> Tuple[Optional[float], int]:
        """The two per-decode-block telemetry gauges ``(block_occupancy,
        bytes_resident)`` — cheap enough for the decode hot loop (two
        counter reads; :meth:`kv_stats` builds the full dict and walks
        the cache pytree, which has no place per dispatch)."""
        if self.alloc is None:
            return None, self._dense_resident_bytes
        return (self.alloc.blocks_in_use / self.alloc.num_blocks,
                self.alloc.blocks_in_use * self._block_bytes)

    def spmd_stats(self) -> Dict[str, object]:
        """The serving-mesh story for reports/tests: mesh geometry, the
        TP-overlap routing mode, and where the param bytes live.  All
        ``None``/trivial on a single-device engine."""
        if self.mesh is None:
            return {"mesh": None, "tp_overlap": "off"}
        d, m = self._mesh_cfg.dims
        return {"mesh": {"data": d, "model": m},
                "n_devices": self._mesh_cfg.n_devices,
                "tp_overlap": self.tp_overlap,
                **self._spmd_param_stats}

    # -- speculative draft hot-swap (tpudist.distill) -----------------------

    def swap_draft(self, new_params) -> Dict[str, object]:
        """Replace the serving draft's parameters with a same-geometry
        candidate — a PURE data update: the draft programs take their
        params as a runtime argument, so nothing recompiles and every
        compile pin holds (:meth:`compile_counts` is flat across swaps).

        The geometry invariant is ASSERTED, not assumed: tree structure,
        leaf shapes, and dtypes must match the serving copy exactly (the
        jit cache key — a mismatch would silently compile a second
        program set).  Each leaf is placed on the serving copy's exact
        sharding, then every OCCUPIED lane's draft context is re-armed
        via the existing ``draft_arm`` program (cursor at the lane's
        target position over cold context — the import_slot precedent:
        a cold draft context can only lower acceptance, never
        correctness, and it warms with every token decoded from here).
        Greedy output is byte-identical across swaps because the target
        verify is the oracle; acceptance only moves speed.

        Single-threaded by the engine contract — callers on another
        thread go through ``InferenceServer.swap_draft``, which lands
        the swap between decode blocks."""
        if not self.spec:
            raise RuntimeError("engine built without spec_draft")
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        ref = self.draft_params
        ref_leaves, ref_def = jax.tree.flatten(ref)
        new_leaves, new_def = jax.tree.flatten(new_params)
        if new_def != ref_def:
            raise ValueError(
                "draft swap geometry mismatch: candidate param tree "
                f"structure != serving draft's ({new_def} vs {ref_def})")
        for r, n in zip(ref_leaves, new_leaves):
            if tuple(n.shape) != tuple(r.shape) \
                    or np.dtype(n.dtype) != np.dtype(r.dtype):
                raise ValueError(
                    "draft swap geometry mismatch: leaf "
                    f"{tuple(n.shape)}/{np.dtype(n.dtype)} != serving "
                    f"{tuple(r.shape)}/{np.dtype(r.dtype)}")
        # place every leaf EXACTLY like the serving copy: the jit cache
        # keys on sharding AND committedness, so a candidate carrying
        # e.g. the trainer mesh's NamedSharding — or merely a COMMITTED
        # copy where the original was uncommitted — would silently
        # recompile every draft program on first use (and committedness
        # is contagious through jit outputs: the lane state coming back
        # from those dispatches would recompile insert/evict/verify
        # too).  Committed ref → device_put pins the same placement;
        # uncommitted ref → host round-trip lands an uncommitted copy
        # on the default device, same as the original.
        new_params = jax.tree.map(
            lambda r, n: (jax.device_put(n, r.sharding)
                          if getattr(r, "committed", True)
                          else jnp.asarray(np.asarray(n))),
            ref, new_params)
        self.draft_params = new_params
        # re-warm: cursor re-arm for every occupied lane (paged lanes
        # keep their table row — ONE D2H table fetch per swap, off the
        # per-block hot path)
        rearmed = 0
        table_h = (np.asarray(self.dcache.table)
                   if self.alloc is not None else None)
        for slot in range(self.num_slots):
            if not self.occupied[slot]:
                continue
            pos = int(self.pos[slot])
            if self.alloc is not None:
                self.dcache = self.fns.draft_arm(
                    self.dcache, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(table_h[slot]),
                    jnp.asarray(pos, jnp.int32))
            else:
                self.dcache = self.fns.draft_arm(
                    self.dcache, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(pos, jnp.int32))
            rearmed += 1
        self.draft_swaps += 1
        return {"swapped": True, "lanes_rearmed": rearmed,
                "swap_s": time.perf_counter() - t0,
                "draft_swaps": self.draft_swaps}

    # -- per-tenant adapters ------------------------------------------------

    def has_adapter(self, name: Optional[str]) -> bool:
        """Would a NEW request naming ``name`` bind right now?  (None =
        base-only, always true on any engine; a named adapter needs an
        adapter pool holding it and not marked for unload.)"""
        if name is None:
            return True
        return self.adapters is not None and self.adapters.has(name)

    def load_adapter(self, name: str, factors) -> Dict[str, object]:
        """Load ``factors`` (:func:`tpudist.models.lora.
        make_adapter_factors`-shaped dict) under ``name``: reserves a
        pool block (LRU-evicting a cold adapter if full — its block is
        zeroed first), writes the factor set, and returns ``{"block",
        "evicted", "resident"}`` for the caller's telemetry.  Thread-
        safe against the engine thread: the pool swap is one atomic
        rebind, and only NOT-in-use blocks are ever written."""
        if self.adapters is None:
            raise RuntimeError("engine built without adapters=True")
        self._lora.check_factors(self.module, self.adapter_cfg, factors)
        # two-phase load: the registry keeps the name PENDING (not
        # bindable) until the factors are actually in the device pool —
        # a racing admission must never gather a zeroed (or, after an
        # LRU evict, the victim's) block under the new name
        bid, evicted = self.adapters.load(name)
        pool = self.apool
        if evicted is not None:
            pool = self._lora.zero_block(pool, evicted[1])
        self.apool = self._lora.load_factors(pool, bid, factors)
        self.adapters.activate(name)
        return {"block": bid,
                "evicted": None if evicted is None else evicted[0],
                "resident": self.adapters.resident}

    def unload_adapter(self, name: str) -> Dict[str, object]:
        """Unload ``name``: frees (and zeroes) its block now when no
        lane holds it, else defers — new requests reject
        ``adapter_missing`` immediately, the block frees when the last
        bound lane evicts.  Returns ``{"freed", "resident"}``."""
        if self.adapters is None:
            raise RuntimeError("engine built without adapters=True")
        res = self.adapters.unload(name)
        if res is None:
            return {"freed": False, "resident": self.adapters.resident,
                    "known": False}
        freed_now, bid = res
        if freed_now:
            self.apool = self._lora.zero_block(self.apool, bid)
        return {"freed": freed_now, "resident": self.adapters.resident,
                "known": True}

    def adapter_stats(self) -> Dict[str, object]:
        """Adapter-pool accounting for reports/statusz: registry
        counters plus pool geometry/bytes (all trivial when off)."""
        if self.adapters is None:
            return {"enabled": False}
        return {
            "enabled": True,
            "rank": self.adapter_cfg.rank,
            "block_bytes": self._lora.adapter_block_bytes(
                self.module, self.adapter_cfg),
            "pool_bytes": self._lora.pool_bytes(self.apool),
            "slots_bound": sum(1 for a in self.slot_adapter
                               if a is not None),
            **self.adapters.stats(),
        }

    def _acquire_adapter(self, slot: int, name: Optional[str]) -> int:
        """Bind ``name`` to ``slot`` (refcount pin) and return its pool
        block id — the sentinel for a base-only lane.  Raises
        :class:`~tpudist.serve.adapters.AdapterMissingError` when the
        name is not resident (a raced unload, or a re-bind onto a pool
        that never loaded it)."""
        if name is None:
            return self._aid_sentinel()
        from tpudist.serve.adapters import AdapterMissingError

        if self.adapters is None:
            raise AdapterMissingError(name)
        bid = self.adapters.acquire(name)
        if bid is None:
            raise AdapterMissingError(name)
        self.slot_adapter[slot] = (name, bid)
        return bid

    def _release_adapter(self, slot: int) -> None:
        if self.adapters is None:
            return
        bound = self.slot_adapter[slot]
        if bound is None:
            return
        self.slot_adapter[slot] = None
        freed = self.adapters.release(*bound)
        if freed is not None:
            # a deferred unload / retired generation just completed:
            # zero the block before the free list hands it on
            self.apool = self._lora.zero_block(self.apool, freed)

    def _aid_sentinel(self) -> int:
        return (self.adapter_cfg.num_blocks
                if self.adapter_cfg is not None else 0)

    def _slot_aid(self, slot: int) -> int:
        """The pool block id bound to ``slot`` (sentinel = base-only) —
        the bid captured at acquire, so a reload retiring the name's
        current generation cannot redirect a live lane."""
        bound = (self.slot_adapter[slot] if self.adapters is not None
                 else None)
        return self._aid_sentinel() if bound is None else bound[1]

    # -- structured output (grammar pool) -----------------------------------

    def has_constrain(self) -> bool:
        """Would a NEW constrained request bind right now (pool-full
        deferral aside)?  False on an engine built without
        ``constrain=``, where admission rejects synchronously."""
        return self.grammars is not None

    def _gid_sentinel(self) -> int:
        return (self.constrain_cfg.num_blocks
                if self.constrain_cfg is not None else 0)

    def _g_tail(self) -> Tuple:
        """Trailing grammar-pool argument for the constrained program
        wrappers (empty when structured output is off — the traced
        signatures then match the pre-constrain programs exactly)."""
        return () if self.gpool is None else (self.gpool,)

    def _write_grammar_block(self, block: int, tg) -> None:
        """Write ``tg``'s dense tables into pool ``block`` (rows past
        ``n_states`` stay the identity — unreachable, but a defensive
        gather must never land on an all-masked row)."""
        import jax.numpy as jnp

        cfg = self.constrain_cfg
        S, V = cfg.max_states, len(cfg.vocab)
        if tg.n_states > S or tg.allowed.shape[1] != V:
            from tpudist.constrain import GrammarError

            raise GrammarError(
                f"grammar tables [{tg.n_states}, {tg.allowed.shape[1]}] "
                f"exceed the pool row [{S}, {V}] "
                "(TPUDIST_CONSTRAIN_STATES)")
        allow = np.ones((S, V), bool)
        nxt = np.zeros((S, V), np.int32)
        allow[:tg.n_states] = tg.allowed
        nxt[:tg.n_states] = tg.next_state
        self._gallow = self._gallow.at[block].set(jnp.asarray(allow))
        self._gnext = self._gnext.at[block].set(jnp.asarray(nxt))
        self.gpool = (self._gallow, self._gnext)

    def _acquire_grammar(self, slot: int, tg) -> int:
        """Bind compiled grammar ``tg`` to ``slot`` (refcount pin) and
        return its pool block id — the sentinel for an unconstrained
        lane.  A fresh bind writes the device tables before any lane
        can decode under the block.  Raises
        :class:`~tpudist.constrain.GrammarPoolFull` when every block is
        pinned (admission defers rather than errors)."""
        if tg is None:
            return self._gid_sentinel()
        if self.grammars is None:
            raise RuntimeError("engine built without constrain=")
        block, fresh = self.grammars.bind(tg)
        if fresh:
            try:
                self._write_grammar_block(block, tg)
            except BaseException:
                self.grammars.release(block)
                raise
        self.slot_grammar[slot] = (tg, block)
        return block

    def _release_grammar(self, slot: int) -> None:
        if self.grammars is None:
            return
        bound = self.slot_grammar[slot]
        if bound is None:
            return
        self.slot_grammar[slot] = None
        self.grammars.release(bound[1])

    def constrain_stats(self) -> Dict[str, object]:
        """Grammar-pool accounting for reports/statusz: registry
        counters, compile-cache hit/miss, pool geometry/bytes (all
        trivial when off)."""
        if self.grammars is None:
            return {"enabled": False}
        from tpudist.constrain.grammar import compile_cache_stats

        cfg = self.constrain_cfg
        return {
            "enabled": True,
            "max_states": cfg.max_states,
            "pool_bytes": int(self._gallow.size
                              + self._gnext.size * 4),
            "slots_bound": sum(1 for g in self.slot_grammar
                               if g is not None),
            "compile_cache": compile_cache_stats(),
            **self.grammars.stats(),
        }

    # -- KV handoff (prefill/decode disaggregation) -------------------------

    def export_slot(self, slot: int) -> Dict[str, object]:
        """Package a DECODING slot for handoff to another engine
        (:mod:`tpudist.serve.disagg`): its KV lane, its SlotState row
        (``last_tok``/``counts``/``keys`` — the sampling stream
        continues byte-identically wherever the lane lands), and the
        host shadows the importing engine needs for budget accounting.
        Does not evict — the caller evicts once the handoff is safe."""
        if not self.decoding[slot]:
            raise ValueError(
                f"slot {slot} is not decoding (export happens after the "
                "prompt completes and the first token is sampled)")
        import jax.numpy as jnp

        lane, lane_state = self.fns.export_lane(
            self.state, self.cache, jnp.asarray(slot, jnp.int32))
        return {"paged": self.alloc is not None,
                "lane": lane, "state": lane_state,
                "pos": int(self.pos[slot]),
                "counts": int(self.counts[slot]),
                "budget": int(self.budget[slot]),
                # adapter binding travels by NAME: pool block ids are
                # local, so the importing engine re-binds in its own
                # registry (AdapterMissingError → "adapter_missing")
                "adapter": (self.slot_adapter[slot][0]
                            if self.adapters is not None
                            and self.slot_adapter[slot] is not None
                            else None),
                # grammar binding travels by SOURCE: pool block ids are
                # local, so the importing engine re-compiles (cache
                # hit) and re-binds; the row's gidx/gstate leaves ride
                # the state blob and gidx is overwritten at install
                "grammar": (
                    {"source": self.slot_grammar[slot][0].source,
                     "eos_id": int(self.slot_grammar[slot][0].eos_id)}
                    if self.grammars is not None
                    and self.slot_grammar[slot] is not None else None)}

    def can_import(self, package: Dict[str, object]) -> bool:
        """Would this engine's KV budget take the package right now
        (a free slot is checked by the caller)?  Paged engines reserve
        the remaining whole footprint; dense engines always fit."""
        if self.alloc is None:
            return True
        return self.alloc.can_admit(int(package["pos"]),
                                    int(package["budget"]), ())

    def import_slot(self, slot: int, package: Dict[str, object], *,
                    spec: Optional[bool] = None) -> None:
        """Install an exported lane into free ``slot`` and arm it for
        decode.  Paged: the remaining footprint is reserved on THIS
        pool (fresh blocks — handed-off lanes never share prefix blocks
        across pools; the prefill pool's prefix cache already saved the
        recompute) and the lane scatters into the new row in-graph.

        Speculative engine: handoff packages are UNCHANGED (the decode
        pool owns the draft), so the imported lane's draft cache starts
        COLD — cursor at ``pos`` over zeroed context.  The draft's
        missing prompt context can only lower acceptance, never
        correctness (the target verify is the oracle), and the draft
        warms with every token the lane decodes from here on.  ``spec``
        False opts the lane out of speculation entirely."""
        if self.occupied[slot]:
            raise ValueError(f"slot {slot} is occupied")
        if bool(package["paged"]) != (self.alloc is not None):
            raise ValueError("handoff package and engine disagree on "
                             "paged mode — pools must share KV geometry")
        pos, counts = int(package["pos"]), int(package["counts"])
        budget = int(package["budget"])
        self._install_lane(slot, package["lane"], package["state"], pos,
                           admit_span=(pos, budget),
                           adapter=package.get("adapter"),
                           grammar=package.get("grammar"))
        self.occupied[slot] = True
        self.decoding[slot] = True
        self.pos[slot] = pos
        self.counts[slot] = counts
        self.budget[slot] = budget
        self.spec_on[slot] = True if spec is None else bool(spec)
        self.peak_occupied = max(self.peak_occupied, self.num_occupied)

    def _install_lane(self, slot: int, lane, row_state, pos: int, *,
                      admit_span: Tuple[int, int],
                      adapter: Optional[str] = None,
                      grammar: Optional[Dict[str, object]] = None) -> None:
        """The ONE import dispatch both :meth:`import_slot` (handoff /
        preemption resume) and :meth:`resume_slot` (session resume)
        ride: paged engines reserve ``admit_span`` (admission args for
        the whole-footprint reservation) and build the sentinel-padded
        table row, then ``import_lane`` installs the lane + state row
        and ``draft_arm`` cold-starts the draft cursor at ``pos`` — a
        package-layout or draft-signature change lands in both resume
        flavors by construction.  ``adapter``: the package's adapter
        NAME — re-bound in THIS pool's registry before install (ids are
        pool-local; a name this pool never loaded raises
        ``AdapterMissingError`` BEFORE any state mutates)."""
        import numpy as _np

        import jax.numpy as jnp

        if adapter is not None or self.adapters is not None:
            # re-bind by name: the row's adapter_id leaf is the SOURCE
            # pool's id (or a foreign sentinel) — overwrite with ours
            aid = self._acquire_adapter(slot, adapter)
            row_state = row_state._replace(
                adapter_id=_np.asarray(aid, _np.int32))
        if grammar is not None and self.grammars is None:
            from tpudist.constrain import GrammarError

            self._release_adapter(slot)
            raise GrammarError(
                "imported lane carries a grammar but this engine was "
                "built without constrain= — pools must agree on "
                "structured-output support")
        if grammar is not None or self.grammars is not None:
            # re-bind by SOURCE: the row's gidx leaf is the source
            # pool's block id — recompile (a cache hit for any grammar
            # this process has seen) and overwrite with ours.  The
            # gstate leaf carries byte-faithfully; an unconstrained
            # import resets it alongside the sentinel gidx (a foreign
            # gstate could exceed THIS pool's state rows).
            gid = self._gid_sentinel()
            if grammar is not None:
                from tpudist.constrain import compile_grammar

                src = grammar["source"]
                try:
                    tg = compile_grammar(
                        regex=(src["src"] if src["kind"] == "regex"
                               else None),
                        json_schema=(src["src"]
                                     if src["kind"] == "json_schema"
                                     else None),
                        vocab=self.constrain_cfg.vocab,
                        eos_id=int(grammar["eos_id"]),
                        max_states=self.constrain_cfg.max_states)
                    gid = self._acquire_grammar(slot, tg)
                except BaseException:
                    # a failed bind must not leak the adapter pin
                    # acquired above
                    self._release_adapter(slot)
                    raise
                row_state = row_state._replace(
                    gidx=_np.asarray(gid, _np.int32))
            else:
                row_state = row_state._replace(
                    gidx=_np.asarray(gid, _np.int32),
                    gstate=_np.zeros((), _np.int32))
        if self.alloc is not None:
            row, _ = self.alloc.admit(slot, admit_span[0], admit_span[1],
                                      ())
            M = self.max_len // self.paged_cfg.block_size
            full = np.full(M, self.paged_cfg.num_blocks, np.int32)
            full[:len(row)] = row
            self.state, self.cache = self.fns.import_lane(
                self.state, self.cache, jnp.asarray(slot, jnp.int32),
                jnp.asarray(full), lane, row_state)
            if self.spec:
                self.dcache = self.fns.draft_arm(
                    self.dcache, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(full), jnp.asarray(pos, jnp.int32))
        else:
            self.state, self.cache = self.fns.import_lane(
                self.state, self.cache, jnp.asarray(slot, jnp.int32),
                lane, row_state)
            if self.spec:
                self.dcache = self.fns.draft_arm(
                    self.dcache, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(pos, jnp.int32))

    def resume_slot(self, slot: int, package: Dict[str, object], prompt,
                    *, temperature: float = 0.0, seed: int = 0,
                    max_new: int = 1, spec: Optional[bool] = None) -> None:
        """Install a PARKED lane (host-tier session resume,
        :mod:`tpudist.serve.host_tier`) into free ``slot`` and continue
        in PREFILL mode: the package's covered positions are a verified
        prefix of ``prompt`` (the tier checks token equality), so only
        ``prompt[pos:]`` — the new turn — is teacher-forced, through the
        ordinary chunked-prefill path.  No new compiled program exists
        for this: resume is ``import_lane`` + ``prefill_extend``, so the
        compile pins stay flat under park/resume churn.

        The imported SlotState row is re-armed ON THE HOST for the new
        turn — fresh ``temps``/``keys`` (derived exactly like
        ``insert_batch``'s in-graph ``PRNGKey(seed)``) and zeroed
        ``counts``/acceptance — so the resumed stream is byte-identical
        to a fresh serve of the full prompt at the same seed, minus the
        covered prefix's recompute.  Paged engines reserve the FULL
        ``prompt + max_new`` footprint here (no prefix sharing — a
        resumed lane's context is private, like an imported handoff)."""
        if self.occupied[slot]:
            raise ValueError(f"slot {slot} is occupied")
        if bool(package["paged"]) != (self.alloc is not None):
            raise ValueError("parked package and engine disagree on "
                             "paged mode — tiers must share KV geometry")
        import jax

        prompt = np.asarray(prompt, np.int32).reshape(-1)
        pos = int(package["pos"])
        max_new = int(max_new)
        if not 0 < pos < len(prompt):
            raise ValueError(
                f"resume cursor {pos} outside prompt of {len(prompt)} — "
                "the parked context must be a strict prefix of the new "
                "turn's prompt")
        reason = self.check_budget(len(prompt), max_new)
        if reason is not None:
            raise ValueError(reason)
        # fresh per-turn sampling state, derived EXACTLY like
        # insert_batch derives it in-graph (int32 seed wrap → PRNGKey),
        # so a resumed turn's sampled stream equals the fresh-prefill
        # twin's at the same seed
        seed32 = int(np.uint32(int(seed) & 0xFFFFFFFF).astype(np.int32))
        key = np.asarray(jax.random.PRNGKey(seed32), np.uint32)
        row_state = package["state"]._replace(
            last_tok=np.zeros((), np.int32),
            active=np.zeros((), bool),
            counts=np.zeros((), np.int32),
            temps=np.asarray(temperature, np.float32),
            keys=key,
            accepted=np.zeros((), np.int32),
            drafted=np.zeros((), np.int32))
        # full prompt + max_new reservation (no prefix sharing on a
        # resumed lane), then the same install dispatch imports ride
        self._install_lane(slot, package["lane"], row_state, pos,
                           admit_span=(len(prompt), max_new),
                           adapter=package.get("adapter"),
                           grammar=package.get("grammar"))
        self.occupied[slot] = True
        self.decoding[slot] = False
        self.pos[slot] = pos
        self.counts[slot] = 0
        self.budget[slot] = max_new
        self.spec_on[slot] = True if spec is None else bool(spec)
        # the uncovered suffix rides the ordinary chunked-prefill path
        # (its first token is the parked last_tok — teacher-forcing it
        # writes the one cache position the park left pending)
        self._prefill_rest[slot] = (prompt, pos)
        self.peak_occupied = max(self.peak_occupied, self.num_occupied)

    def exportable(self, slot: int, delivered: int) -> bool:
        """Can this decoding lane park WITHOUT overshoot — device counts
        equal the ``delivered`` tokens the caller actually streamed?  An
        EOS that fired mid-block leaves speculated tokens in the cache
        beyond the delivered stream; parking that lane would corrupt the
        next turn's context, so the server skips the park (the next turn
        simply re-prefills — bounded waste, never wrong bytes)."""
        return bool(self.decoding[slot]) \
            and int(self.counts[slot]) == int(delivered)

    # -- lifecycle of a request -------------------------------------------

    def check_budget(self, prompt_len: int, max_new: int) -> Optional[str]:
        """``None`` if a request fits, else the rejection reason — the ONE
        budget rule admission control and the engine agree on.  Chunked
        prefill admits any prompt up to ``max_len - max_new`` (the
        prefill pad is a chunk size, not an admission bound)."""
        if prompt_len < 1:
            return "empty_prompt"
        if max_new < 1:
            return "max_new_must_be_positive"
        if prompt_len + max_new > self.max_len:
            return (f"budget_exceeded: prompt {prompt_len} + max_new "
                    f"{max_new} > max_len {self.max_len}")
        if self.alloc is not None:
            need = self.alloc.blocks_needed(prompt_len, max_new)
            if need > self.alloc.num_blocks:
                # can NEVER be admitted: the whole-footprint reservation
                # exceeds the pool even when it is empty (transient
                # exhaustion is not a reject — the request queues and
                # admission waits for blocks to free)
                return (f"kv_exhausted: footprint {need} blocks > pool "
                        f"{self.alloc.num_blocks}")
        return None

    def cache_full_slots(self) -> List[int]:
        """Decoding slots whose KV cursor reached ``max_len`` with
        budget still unspent — decoding on would clamp writes onto the
        last position and attend over garbage (the silent-overflow
        failure :class:`tpudist.models.generate.CacheFullError` exists
        for).  Admission's budget rule makes this empty in healthy runs;
        the server finishes any hit with reason ``"cache_full"`` instead
        of letting ``decode_block`` corrupt or crash the loop."""
        return [int(s) for s in np.nonzero(
            self.decoding & (self.pos >= self.max_len)
            & (self.counts < self.budget))[0]]

    def can_admit_kv(self, prompt_len: int, max_new: int,
                     prefix_hashes: Sequence[str] = (), *,
                     reserve: int = 0) -> bool:
        """Would the block pool cover this request RIGHT NOW (reused
        prefix blocks discounted), on top of ``reserve`` blocks already
        promised to same-batch admissions?  The server's take-from-queue
        gate on the paged engine; always True on the dense engine, where
        a free slot IS the whole admission budget."""
        if self.alloc is None:
            return True
        return self.alloc.can_admit(prompt_len, max_new, prefix_hashes,
                                    reserve=reserve)

    def kv_admission_probe(self, prompt_len: int, max_new: int,
                           prefix_hashes: Sequence[str] = (), *,
                           reserve: int = 0, protect: Sequence[int] = ()):
        """Multi-take admission probe: ``(fresh_blocks, reused_ids)`` if
        the pool covers this request on top of ``reserve`` fresh blocks
        and the ``protect``-pinned reuses already promised to earlier
        same-batch candidates, else ``None``.  Trivially admits on the
        dense engine (``(0, [])``)."""
        if self.alloc is None:
            return 0, []
        return self.alloc.probe(prompt_len, max_new, prefix_hashes,
                                reserve=reserve, protect=protect)

    def kv_footprint(self, prompt_len: int, max_new: int,
                     prefix_hashes: Sequence[str] = ()) -> int:
        """Fresh blocks this request would reserve right now (0 on the
        dense engine) — what the server adds to its same-batch reserve
        after each gate pass."""
        if self.alloc is None:
            return 0
        return self.alloc.footprint(prompt_len, max_new, prefix_hashes)

    def start_batch(self, items: Sequence[InsertItem]
                    ) -> Dict[int, Optional[int]]:
        """Admit up to ``num_slots`` requests in ONE compiled dispatch:
        each request's FIRST prompt chunk is prefilled and scattered into
        its slot (the multi-slot scatter — no per-item insert loop).
        Returns ``slot → first generated token`` for requests whose whole
        prompt fit the first chunk (drawn from the post-prompt logits, so
        a ``max_new == 1`` request is complete without any decode), and
        ``slot → None`` for longer prompts, which continue through
        ``advance_prefill`` chunk by chunk.

        Paged engine: each item's whole block footprint is reserved here
        (the allocator's admission-only policy), its prompt's cached
        prefix blocks are mapped in instead of re-prefilled (the chunk
        walk starts at the reused length), and the host-built block-table
        rows ride into the compiled program as data.  Items may carry a
        6th element — the prompt's prefix hash chain; without it a
        request simply never shares."""
        if not items:
            return {}
        if len(items) > self.num_slots:
            raise ValueError(
                f"start_batch of {len(items)} > num_slots {self.num_slots}")
        import jax.numpy as jnp

        pad = self.prefill_pad
        prompts = np.zeros((self.num_slots, pad), np.int32)
        clens = np.zeros(self.num_slots, np.int32)
        # dst == num_slots marks an unused lane (out-of-bounds scatter
        # indices are dropped in the compiled program)
        dsts = np.full(self.num_slots, self.num_slots, np.int32)
        seeds = np.zeros(self.num_slots, np.int32)
        temps = np.zeros(self.num_slots, np.float32)
        last = np.zeros(self.num_slots, bool)
        # validate the WHOLE batch before touching any state — a bad item
        # must not leak half-reserved slots
        norm = []
        taken = set()
        spec_flags = {}
        adapter_names: Dict[int, Optional[str]] = {}
        grammar_objs: Dict[int, Optional[object]] = {}
        for item in items:
            slot, prompt, temperature, seed, max_new = item[:5]
            hashes = tuple(item[5]) if len(item) > 5 else ()
            spec_flags[int(slot)] = (bool(item[6]) if len(item) > 6
                                     and item[6] is not None else True)
            adapter = item[7] if len(item) > 7 else None
            if adapter is not None and not self.has_adapter(adapter):
                # whole-batch validation: a vanished adapter (raced
                # unload) must not leak half-admitted neighbors — the
                # server finishes the request "adapter_missing"
                from tpudist.serve.adapters import AdapterMissingError

                raise AdapterMissingError(str(adapter))
            adapter_names[int(slot)] = adapter
            grammar = item[8] if len(item) > 8 else None
            if grammar is not None and self.grammars is None:
                raise ValueError(
                    "constrained request on an engine built without "
                    "constrain= (TPUDIST_SERVE_CONSTRAIN)")
            grammar_objs[int(slot)] = grammar
            if self.occupied[slot] or slot in taken:
                raise ValueError(f"slot {slot} is occupied")
            taken.add(slot)
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            reason = self.check_budget(len(prompt), max_new)
            if reason is not None:
                raise ValueError(reason)
            norm.append((int(slot), prompt, temperature, seed, int(max_new),
                         hashes))
        ad_args = ()
        if self.adapters is not None:
            # bind each lane's adapter FIRST (before any KV reservation
            # — a failed bind must leave no alloc state behind); the
            # compiled programs take the resolved ids as data and
            # gather the factors in-graph.  TRANSACTIONAL: a mid-batch
            # AdapterMissingError (a user thread unloaded between
            # validation and here) rolls every earlier pin back — the
            # server retries the surviving items through this same
            # path, and a double-acquire would leak a refcount (and
            # its block) forever
            aids = np.full(self.num_slots, self._aid_sentinel(), np.int32)
            bound_slots: List[int] = []
            try:
                for j, (slot, *_rest) in enumerate(norm):
                    aids[j] = self._acquire_adapter(slot,
                                                    adapter_names[slot])
                    bound_slots.append(slot)
            except BaseException:
                for slot in bound_slots:
                    self._release_adapter(slot)
                raise
            ad_args = (jnp.asarray(aids), self.apool)
        g_args = ()
        if self.grammars is not None:
            # grammar binds follow the adapter discipline exactly:
            # transactional (a mid-batch GrammarPoolFull — every block
            # pinned by running lanes — rolls every earlier pin back,
            # adapter pins included; the server defers the batch), and
            # the resolved block ids ride in as data
            gids = np.full(self.num_slots, self._gid_sentinel(), np.int32)
            gbound: List[int] = []
            try:
                for j, (slot, *_rest) in enumerate(norm):
                    gids[j] = self._acquire_grammar(slot,
                                                    grammar_objs[slot])
                    gbound.append(slot)
            except BaseException:
                for slot in gbound:
                    self._release_grammar(slot)
                for slot, *_rest in norm:
                    self._release_adapter(slot)
                raise
            g_args = (jnp.asarray(gids), self.gpool)
        reused_len = np.zeros(self.num_slots, np.int32)
        if self.alloc is not None:
            M = self.max_len // self.paged_cfg.block_size
            tables = np.full((self.num_slots, M), self.paged_cfg.num_blocks,
                             np.int32)
            admitted = []
            # pin every item's currently-reusable chain for the WHOLE
            # batch: an earlier admission's LRU eviction must not take a
            # block a later (gate-approved) item is about to reuse
            protect: List[int] = []
            for slot, prompt, _, _, max_new, hashes in norm:
                protect.extend(
                    self.alloc.reusable_blocks(len(prompt), hashes))
            try:
                for j, (slot, prompt, _, _, max_new, hashes) in \
                        enumerate(norm):
                    row, reused = self.alloc.admit(
                        slot, len(prompt), max_new, hashes,
                        protect=protect)
                    admitted.append(slot)
                    tables[j, :len(row)] = row
                    reused_len[j] = reused
            except RuntimeError:
                # a half-admitted batch must not leak reservations; the
                # caller gates on can_admit_kv, so this is the defense
                # (adapter/grammar pins acquired above roll back too)
                for slot in admitted:
                    self.alloc.release(slot)
                for slot, *_rest in norm:
                    self._release_adapter(slot)
                    self._release_grammar(slot)
                raise
        for j, (slot, prompt, temperature, seed, max_new, _) in \
                enumerate(norm):
            rest = len(prompt) - int(reused_len[j])
            clen = min(rest, pad)
            prompts[j, :clen] = prompt[reused_len[j]:reused_len[j] + clen]
            clens[j] = clen
            dsts[j] = slot
            # int32 wrap keeps huge seeds admissible (the stream just
            # derives from the wrapped value)
            seeds[j] = np.uint32(seed & 0xFFFFFFFF).astype(np.int32)
            temps[j] = temperature
            last[j] = rest <= pad
        if self.alloc is not None:
            self.state, self.cache, firsts = self.fns.insert_batch(
                self.state, self.cache, jnp.asarray(tables),
                jnp.asarray(reused_len), jnp.asarray(prompts),
                jnp.asarray(clens), jnp.asarray(dsts), jnp.asarray(seeds),
                jnp.asarray(temps), jnp.asarray(last), *ad_args, *g_args)
            r, w = self._prefill_kv_bytes(reused_len, clens,
                                          self.num_slots)
            self.prefill_read_bytes_total += r
            self.prefill_write_bytes_total += w
            if self.spec:
                # same chunks, same (host-built) table rows: the draft's
                # pool blocks mirror the target's ids, so a reused
                # prefix's draft KV is already in place
                self.dcache = self.fns.draft_prefill(
                    self.dcache, jnp.asarray(tables),
                    jnp.asarray(reused_len), jnp.asarray(prompts),
                    jnp.asarray(clens), jnp.asarray(dsts), *ad_args,
                    self.draft_params)
        else:
            self.state, self.cache, firsts = self.fns.insert_batch(
                self.state, self.cache, jnp.asarray(prompts),
                jnp.asarray(clens), jnp.asarray(dsts), jnp.asarray(seeds),
                jnp.asarray(temps), jnp.asarray(last), *ad_args, *g_args)
            r, w = self._prefill_kv_bytes(reused_len, clens,
                                          self.num_slots)
            self.prefill_read_bytes_total += r
            self.prefill_write_bytes_total += w
            if self.spec:
                self.dcache = self.fns.draft_prefill(
                    self.dcache, jnp.asarray(prompts), jnp.asarray(clens),
                    jnp.asarray(dsts), *ad_args, self.draft_params)
        firsts_h = np.asarray(firsts) if last.any() else None
        out: Dict[int, Optional[int]] = {}
        for j, (slot, prompt, temperature, seed, max_new, _) in \
                enumerate(norm):
            self.occupied[slot] = True
            self.budget[slot] = max_new
            self.spec_on[slot] = spec_flags[slot]
            self.pos[slot] = reused_len[j] + clens[j]
            if self.alloc is not None:
                self.alloc.note_progress(slot, int(self.pos[slot]))
            if last[j]:
                self.decoding[slot] = True
                self.counts[slot] = 1
                out[slot] = int(firsts_h[j])
            else:
                self.counts[slot] = 0
                self._prefill_rest[slot] = (
                    prompt, int(reused_len[j]) + clens[j])
                out[slot] = None
        self.peak_occupied = max(self.peak_occupied, self.num_occupied)
        return out

    def advance_prefill(self) -> Dict[int, int]:
        """Feed ONE prompt chunk to every prefilling slot (one compiled
        ``prefill_extend`` dispatch each, appended at the slot's running
        cache offset).  Returns ``slot → first generated token`` for the
        slots whose prompt just completed (they switch to decoding)."""
        if not self._prefill_rest:
            return {}
        import jax.numpy as jnp

        pad = self.prefill_pad
        done: List[Tuple[int, object]] = []
        for slot in sorted(self._prefill_rest):
            prompt, off = self._prefill_rest[slot]
            clen = min(pad, len(prompt) - off)
            chunk = np.zeros(pad, np.int32)
            chunk[:clen] = prompt[off:off + clen]
            is_last = off + clen >= len(prompt)
            ad_tail = () if self.adapters is None else (self.apool,)
            self.state, self.cache, first = self.fns.prefill_extend(
                self.state, self.cache, jnp.asarray(slot, jnp.int32),
                jnp.asarray(chunk), jnp.asarray(clen, jnp.int32),
                jnp.asarray(is_last), *ad_tail, *self._g_tail())
            if self.prefill_kernel:
                # the one-hot batched program walks EVERY lane's prefix
                r, w = self._prefill_kv_bytes(
                    self.pos,
                    np.where(np.arange(self.num_slots) == slot, clen, 0),
                    1)
            else:
                r, w = self._prefill_kv_bytes(
                    np.asarray([self.pos[slot]]), np.asarray([clen]), 1)
            self.prefill_read_bytes_total += r
            self.prefill_write_bytes_total += w
            if self.spec:
                d_tail = () if self.adapters is None else (
                    jnp.asarray(self._slot_aid(slot), jnp.int32),
                    self.apool)
                self.dcache = self.fns.draft_extend(
                    self.dcache, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(chunk), jnp.asarray(clen, jnp.int32),
                    *d_tail, self.draft_params)
            self.pos[slot] += clen
            if self.alloc is not None:
                # prompt blocks now fully written become shareable
                # prefix-cache entries (LRU-bounded)
                self.alloc.note_progress(slot, int(self.pos[slot]))
            if is_last:
                del self._prefill_rest[slot]
                self.decoding[slot] = True
                self.counts[slot] = 1
                done.append((slot, first))
            else:
                self._prefill_rest[slot] = (prompt, off + clen)
        return {int(s): int(f) for s, f in done}

    def decode_block(self, max_k: Optional[int] = None
                     ) -> Tuple[Optional[dict], Dict[int, List[int]]]:
        """One fused decode block over every decoding slot: ``K`` steps in
        one dispatch (in-graph token feedback), one D2H fetch of the
        ``K×num_slots`` token block.  ``K = min(block, min remaining
        budget)`` bucketed to a power of two, so no slot can overshoot
        its length budget.  Returns ``(info, slot → K tokens)`` where
        ``info`` carries the dispatch/sync attribution (``None`` when no
        slot is decoding).  SPEC-UNSAFE on its own: a spec engine's
        draft cache must see every emitted token — call through
        :meth:`decode_auto` / :meth:`decode_auto_plain` (which
        draft-track) instead."""
        if not self.decoding.any():
            return None, {}
        dec = np.nonzero(self.decoding)[0]
        remaining = self.budget[dec] - self.counts[dec]
        if (remaining < 1).any():
            raise RuntimeError(
                "decoding slot with exhausted budget — the caller must "
                "evict finished slots before the next block")
        if (self.pos[dec] >= self.max_len).any():
            # admission's budget rule makes this unreachable; a loud error
            # beats silently attending over a recycled cache ring.
            raise RuntimeError("active slot at max_len — admission budget "
                               "violated")
        cap = self.block if max_k is None else max(1, int(max_k))
        # K is also capped by cache headroom: for correctly-admitted
        # requests headroom >= remaining always (prompt + max_new <=
        # max_len), but if the budget rule was bypassed this stops the
        # block EXACTLY at the cache edge — no write ever clamps onto
        # max_len-1 — and the server then finishes the slot
        # "cache_full" (cache_full_slots) instead of decoding garbage.
        headroom = int((self.max_len - self.pos[dec]).min())
        k = _pow2_floor(min(cap, int(remaining.min()), headroom))
        pos0 = self.pos[dec].copy()  # dispatch-start cursors (accounting)
        tail = (() if self.adapters is None else (self.apool,)) \
            + self._g_tail()
        t0 = time.perf_counter()
        lpi = lpv = None
        if self.n_lp:
            self.state, self.cache, blocks, lpi, lpv = \
                self.fns.decode_block(self.state, self.cache, k, *tail)
        else:
            self.state, self.cache, blocks = self.fns.decode_block(
                self.state, self.cache, k, *tail)
        t1 = time.perf_counter()
        arr = np.asarray(blocks)  # ONE host sync for K×num_slots tokens
        if self.n_lp:
            # the top-n arrays ride the same packed fetch window
            lpi, lpv = np.asarray(lpi), np.asarray(lpv)
        t2 = time.perf_counter()
        self.n_decode_blocks += 1
        self.n_decode_tokens += k * len(dec)
        self.n_decode_steps += k
        self.t_decode_dispatch_s += t1 - t0
        self.t_decode_sync_s += t2 - t1
        self.counts[dec] += k
        self.pos[dec] += k
        out = {int(s): [int(t) for t in arr[:, s]] for s in dec}
        # KV bytes the block's attention streamed, per the ACTIVE path
        # (_decode_kv_read_bytes): k full sweeps; the kernel's window
        # buffer grows one token per step (Σ = k(k+1)/2 per lane).
        kv_read = self._decode_kv_read_bytes(pos0, k, k * (k + 1) // 2)
        self.kv_read_bytes_total += kv_read
        info = {"k": k, "tokens": k * len(dec),
                "dispatch_s": t1 - t0, "sync_s": t2 - t1,
                "kv_read_bytes": int(kv_read)}
        if self.n_lp:
            # slot → one (ids, logprobs) top-n pair per emitted token,
            # aligned with the token lists in ``out``
            info["logprobs"] = {
                int(s): [(lpi[i, s].tolist(), lpv[i, s].tolist())
                         for i in range(k)] for s in dec}
        return info, out

    def step(self) -> Dict[int, int]:
        """One single-token decode iteration (a K=1 block) — the
        per-token path ``decode_block`` amortizes; kept for tests and
        K=1 comparisons.  Returns ``slot → token`` for decoding slots.
        On a spec engine the emitted token is draft-tracked (the
        plain-path rule: the draft must never desync from the target —
        :meth:`decode_block` alone is spec-UNSAFE; go through
        :meth:`decode_auto` / :meth:`decode_auto_plain`)."""
        _, toks = (self.decode_auto_plain(max_k=1) if self.spec
                   else self.decode_block(max_k=1))
        return {s: t[0] for s, t in toks.items()}

    def spec_decode_block(self, max_k: Optional[int] = None
                          ) -> Tuple[Optional[dict], Dict[int, List[int]]]:
        """One speculative block over every decoding slot: K draft
        proposal steps (one cheap dispatch), ONE batched target verify
        of the whole ``K+1``-token window, in-graph acceptance +
        rollback, one D2H fetch of the packed emitted tokens.  Each lane
        emits 1..K+1 tokens — ``accepted + 1`` — for ~one target
        weight/KV sweep, which is how wall-TPOT drops below the
        single-model device-busy floor once the draft agrees often
        enough.  Per-lane budgets are clamped IN-GRAPH (``rem`` rides as
        data), so mixed remaining budgets never overshoot and a lane
        with 1 remaining still participates.  K is capped by cache
        headroom (the window must fit below ``max_len`` in every active
        lane) and bucketed to a power of two (jit cache bounded like
        ``decode_block``'s).  Falls back to ``None, {}`` when no slot is
        decoding; the caller should use :meth:`decode_auto`, which also
        falls back to the plain block when speculation cannot run."""
        if not self.spec:
            raise RuntimeError("engine built without spec_draft")
        if not self.decoding.any():
            return None, {}
        dec = np.nonzero(self.decoding)[0]
        remaining = self.budget[dec] - self.counts[dec]
        if (remaining < 1).any():
            raise RuntimeError(
                "decoding slot with exhausted budget — the caller must "
                "evict finished slots before the next block")
        if (self.pos[dec] >= self.max_len).any():
            raise RuntimeError("active slot at max_len — admission budget "
                               "violated")
        import jax
        import jax.numpy as jnp

        # the verify window writes K+1 positions in every active lane:
        # K is bounded by the tightest lane's cache headroom (for
        # correctly-admitted lanes headroom >= remaining, so this only
        # bites when the budget rule was bypassed — the cache_full path)
        headroom = int((self.max_len - self.pos[dec]).min())
        cap = self.spec_k if max_k is None else max(1, int(max_k))
        # also capped by the LARGEST remaining budget: when every lane
        # needs exactly one more token, drafting is pure waste — the
        # plain (draft-tracked) block serves that iteration
        cap = min(cap, max(int(remaining.max()) - 1, 0),
                  max(headroom - 1, 0))
        k = _pow2_floor(cap) if cap >= 1 else 0
        if k < 1:
            return self.decode_auto_plain()
        rem = np.zeros(self.num_slots, np.int32)
        rem[dec] = remaining
        pos0 = self.pos[dec].copy()  # dispatch-start cursors (accounting)
        ad_tail = () if self.adapters is None else (self.apool,)
        t0 = time.perf_counter()
        # the draft proposes UNMASKED (a grammar-forbidden draft token
        # is just a rejection in the verify) — its tail stays
        # adapter-only
        self.dcache, drafts, dlogits = self.fns.draft_propose(
            self.state, self.dcache, k, *ad_tail, self.draft_params)
        jax.block_until_ready(drafts)
        t1 = time.perf_counter()
        lpi = lpv = None
        if self.n_lp:
            (self.state, self.cache, self.dcache, packed, lpi,
             lpv) = self.fns.spec_verify(
                self.state, self.cache, self.dcache, drafts, dlogits,
                jnp.asarray(self.spec_on), jnp.asarray(rem), *ad_tail,
                *self._g_tail())
        else:
            self.state, self.cache, self.dcache, packed = \
                self.fns.spec_verify(
                    self.state, self.cache, self.dcache, drafts, dlogits,
                    jnp.asarray(self.spec_on), jnp.asarray(rem), *ad_tail,
                    *self._g_tail())
        t2 = time.perf_counter()
        pk = np.asarray(packed)  # ONE host sync: counts + token block
        if self.n_lp:
            lpi, lpv = np.asarray(lpi), np.asarray(lpv)
        t3 = time.perf_counter()
        n_emit = pk[dec, 0]
        a_raw = pk[dec, 1]
        accepted = int(a_raw.sum())
        drafted = int(k * (self.spec_on[dec]).sum())
        emitted = int(n_emit.sum())
        # a rollback is a verify that REJECTED a draft (budget-clamped
        # full accepts are not rollbacks — the drafts were right)
        rollbacks = int(((a_raw < k) & self.spec_on[dec]).sum())
        self.counts[dec] += n_emit
        self.pos[dec] += n_emit
        self.n_decode_blocks += 1
        self.n_decode_tokens += emitted
        self.n_decode_steps += 1  # ONE target pass per spec block
        self.t_decode_dispatch_s += t2 - t0
        self.t_decode_sync_s += t3 - t2
        self.n_spec_blocks += 1
        self.n_spec_lane_passes += len(dec)
        self.n_spec_tokens += emitted
        self.n_spec_accepted += accepted
        self.n_spec_drafted += drafted
        self.n_spec_rollbacks += rollbacks
        self.t_spec_draft_s += t1 - t0
        self.t_spec_verify_s += t2 - t1
        self.t_spec_sync_s += t3 - t2
        # per-adapter acceptance (the labeled twin of the engine-wide
        # counters): host-side bookkeeping off the SAME packed fetch —
        # no extra D2H (slot→adapter is a host shadow)
        by_adapter: Dict[str, List[int]] = {}
        if self.adapters is not None:
            for j, s in enumerate(dec):
                bound = self.slot_adapter[s]
                if bound is None or not self.spec_on[s]:
                    continue
                d = by_adapter.setdefault(bound[0], [0, 0])
                d[0] += int(a_raw[j])
                d[1] += k
            for name, (acc, dr) in by_adapter.items():
                tot = self.spec_adapter_counts.setdefault(name, [0, 0])
                tot[0] += acc
                tot[1] += dr
        out = {int(s): [int(t) for t in pk[s, 2:2 + pk[s, 0]]] for s in dec
               if pk[s, 0] > 0}
        # the verify is ONE attention sweep over each lane's prefix +
        # the K+1-token window (the draft adds its own smaller sweeps,
        # not charged here) — per the active path's honest model
        kv_read = self._decode_kv_read_bytes(pos0, 1, k + 1)
        self.kv_read_bytes_total += kv_read
        info = {"spec": True, "k": k, "tokens": emitted,
                "accepted": accepted, "drafted": drafted,
                "rollbacks": rollbacks,
                "draft_s": t1 - t0, "verify_s": t2 - t1,
                "dispatch_s": t2 - t0, "sync_s": t3 - t2,
                "kv_read_bytes": int(kv_read),
                **({"accept_by_adapter": {
                    n: [int(a), int(d)] for n, (a, d) in
                    by_adapter.items()}} if by_adapter else {})}
        if self.n_lp:
            # slot → per-emitted-token top-n pairs, rows [:n_emit] of
            # the verify's [S, k+1, n] arrays (aligned with ``out``)
            info["logprobs"] = {
                int(s): [(lpi[s, i].tolist(), lpv[s, i].tolist())
                         for i in range(int(pk[s, 0]))]
                for s in dec if pk[s, 0] > 0}
        return info, out

    def decode_auto_plain(self, max_k: Optional[int] = None
                          ) -> Tuple[Optional[dict],
                                     Dict[int, List[int]]]:
        """A plain fused decode block that ALSO teacher-forces its
        emitted tokens through the draft cache (``draft_track``), so
        draft and target cursors stay in lockstep across
        non-speculative iterations and acceptance survives the next
        spec block."""
        import jax.numpy as jnp

        prev_last = (self.state.last_tok.copy()
                     if self.spec and self.decoding.any() else None)
        info, blocks = self.decode_block(max_k=max_k)
        if self.spec and info is not None and blocks:
            k = info["k"]
            toks = np.zeros((k, self.num_slots), np.int32)
            for s, ts in blocks.items():
                toks[:, s] = ts
            ad_tail = () if self.adapters is None else (self.apool,)
            self.dcache = self.fns.draft_track(
                self.state, self.dcache, prev_last, jnp.asarray(toks),
                *ad_tail, self.draft_params)
        if info is not None:
            info = {**info, "spec": False}
        return info, blocks

    def decode_auto(self) -> Tuple[Optional[dict], Dict[int, List[int]]]:
        """The serving loop's decode dispatcher: the speculative block
        when the engine has a draft and any decoding lane opted in,
        else the plain fused block (draft-tracked when spec is on, so
        the draft never desyncs)."""
        if not self.spec:
            return self.decode_block()
        dec = self.decoding
        if not (dec & self.spec_on).any():
            return self.decode_auto_plain()
        return self.spec_decode_block()

    def evict(self, slot: int) -> None:
        """Free a lane: zero its cache and device state (no K/V leakage
        into the next tenant's garbage window), reset the host shadows,
        drop any pending prefill chunks.  Paged: the slot's tenancy is
        released on the host; only blocks whose refcount hit zero AND
        that no prefix-cache entry pins are zeroed on device and
        returned to the free list — a shared prefix block outlives any
        one tenant."""
        import jax.numpy as jnp

        if self.alloc is not None:
            freed = self.alloc.release(slot)
            M = self.max_len // self.paged_cfg.block_size
            free_ids = np.full(M, self.paged_cfg.num_blocks, np.int32)
            free_ids[:len(freed)] = freed
            self.state, self.cache = self.fns.evict(
                self.state, self.cache, jnp.asarray(slot, jnp.int32),
                jnp.asarray(free_ids))
            if self.spec:
                # same recycled block ids: the draft pool's copies are
                # zeroed alongside the target's (no cross-tenant K/V
                # leakage in either pool)
                self.dcache = self.fns.draft_evict(
                    self.dcache, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(free_ids))
        else:
            self.state, self.cache = self.fns.evict(
                self.state, self.cache, jnp.asarray(slot, jnp.int32))
            if self.spec:
                self.dcache = self.fns.draft_evict(
                    self.dcache, jnp.asarray(slot, jnp.int32))
        self._release_adapter(slot)
        self._release_grammar(slot)
        self.occupied[slot] = False
        self.decoding[slot] = False
        self.pos[slot] = 0
        self.counts[slot] = 0
        self.budget[slot] = 0
        self.spec_on[slot] = True
        self._prefill_rest.pop(slot, None)
