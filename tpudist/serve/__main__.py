"""``python -m tpudist.serve`` — self-contained serving demo.

Builds a small randomly-initialized ``TransformerLM``, starts the
continuous-batching server, pushes a burst of concurrent requests with
heterogeneous prompt/output lengths through it, streams tokens, drains,
and prints a JSON summary (per-request TTFT/latency + server stats).
Runs on CPU in seconds — the quick-start for the serving subsystem; the
real measurement harness is ``benchmarks/serve_bench.py``.

``--replicas N`` (N >= 2) runs the same burst through the fleet router
instead: N replica servers behind :class:`tpudist.serve.FleetRouter`,
with the routing/failover stats in the summary — the multi-replica
quick-start (``benchmarks/router_bench.py`` is the measurement
harness).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpudist.serve",
        description="continuous-batching serving demo (random weights)")
    p.add_argument("--requests", type=int, default=12)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--queue", type=int, default=64)
    p.add_argument("--max-new", type=int, default=16,
                   help="output-length ceiling; each request draws from "
                        "[2, max-new]")
    p.add_argument("--prompt-len", type=int, default=12,
                   help="prompt-length ceiling; each request draws from "
                        "[1, prompt-len]")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--decode-block", type=int, default=8,
                   help="max fused decode tokens per device dispatch (K)")
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--max-len", type=int, default=128)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--replicas", type=int, default=1,
                   help="run N replica servers behind the fleet router "
                        "(1 = single server, no router)")
    p.add_argument("--telemetry-dir", default=None,
                   help="where serving spans land (default: "
                        "TPUDIST_TELEMETRY_DIR or runs/telemetry)")
    args = p.parse_args(argv)

    import jax
    import numpy as np

    from tpudist import telemetry
    from tpudist.models import create_transformer
    from tpudist.serve import (FleetRouter, InferenceServer, RouterConfig,
                               ServeConfig)

    if args.telemetry_dir:
        telemetry.start(args.telemetry_dir)
    module, params = create_transformer(
        jax.random.PRNGKey(args.seed), seq_len=16, vocab=args.vocab,
        d_model=args.d_model, n_layers=args.n_layers,
        n_heads=max(2, args.d_model // 32), d_ff=4 * args.d_model,
        max_len=args.max_len)
    # chunked prefill admits prompts up to max_len - max_new; the pad is
    # just the chunk size — half the prompt ceiling, so the demo's longer
    # prompts actually exercise the chunked-prefill path
    prefill_pad = max(1, min(args.prompt_len // 2, args.max_len // 2))
    cfg = ServeConfig(num_slots=args.slots, queue_limit=args.queue,
                      max_new=args.max_new, prefill_pad=prefill_pad,
                      decode_block=args.decode_block,
                      host_tier=args.replicas > 1)
    if args.replicas > 1:
        # the multi-replica rig: N servers sharing the (tiny random)
        # weights, the router in front — sessions park in each
        # replica's host tier so death/drain can migrate them
        replicas = [InferenceServer(module, params, cfg,
                                    install_signal_handler=False).start()
                    for _ in range(args.replicas)]
        front = FleetRouter(replicas, RouterConfig()).start()
    else:
        front = InferenceServer(module, params, cfg)
        front.start()

    import time

    from tpudist.serve import AdmissionError

    rng = np.random.default_rng(args.seed)
    handles = []
    # prompts range past the pad (chunked prefill) but stay admissible
    # under the budget rule plen + max_new <= max_len
    plen_cap = max(1, min(args.prompt_len, args.max_len - args.max_new))
    for i in range(args.requests):
        plen = int(rng.integers(1, plen_cap + 1))
        max_new = int(rng.integers(2, args.max_new + 1))
        prompt = rng.integers(0, args.vocab, size=plen).astype(np.int32)
        stop_burst = False
        while True:
            try:
                handles.append(front.submit(
                    prompt, max_new=max_new, temperature=args.temperature,
                    seed=i))
                break
            except AdmissionError as e:
                if e.reason != "queue_full":
                    # only backpressure is transient; "draining" (e.g. the
                    # engine loop died) would spin here forever
                    print(f"[serve demo] submit stopped: {e.reason}",
                          file=sys.stderr)
                    stop_burst = True
                    break
                time.sleep(0.01)  # bounded queue doing its job: wait
        if stop_burst:
            break
    for h in handles:
        h.wait()
    stats = front.stats()
    front.close()
    report = telemetry.finish()

    rows = [{
        "id": getattr(h, "id", None),
        "prompt_len": int(len(getattr(h, "prompt", None)
                              if args.replicas > 1 else h.request.prompt)),
        "tokens_out": len(h.tokens),
        "reason": h.finish_reason,
        **({"replica": h.replica} if args.replicas > 1 else {}),
        "ttft_ms": round(h.ttft_s * 1e3, 2) if h.ttft_s else None,
        "tpot_ms": round(h.tpot_s * 1e3, 2) if h.tpot_s else None,
    } for h in handles]
    print(json.dumps({"requests": rows, "stats": stats,
                      "telemetry_report": bool(report)}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
