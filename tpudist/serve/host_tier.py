"""Host-RAM KV session tier: the degraded-but-alive layer under the pool.

The device KV pool is the scarce resource serving fights over: a full
pool means "pool full → reject", an idle chat session holds device
blocks across minutes-long user gaps, and a preempted lane's KV would
otherwise be recompute.  This module is the tier BELOW the pool — a
byte-budgeted host-memory store of serialized KV packages (the PR-7
handoff wire format: ``serialize_package``'s schema-versioned,
blake2b-digested blob), keyed by session, so a lane can leave the
device and come back without recompute:

- **idle session park** — a finished turn's lane exports through the
  existing ``export_lane``/``serialize_package`` path and parks here
  keyed by ``(tenant, session)``; the session's NEXT turn re-imports it
  and teacher-forces only the new suffix (resume-TTFT ∝ the new turn,
  not the whole conversation);
- **preemption park** — a low-priority decode lane preempted by a
  high-priority arrival parks here mid-stream (keyed by request id,
  pinned) and resumes BYTE-IDENTICALLY later: decode is a pure function
  of the packaged ``(state, cache)`` plus the ``fold_in(key, count)``
  sampling stream, the same invariant lane recovery already rides;
- **LRU spill** — the store never exceeds its byte budget
  (``TPUDIST_HOST_TIER_BYTES``): least-recently-touched unpinned
  entries spill first (a spilled session's next turn re-prefills — the
  graceful degradation, not an error), pinned (preempted) entries spill
  only when nothing else is left (their resume falls back to a full
  re-prefill with duplicate-drop, still byte-identical);
- **integrity** — packages keep their serialize-time blake2b digest;
  re-import verifies it, and a corrupt parked blob degrades to a full
  re-prefill with a ``host_tier_corrupt`` event — never a crash, never
  wrong bytes (the ``TPUDIST_FAULT=host_tier_corrupt@nth:N`` chaos kind
  garbles the Nth parked package post-digest to prove exactly that).

Fleet re-homing (the router PR): a parked session is also the unit of
MIGRATION between replicas — :meth:`export_entry` hands out a copy of
the serialized entry (the same schema-versioned wire format), and
:meth:`adopt` installs one that was parked on ANOTHER replica's tier.
Integrity still travels with the blob: adopt stores the bytes verbatim,
and the adopting replica's resume path verifies the digest exactly as
if it had parked the package itself — a corrupt migrated blob degrades
to a full re-prefill there, never imports.

Thread contract: same as the engine — exactly one caller (the serving
loop's engine thread) for the put/get/match mutation paths; ``stats()``
reads are GIL-atomic counters.  :meth:`export_entry`,
:meth:`session_keys` and :meth:`adopt` are additionally safe to call
from a router thread: each is a single GIL-atomic dict operation (plus
reads of immutable entry fields), the same contract ``stats()`` rides.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np


class HostTierError(RuntimeError):
    """A parked package the tier cannot hand back: ``reason`` is
    ``"missing"`` (never parked, spilled, or expired) or ``"corrupt"``
    (failed its integrity digest — the caller degrades to a full
    re-prefill, never imports the bytes)."""

    def __init__(self, msg: str, reason: str):
        super().__init__(msg)
        self.reason = reason


class _Entry:
    __slots__ = ("ser", "nbytes", "context", "pinned", "kind",
                 "t_parked", "t_touch")

    def __init__(self, ser: dict, nbytes: int, context, pinned: bool,
                 kind: str, now: float):
        self.ser = ser
        self.nbytes = nbytes
        self.context = context
        self.pinned = pinned
        self.kind = kind
        self.t_parked = now
        self.t_touch = now


class HostKVTier:
    """Byte-budgeted LRU store of serialized KV packages (module doc).

    Keys are tuples (``("sess", tenant, session)`` for idle session
    parks — tenant-scoped, so one tenant can never resume another's
    context — and ``("preempt", request_id)`` for preempted lanes), so
    caller-supplied session strings can never collide with internal
    keys.  ``context`` on a session entry is the full covered token
    stream (prompt + every delivered token): :meth:`match` resumes only
    when the next turn's prompt EXTENDS it exactly — a diverged context
    silently falls back to a fresh prefill."""

    def __init__(self, byte_budget: int, *, ttl_s: Optional[float] = None):
        if byte_budget < 1:
            raise ValueError(
                f"host-tier byte budget must be >= 1, got {byte_budget}")
        self.byte_budget = int(byte_budget)
        self.ttl_s = ttl_s
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self.bytes_resident = 0
        # cumulative counters (stats() / /statusz / telemetry gauges)
        self.parks = 0
        self.resumes = 0
        self.spills = 0
        self.spilled_bytes = 0
        self.expired = 0
        self.rejected_oversize = 0

    # -- write side ---------------------------------------------------------

    def put(self, key: tuple, package: dict, *, context=None,
            pinned: bool = False, kind: str = "turn",
            now: Optional[float] = None) -> Optional[int]:
        """Serialize ``package`` (a raw :meth:`SlotEngine.export_slot`
        dict) and park it under ``key``, spilling LRU entries to stay
        under the byte budget.  Returns the stored byte count, or
        ``None`` — package dropped — when it alone exceeds the whole
        budget (the caller serves on without the tier, it does not
        crash).  Re-parking an existing key replaces the entry (a
        session's newest turn wins)."""
        from tpudist.runtime import faults
        from tpudist.serve.disagg import serialize_package

        now = time.monotonic() if now is None else now
        ser = serialize_package(package)
        # chaos harness: a due host_tier_corrupt fault garbles the blob
        # AFTER serialize stamped the digest — detectable corruption the
        # resume path must degrade on, not import
        faults.inject_host_tier(ser)
        nbytes = int(ser["bytes"])
        if context is not None:
            context = np.asarray(context, np.int32).reshape(-1)
            nbytes += context.nbytes
        return self._store(key, ser, nbytes, context, pinned, kind, now)

    def _store(self, key: tuple, ser: dict, nbytes: int, context,
               pinned: bool, kind: str, now: float) -> Optional[int]:
        """Budget-checked insert of an already-serialized entry — the
        shared tail of :meth:`put` (fresh park) and :meth:`adopt`
        (migrated park)."""
        if nbytes > self.byte_budget:
            self.rejected_oversize += 1
            return None
        self.discard(key)
        self._spill(nbytes)
        self._entries[key] = _Entry(ser, nbytes, context, pinned, kind, now)
        self.bytes_resident += nbytes
        self.parks += 1
        return nbytes

    def adopt(self, key: tuple, ser: dict, *, context=None,
              kind: str = "turn", now: Optional[float] = None
              ) -> Optional[int]:
        """Install a package serialized ELSEWHERE (another replica's
        tier, a router-side stash) under ``key`` — the migration half of
        :meth:`export_entry`.  The bytes are stored verbatim, digest and
        all: integrity is still checked by the resume path's
        deserialize, so a blob corrupted in transit degrades to a full
        re-prefill on THIS replica instead of importing.  Same budget
        rules as :meth:`put` (LRU spill, oversize → ``None``)."""
        now = time.monotonic() if now is None else now
        nbytes = int(ser["bytes"])
        if context is not None:
            context = np.asarray(context, np.int32).reshape(-1)
            nbytes += context.nbytes
        return self._store(key, ser, nbytes, context, False, kind, now)

    def export_entry(self, key: tuple) -> Optional[dict]:
        """A stashable copy of the entry under ``key`` WITHOUT popping
        it: the serialized package plus the covered context — everything
        :meth:`adopt` needs to re-home the session on another replica.
        ``None`` when not resident.  The package dict is returned as-is
        (entries are never mutated in place), so the copy is O(1)."""
        e = self._entries.get(key)
        if e is None:
            return None
        return {"ser": e.ser, "context": e.context, "kind": e.kind}

    def session_keys(self) -> List[tuple]:
        """Keys of every parked SESSION entry (``("sess", tenant,
        session)`` — preempted mid-stream lanes excluded: they belong to
        a live handle, not to the migratable idle-session set)."""
        return [k for k in list(self._entries)
                if isinstance(k, tuple) and k and k[0] == "sess"]

    def _spill(self, incoming: int) -> None:
        """Free room for ``incoming`` bytes: least-recently-touched
        UNPINNED entries first; pinned (preempted, mid-stream) entries
        only when nothing else remains — their resume degrades to a full
        re-prefill, a parked idle session is the cheaper loss."""
        for only_unpinned in (True, False):
            for key in list(self._entries):
                if self.bytes_resident + incoming <= self.byte_budget:
                    return
                if only_unpinned and self._entries[key].pinned:
                    continue
                e = self._entries.pop(key)
                self.bytes_resident -= e.nbytes
                self.spills += 1
                self.spilled_bytes += e.nbytes

    # -- read side ----------------------------------------------------------

    def match(self, key: tuple, prompt) -> Optional[int]:
        """Covered cursor position if a parked session entry under
        ``key`` can serve ``prompt`` without recompute — the prompt must
        extend the parked context token-for-token (``prompt[:len(ctx)]
        == ctx``; the resume then teacher-forces ``prompt[pos:]``, whose
        first token is the parked ``last_tok``).  ``None`` = no entry,
        or a diverged context (the caller re-prefills fresh; a stale
        diverged entry is discarded so it stops holding bytes)."""
        e = self._entries.get(key)
        if e is None or e.context is None:
            return None
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ctx = e.context
        if len(prompt) < len(ctx):
            return None  # a different (shorter) turn — miss, keep entry
        if not np.array_equal(prompt[:len(ctx)], ctx):
            self.discard(key)
            return None
        e.t_touch = time.monotonic()
        self._entries.move_to_end(key)
        return int(e.ser["pos"])

    def get(self, key: tuple) -> dict:
        """Pop and return the serialized package under ``key``; raises
        :class:`HostTierError` (``"missing"``) when it is not resident
        (spilled/expired/never parked).  Integrity is the CALLER's
        deserialize step (``deserialize_package`` verifies the digest) —
        the tier hands back exactly the bytes it was given."""
        e = self._entries.pop(key, None)
        if e is None:
            raise HostTierError(
                f"no parked package under {key!r} (spilled, expired, or "
                "never parked) — resume falls back to a full re-prefill",
                reason="missing")
        self.bytes_resident -= e.nbytes
        self.resumes += 1
        return e.ser

    def peek(self, key: tuple) -> Optional[dict]:
        """The serialized package under ``key`` WITHOUT popping it —
        for capacity gates that read the envelope fields (``pos``/
        ``budget``) before committing to the resume."""
        e = self._entries.get(key)
        return None if e is None else e.ser

    def contains(self, key: tuple) -> bool:
        return key in self._entries

    def discard(self, key: tuple) -> bool:
        """Drop an entry (releases its bytes); True iff one existed."""
        e = self._entries.pop(key, None)
        if e is None:
            return False
        self.bytes_resident -= e.nbytes
        return True

    def sweep_expired(self, now: Optional[float] = None) -> List[tuple]:
        """Expire idle parked sessions past ``ttl_s`` (release their
        bytes NOW instead of leaking the entry until LRU pressure).
        Pinned (preempted mid-stream) entries are exempt — their
        lifetime is their request's deadline, enforced by the server's
        parked-deadline sweep.  Returns the expired keys."""
        if self.ttl_s is None:
            return []
        now = time.monotonic() if now is None else now
        out = []
        for key, e in list(self._entries.items()):
            if e.pinned:
                continue
            if now - e.t_touch > self.ttl_s:
                self._entries.pop(key)
                self.bytes_resident -= e.nbytes
                self.expired += 1
                out.append(key)
        return out

    # -- accounting ---------------------------------------------------------

    @property
    def entries(self) -> int:
        return len(self._entries)

    def stats(self) -> Dict[str, object]:
        """Occupancy + lifetime counters — the ``/statusz`` host-tier
        section and the serving report's ``kv.host_tier`` gauges."""
        return {
            "entries": len(self._entries),
            "pinned": sum(1 for e in self._entries.values() if e.pinned),
            "bytes": self.bytes_resident,
            "byte_budget": self.byte_budget,
            "parks": self.parks,
            "resumes": self.resumes,
            "spills": self.spills,
            "spilled_bytes": self.spilled_bytes,
            "expired": self.expired,
            "rejected_oversize": self.rejected_oversize,
            "ttl_s": self.ttl_s,
        }
