"""Host-side block accounting for the paged KV cache.

The device half (:mod:`tpudist.models.paged`) is pure indirection: a
block pool plus per-slot block tables, gathered and scattered inside the
compiled programs.  WHICH physical block backs which logical position is
decided here, on the host, and shipped into the programs as data
(``tables``/``poss`` into ``insert_batch``, ``free_ids`` into ``evict``)
— never as shapes, so allocation churn can't recompile anything.

Allocation policy (deliberately the simplest one that decouples slot
count from ``max_len``): a request reserves its WHOLE footprint
``ceil((prompt_len + max_new) / block_size)`` blocks at admission, minus
whatever prefix blocks it can reuse.  No mid-decode allocation means the
decode program never needs a table-update argument and an admitted
request can never be preempted by a later one's growth — admission is
the only gate.  The capacity win over the dense arena is that a request
holds blocks for its OWN budget, not for ``max_len``: mixed-length
traffic packs ``pool_blocks / mean_footprint`` concurrent sequences
where the dense cache pinned ``num_slots × max_len`` bytes regardless.

Shared-prefix reuse: prompts are hashed block by block into a chain
(``hash_chain``); a prefix cache maps chain hashes to resident pool
blocks, LRU-bounded.  A hit maps the block into the new request's table
row read-only (the compiled commit never writes below the request's
first private block), so a common system prompt is prefilled ONCE and
every later request that shares it skips those prefill steps AND those
blocks' bytes.  Refcounts here are tenant counts; a cache entry pins its
block independently, so a shared block outlives any one tenant and a
hot prefix survives idle gaps up to the cache bound.

Freed blocks returned by :meth:`release` are zeroed on device by the
``evict`` program (KV-hygiene, same as the dense engine).  Blocks freed
by prefix-cache LRU eviction skip the device zero: a recycled block's
stale bytes sit beyond every new tenant's cursor, where the decode
attention's hard mask (`models/paged.py` module doc) excludes them
bit-exactly — the oracle equivalence tests cover recycled-block reuse.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: hash-chain element: hex digest of (previous digest, block tokens)
PrefixHash = str


def hash_chain(prompt: np.ndarray, block_size: int) -> Tuple[PrefixHash, ...]:
    """One digest per FULL block of ``prompt``, each chained on the
    previous — equal chains mean equal token prefixes, so a chain hit is
    a safe block to share.  Computed once at submit (the scheduler
    stamps it on the request) so admission never re-hashes."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    out: List[PrefixHash] = []
    prev = b""
    for b in range(len(prompt) // block_size):
        h = hashlib.blake2b(digest_size=16)
        h.update(prev)
        h.update(prompt[b * block_size:(b + 1) * block_size].tobytes())
        prev = h.digest()
        out.append(h.hexdigest())
    return tuple(out)


class BlockAllocator:
    """Free list + tenant refcounts + LRU prefix cache over a pool of
    ``num_blocks`` physical blocks (ids ``0..num_blocks-1``; the device
    sentinel ``num_blocks`` marks unmapped table entries).

    Thread contract: same as the engine — exactly one caller.
    """

    def __init__(self, num_blocks: int, block_size: int, max_len: int,
                 prefix_cache_blocks: int = 0):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.blocks_per_slot = max_len // block_size
        self.prefix_cache_blocks = max(0, int(prefix_cache_blocks))
        self._free: List[int] = list(range(num_blocks))
        self._refs = np.zeros(num_blocks, np.int32)
        #: hash -> block id, oldest-first (LRU); every mapped block is
        #: pinned resident until the entry is evicted
        self._prefix: "OrderedDict[PrefixHash, int]" = OrderedDict()
        self._cached_id: Dict[int, PrefixHash] = {}
        # per-slot tenancy
        self._rows: Dict[int, List[int]] = {}
        self._hashes: Dict[int, Tuple[PrefixHash, ...]] = {}
        self._plen: Dict[int, int] = {}
        self._registered: Dict[int, int] = {}
        # reuse counters (served up through engine.kv_stats)
        self.prefix_hit_blocks = 0
        self.prefix_miss_blocks = 0
        self.prefix_hit_tokens = 0
        # lifetime admission/release churn (park/resume cycles through
        # the host tier release and re-reserve whole footprints — these
        # make that churn visible in kv_stats//statusz where a
        # point-in-time occupancy gauge cannot)
        self.blocks_admitted_total = 0
        self.blocks_released_total = 0

    # -- accounting ---------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        """Immediately free blocks (cache-pinned ones not counted)."""
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        """Resident blocks: tenant-held or cache-pinned."""
        return self.num_blocks - len(self._free)

    @property
    def cached_blocks(self) -> int:
        return len(self._prefix)

    def _evictable(self, protect: Sequence[int] = ()) -> int:
        """Cache entries whose block no tenant holds — freeable on
        demand (``protect``: blocks a pending reuse is about to pin)."""
        ps = set(protect)
        return sum(1 for bid in self._prefix.values()
                   if self._refs[bid] == 0 and bid not in ps)

    # -- admission ----------------------------------------------------------

    def blocks_needed(self, prompt_len: int, max_new: int) -> int:
        """Whole-footprint reservation (module doc: admission is the
        only allocation point)."""
        span = prompt_len + max_new
        return -(-span // self.block_size)

    def _reusable(self, hashes: Sequence[PrefixHash], prompt_len: int
                  ) -> List[int]:
        """Pool blocks backing the longest cached prefix chain — capped
        one position short of the full prompt, so at least one prompt
        token is always teacher-forced (the lane needs live last-token
        logits to sample from)."""
        cap = (prompt_len - 1) // self.block_size
        out: List[int] = []
        for h in hashes[:cap]:
            bid = self._prefix.get(h)
            if bid is None:
                break
            out.append(bid)
        return out

    def footprint(self, prompt_len: int, max_new: int,
                  hashes: Sequence[PrefixHash] = ()) -> int:
        """Fresh blocks an admission would actually take right now
        (whole footprint minus the currently-reusable prefix chain)."""
        return (self.blocks_needed(prompt_len, max_new)
                - len(self._reusable(hashes, prompt_len)))

    def probe(self, prompt_len: int, max_new: int,
              hashes: Sequence[PrefixHash] = (), *, reserve: int = 0,
              protect: Sequence[int] = ()
              ) -> Optional[Tuple[int, List[int]]]:
        """Admission peek (no state change): ``(fresh_blocks,
        reused_block_ids)`` if :meth:`admit` would succeed right now,
        else ``None``.  The two extra terms make a MULTI-take sound —
        without them a batch of gate checks each sees the same free
        list and collectively overdraws the pool:

        - ``reserve``: fresh blocks already promised to admissions
          taken earlier in the same batch;
        - ``protect``: cache-pinned blocks those admissions will REUSE —
          they count as evictable to a naive peek, but the moment the
          earlier tenant lands they are refcounted and cannot free.
        """
        reused = self._reusable(hashes, prompt_len)
        need = self.blocks_needed(prompt_len, max_new) - len(reused)
        ok = (need + reserve <= len(self._free)
              + self._evictable(protect=list(reused) + list(protect)))
        return (need, reused) if ok else None

    def can_admit(self, prompt_len: int, max_new: int,
                  hashes: Sequence[PrefixHash] = (), *,
                  reserve: int = 0,
                  protect: Sequence[int] = ()) -> bool:
        """Boolean form of :meth:`probe` (same contract)."""
        return self.probe(prompt_len, max_new, hashes, reserve=reserve,
                          protect=protect) is not None

    def reusable_blocks(self, prompt_len: int,
                        hashes: Sequence[PrefixHash] = ()) -> List[int]:
        """Pool blocks the longest cached prefix chain currently maps to
        — what an admission of this request would reuse.  The engine
        unions these over a whole admission batch into the ``protect``
        set, so an earlier admission's LRU eviction can't take a block a
        later gate-approved item was counting on."""
        return list(self._reusable(hashes, prompt_len))

    def admit(self, slot: int, prompt_len: int, max_new: int,
              hashes: Sequence[PrefixHash] = (), *,
              protect: Sequence[int] = ()) -> Tuple[List[int], int]:
        """Reserve ``slot``'s whole footprint: returns ``(row, reused_len)``
        — the block-table row (reused prefix blocks first, fresh blocks
        after) and the block-aligned position prefill starts at.
        ``protect``: cached blocks same-batch admissions will reuse —
        never evicted here (same contract as :meth:`probe`).  Raises
        ``RuntimeError`` when the pool can't cover it (callers gate on
        :meth:`can_admit` / ``check_budget`` first)."""
        if slot in self._rows:
            raise ValueError(f"slot {slot} already holds blocks")
        reused = self._reusable(hashes, prompt_len)
        guard = list(reused) + list(protect)
        need = self.blocks_needed(prompt_len, max_new) - len(reused)
        if need > len(self._free) + self._evictable(protect=guard):
            raise RuntimeError(
                f"kv pool exhausted: need {need} blocks, "
                f"{len(self._free)} free + "
                f"{self._evictable(protect=guard)} evictable")
        # pin the reused chain FIRST (a reused block must not be the LRU
        # victim of its own admission), then take free / evict LRU
        for bid in reused:
            self._refs[bid] += 1
            self._prefix.move_to_end(self._cached_id[bid])
        fresh: List[int] = []
        for _ in range(need):
            if not self._free:
                self._evict_lru_cached(protect=protect)
            fresh.append(self._free.pop(0))
        row = reused + fresh
        for bid in fresh:
            self._refs[bid] += 1
        self._rows[slot] = row
        self._hashes[slot] = tuple(hashes)
        self._plen[slot] = prompt_len
        self._registered[slot] = len(reused)
        n_prompt_blocks = prompt_len // self.block_size
        self.prefix_hit_blocks += len(reused)
        self.prefix_miss_blocks += max(0, n_prompt_blocks - len(reused))
        self.prefix_hit_tokens += len(reused) * self.block_size
        self.blocks_admitted_total += len(row)
        return row, len(reused) * self.block_size

    def _evict_lru_cached(self, protect: Sequence[int] = ()) -> None:
        """Free the oldest cache entry whose block no tenant holds and
        no pending same-batch reuse pins (``protect``).  Ineligible
        entries are SKIPPED, not popped — destroying a tenant-held entry
        frees nothing and silently loses the shared prefix for every
        future request that would have hit it."""
        ps = set(protect)
        for h, bid in list(self._prefix.items()):
            if self._refs[bid] == 0 and bid not in ps:
                del self._prefix[h]
                del self._cached_id[bid]
                self._free.append(bid)
                return
        raise RuntimeError("kv pool exhausted: no evictable cache entry")

    # -- prefix registration -------------------------------------------------

    def note_progress(self, slot: int, cursor: int) -> None:
        """Called after each prefill dispatch: prompt blocks now fully
        written (``(b+1)·bs <= cursor``, and fully inside the prompt —
        the block decode writes into is private forever) become
        shareable cache entries, LRU-bounded."""
        if self.prefix_cache_blocks <= 0 or slot not in self._rows:
            return
        hashes, row = self._hashes[slot], self._rows[slot]
        plen = self._plen[slot]
        b = self._registered[slot]
        while (b < len(hashes) and (b + 1) * self.block_size <= cursor
               and (b + 1) * self.block_size <= plen):
            h = hashes[b]
            bid = row[b]
            if h not in self._prefix and bid not in self._cached_id:
                while len(self._prefix) >= self.prefix_cache_blocks:
                    self._evict_any_lru()
                self._prefix[h] = bid
                self._cached_id[bid] = h
            b += 1
        self._registered[slot] = b

    def _evict_any_lru(self) -> None:
        """Capacity eviction: drop the oldest entry; its block frees
        only once no tenant holds it."""
        h, bid = self._prefix.popitem(last=False)
        del self._cached_id[bid]
        if self._refs[bid] == 0:
            self._free.append(bid)

    # -- release ------------------------------------------------------------

    def release(self, slot: int) -> List[int]:
        """Drop ``slot``'s tenancy.  Returns the block ids whose
        refcount hit zero AND that no cache entry pins — the ones the
        device ``evict`` program zeroes and the free list regains.
        Cache-pinned blocks stay resident (that is the prefix cache)."""
        row = self._rows.pop(slot, None)
        if row is None:
            return []
        self._hashes.pop(slot, None)
        self._plen.pop(slot, None)
        self._registered.pop(slot, None)
        freed: List[int] = []
        for bid in row:
            self._refs[bid] -= 1
            if self._refs[bid] == 0 and bid not in self._cached_id:
                self._free.append(bid)
                freed.append(bid)
        self.blocks_released_total += len(row)
        return freed

    def slot_row(self, slot: int) -> Optional[List[int]]:
        return self._rows.get(slot)
