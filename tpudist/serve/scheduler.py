"""Iteration-level request scheduler: the host half of continuous batching.

The engine (:mod:`tpudist.serve.engine`) exposes slots; this module
decides WHAT goes into them.  Responsibilities, in the order a request
meets them:

- **admission control** — a request is checked against the engine's
  budget rule (prompt + max_new fits the KV cache; prompts longer than
  one prefill chunk are admitted and prefilled chunk by chunk) and the
  queue bound AT SUBMIT TIME, synchronously: the
  caller gets an :class:`AdmissionError` with a machine-readable
  ``reason`` instead of a request that can never complete
  (reject-with-reason backpressure — a bounded queue is the only thing
  standing between a traffic spike and an unbounded-memory host);
- **priority-ordered FIFO assignment** — each engine iteration, the
  server pulls up to ``len(free_slots)`` requests off the queue head;
  the queue is ordered by ``priority`` (higher first) and arrival order
  within a class, so fairness is arrival order among equals and the
  budget is the slot count;
- **deadline enforcement** — a request carries an optional relative
  ``deadline_s``; expired requests finish with reason ``"deadline"``
  whether they are still queued (checked when pulled) or mid-decode
  (checked by the server every iteration).

Thread contract: ``submit`` is called from any number of ingestion
threads; ``take``/``drain`` from the single engine thread.  Everything
shared sits behind one lock + condition.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import warnings
from typing import Callable, List, Optional

import numpy as np

from tpudist.telemetry.trace import new_trace_id

#: THE finish-reason registry: every reason a handle can carry
#: (``finish_reason`` is always one of these once ``done`` is set),
#: name → one-line contract.  The serving loops emit these as string
#: literals at ~40 sites across ``serve/*.py``; this dict is the single
#: place that enumerates and documents them, and
#: ``tests/test_finish_reasons.py`` is the gate (the env-var-inventory
#: pattern): every literal passed to a ``_finish*`` call must be
#: registered here AND documented in ``docs/ARCHITECTURE.md``, and every
#: registered reason must still be emitted somewhere.  Telemetry
#: consumers (the aggregate report's ``finish_reasons`` counts, the
#: live ``tpudist_requests_finished_total{reason=}`` counter) key on
#: these names, so an unregistered reason is an unqueryable one.
FINISH_REASONS = {
    "length": "completed its max_new output-token budget",
    "eos": "emitted its per-request stop token",
    "deadline": "missed its relative deadline (queued or mid-decode)",
    "shutdown": "cut off by a non-graceful server stop (dead engine "
                "loop, hard drain, never-started server)",
    "cache_full": "hit a full KV cache with budget unspent — only "
                  "reachable when the admission budget rule is bypassed "
                  "(finished loudly instead of decoding garbage)",
    "worker_lost": "its pool worker died with NO survivor to recover "
                   "onto (with survivors the lane replays and finishes "
                   "normally)",
    "handoff_corrupt": "rode a KV-handoff package the decode pool "
                       "rejected (schema mismatch or failed integrity "
                       "digest)",
    "preempted": "parked in the host KV tier by a higher-priority "
                 "arrival and cut off (drain/stop/pool collapse) before "
                 "it could resume — an in-flight resume finishes with "
                 "its normal reason instead",
    "shed_load": "shed from the queue by the SLO-aware overload "
                 "controller: measured attainment of the protected "
                 "priority class fell below target, so queued "
                 "lower-priority work was finished with a reason "
                 "instead of starving it",
    "session_resumed": "completed its max_new budget on a lane resumed "
                       "from the host KV tier without recompute (the "
                       "multi-turn no-recompute path; eos/deadline "
                       "still win when they fire first)",
    "replica_lost": "its fleet replica died mid-serve and the router "
                    "could not re-home it — no healthy sibling had "
                    "headroom within the bounded retry budget (with a "
                    "survivor available the lane re-homes and finishes "
                    "normally, duplicates dropped)",
    "router_spill": "an inner per-replica attempt the fleet router "
                    "ABANDONED when it re-homed the request onto a "
                    "sibling (replica marked dead, or rejected/timed "
                    "out mid-admission) — the caller-facing handle "
                    "lives on and finishes with the sibling's reason; "
                    "this reason only ever marks the orphaned attempt",
    "adapter_missing": "named a per-tenant adapter no longer resident in "
                       "the pool when its lane had to (re-)bind — a "
                       "raced unload between admission and placement, or "
                       "a handoff/host-tier re-bind onto a pool that "
                       "never loaded the name (submit-time misses reject "
                       "with the same reason instead; the engine NEVER "
                       "silently serves base-model output for an "
                       "adapter request)",
    "stop_sequence": "matched one of its per-request stop sequences on "
                     "the delivered stream (host-side suffix match on "
                     "the packed block fetch, block-boundary straddles "
                     "included; the stop tokens stay in the output)",
    "grammar_violation": "a constrained lane's emitted token broke its "
                         "grammar's host shadow automaton — the stream "
                         "is truncated before the violating token "
                         "(defense in depth: the device-side mask makes "
                         "this unreachable unless the pool tables and "
                         "the host shadow diverge)",
}


class AdmissionError(RuntimeError):
    """A request the scheduler refused; ``reason`` is machine-readable
    (``queue_full``, ``draining``, ``budget_exceeded: ...``,
    ``empty_prompt``, ``kv_exhausted: ...`` — a paged-KV footprint no
    empty pool could ever hold —, ``adapter_missing`` — the named
    per-tenant adapter is not loaded in the pool —,
    ``invalid_grammar: ...`` — an uncompilable/unsatisfiable grammar,
    a grammar+json_schema double ask, or a grammar without ``eos_id``
    —, ``constrain_disabled`` — a grammar on a server without the
    structured-output pool —, ``invalid_stop: ...`` — a malformed stop
    sequence —, ``invalid_logprobs``/``logprobs_unavailable: ...`` — a
    bad or over-wide top-n ask)."""

    def __init__(self, reason: str):
        super().__init__(f"request rejected: {reason}")
        self.reason = reason


@dataclasses.dataclass
class Request:
    """One generation request (token-id space; tokenization is the
    caller's concern, as everywhere else in the LM family)."""

    prompt: np.ndarray  # [plen] int32
    max_new: int
    temperature: float = 0.0  # 0 = greedy (the token-equivalence mode)
    deadline_s: Optional[float] = None  # relative to submit; None = none
    seed: int = 0  # per-request sampling stream (temperature > 0)
    eos_id: Optional[int] = None  # stop token: finish "eos" on emission
    on_token: Optional[Callable[[int, int], None]] = None  # (token, index)
    #: prompt prefix hash chain, stamped ONCE at submit (paged engine:
    #: shared-prefix block reuse keys on it; admission never re-hashes)
    prefix_hashes: tuple = ()
    #: speculative-decoding opt: None = the server's default (speculate
    #: when the engine has a draft), False = this request decodes on the
    #: plain per-token stream even on a spec engine (its lane rides the
    #: same programs with acceptance forced to zero — the mixed
    #: spec/non-spec traffic story), True = explicit opt-in.
    spec: Optional[bool] = None
    #: tenant label: rides into telemetry (``request_finished``), the
    #: live metrics registry (per-tenant latency sketches + SLO
    #: attainment), and ``/statusz`` per-tenant in-flight.  None =
    #: untagged (pools under "default" in per-tenant views).
    tenant: Optional[str] = None
    #: priority class (higher = more important; default 0).  Orders the
    #: queue (FIFO within a class), and on a host-tier-enabled server a
    #: higher-priority arrival may PREEMPT a strictly-lower-priority
    #: decode lane (export to host RAM, resume later byte-identically).
    priority: int = 0
    #: multi-turn session id: on a host-tier-enabled server, a finished
    #: turn's KV lane parks in host RAM under ``(tenant, session)`` and
    #: the session's next turn (whose prompt must EXTEND the parked
    #: context token-for-token) resumes it without recompute.  None =
    #: stateless request, never parked.
    session: Optional[str] = None
    #: per-tenant adapter NAME (tpudist.serve.adapters): the request
    #: decodes through ``base(x) + gather(B)·gather(A)·x`` with this
    #: adapter's rank-r factors gathered per slot from the paged
    #: adapter pool.  None = base model (the bit-exact base-only
    #: path).  Admission rejects ``adapter_missing`` when the name is
    #: not loaded; a lane that must re-bind on another pool (handoff /
    #: host-tier resume) carries the name in its package.
    adapter: Optional[str] = None
    #: compiled grammar (tpudist.constrain.TokenGrammar): the request's
    #: output is constrained token-by-token by the grammar's dense mask
    #: tables (bound into the engine's device pool at placement) and
    #: tracked by its host shadow automaton on delivery.  Compiled ONCE
    #: at submit (uncompilable grammars reject ``invalid_grammar``
    #: synchronously); None = unconstrained (the bit-exact free path).
    grammar: Optional[object] = None
    #: stop sequences: tuple of token-id tuples, matched host-side as a
    #: suffix of the delivered stream after every block fetch (straddles
    #: across block boundaries match too).  First match finishes the
    #: request ``stop_sequence``; the stop tokens stay in the output
    #: (the eos convention).
    stop: tuple = ()
    #: top-n logprobs per emitted token (0 = off): each delivered token
    #: grows a ``(ids, logprobs)`` pair on ``handle.logprobs`` — the
    #: post-mask distribution on constrained lanes.  Capped by the
    #: server's engine-wide width (``logprobs_unavailable`` past it).
    logprobs: int = 0


class RequestHandle:
    """The caller's view of an in-flight request: streamed tokens, a
    ``done`` event, the finish reason, and the latency stamps the
    serving metrics (TTFT/TPOT) are computed from."""

    def __init__(self, request: Request, req_id: int):
        self.request = request
        self.id = req_id
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self._done = threading.Event()
        now = time.monotonic()
        self.t_submit = now
        self.t_admitted: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.slot: Optional[int] = None
        #: per-request trace id (tpudist.telemetry.trace), minted at
        #: submit and threaded through admission, prefill, the
        #: serialized handoff package, decode lanes, recovery replays,
        #: and request_finished — the cross-pool join key.
        self.trace_id: str = new_trace_id()
        #: disaggregated serving only (tpudist.serve.disagg): when the
        #: prefill pool finished the prompt (and sampled token 0), and
        #: when the KV landed in a decode-pool slot — the handoff-wait
        #: gap between them is the disagg coordinator's own latency.
        self.t_prefill_done: Optional[float] = None
        self.t_decode_start: Optional[float] = None
        #: worker attribution for the exported timeline: which prefill
        #: worker ran the prompt, and one (worker, t_start, t_end)
        #: segment per decode residency — a lane that replays onto a
        #: survivor after worker loss grows a SECOND segment, which is
        #: the visible jump in the Chrome trace.
        self.prefill_worker: Optional[int] = None
        self.decode_segments: List[list] = []
        #: host-tier bookkeeping: True once this request was served from
        #: a resumed session lane (its length-finish reads
        #: ``session_resumed`` so the resume path is countable from the
        #: report's finish reasons alone)
        self.resumed: bool = False
        #: structured output: the host shadow automaton's state over the
        #: DELIVERED tokens (request.grammar only; parked sessions carry
        #: it across turns).  The server advances it in _deliver_block
        #: and truncates ``grammar_violation`` on divergence.
        self.gstate: int = 0
        #: per-token top-n logprobs (request.logprobs > 0 only): one
        #: ``(ids, logprobs)`` pair per delivered token, or None for
        #: tokens sampled by the prefill programs (the first token of a
        #: stream), sliced to the request's asked width.
        self.logprobs: List = []

    # -- caller side --------------------------------------------------------

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request finishes; True iff it did."""
        return self._done.wait(timeout)

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token, queue wait included (submit → token 0)."""
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def tpot_s(self) -> Optional[float]:
        """Time per output token AFTER the first (the steady decode
        rate); None until at least two tokens exist."""
        if (self.t_done is None or self.t_first_token is None
                or len(self.tokens) < 2):
            return None
        return (self.t_done - self.t_first_token) / (len(self.tokens) - 1)

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.t_admitted is None:
            return None
        return self.t_admitted - self.t_submit

    @property
    def handoff_wait_s(self) -> Optional[float]:
        """Prefill-done → decode-slot-installed gap (disaggregated
        serving only; None on the single-pool path)."""
        if self.t_prefill_done is None or self.t_decode_start is None:
            return None
        return self.t_decode_start - self.t_prefill_done

    # -- engine side (single engine thread) ---------------------------------

    def _expired(self, now: float) -> bool:
        d = self.request.deadline_s
        return d is not None and (now - self.t_submit) > d

    def _deliver(self, token: int) -> None:
        now = time.monotonic()
        if self.t_first_token is None:
            self.t_first_token = now
        self.tokens.append(int(token))
        cb = self.request.on_token
        if cb is not None:
            try:
                cb(int(token), len(self.tokens) - 1)
            except Exception as e:  # a user callback must not kill the loop
                warnings.warn(f"on_token callback raised: {e!r}",
                              RuntimeWarning, stacklevel=2)

    def _finish(self, reason: str) -> None:
        if self._done.is_set():
            return
        self.finish_reason = reason
        self.t_done = time.monotonic()
        self._done.set()


class Scheduler:
    """Bounded FIFO + admission control (module doc has the contract)."""

    def __init__(self, *, queue_limit: int,
                 check_budget: Callable[[int, int], Optional[str]],
                 default_max_new: int = 64,
                 default_deadline_s: Optional[float] = None,
                 prefix_hasher: Optional[Callable] = None,
                 check_adapter: Optional[Callable] = None,
                 compile_grammar_fn: Optional[Callable] = None,
                 max_logprobs: int = 0):
        if queue_limit < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        self.queue_limit = queue_limit
        self.check_budget = check_budget
        self.default_max_new = default_max_new
        self.default_deadline_s = default_deadline_s
        #: prompt → prefix hash chain, run once per submit (the paged
        #: server passes ``paged_alloc.hash_chain`` at its block size;
        #: None stamps an empty chain — no sharing, no hashing cost)
        self.prefix_hasher = prefix_hasher
        #: adapter-name admission gate (the serving layer passes the
        #: engine's ``has_adapter``): ``name -> Optional[reason]`` — a
        #: request naming an unloaded adapter rejects ``adapter_missing``
        #: NOW instead of occupying queue+slot just to fail binding
        self.check_adapter = check_adapter
        #: grammar compiler (the serving layer passes a closure over the
        #: engine's vocab/state-cap): ``(regex, json_schema, eos_id) ->
        #: TokenGrammar``, raising on anything uncompilable — run
        #: OUTSIDE the lock (compilation is O(states × vocab)), with
        #: failures rejecting ``invalid_grammar`` synchronously.  None =
        #: structured output off (grammar asks reject
        #: ``constrain_disabled``).
        self.compile_grammar_fn = compile_grammar_fn
        #: engine-wide top-n logprobs width (0 = off); per-request asks
        #: past it reject ``logprobs_unavailable`` — the width is a
        #: compile-time constant of the decode programs, so it cannot
        #: stretch per request
        self.max_logprobs = int(max_logprobs)
        self._q: "collections.deque[RequestHandle]" = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._refuse_reason: Optional[str] = None
        self._next_id = 0
        self.rejected = 0
        #: optional extra admission gate (the overload controller):
        #: ``Request, pending -> Optional[reason]``, consulted under the
        #: lock AFTER the queue/budget checks — must be cheap (gauge
        #: reads), must not block.
        self.admission_gate: Optional[Callable] = None

    # -- ingestion side -----------------------------------------------------

    def _reject(self, reason: str) -> None:
        with self._lock:
            self.rejected += 1
        raise AdmissionError(reason)

    def _compile_grammar(self, grammar, json_schema, eos_id):
        """Compile a submit's grammar ask (outside the lock — O(states
        × vocab) work must not serialize submitters) or reject."""
        if grammar is None and json_schema is None:
            return None
        if self.compile_grammar_fn is None:
            self._reject("constrain_disabled")
        if grammar is not None and json_schema is not None:
            self._reject("invalid_grammar: pass exactly one of "
                         "grammar/json_schema")
        if eos_id is None:
            self._reject("invalid_grammar: a grammar requires eos_id — "
                         "the automaton can only terminate on EOS in an "
                         "accept state")
        try:
            return self.compile_grammar_fn(grammar, json_schema,
                                           int(eos_id))
        except ValueError as e:
            self._reject(f"invalid_grammar: {e}")

    def _norm_stop(self, stop) -> tuple:
        """Normalize a submit's ``stop`` ask to a tuple of token-id
        tuples (a bare int is a single-token sequence) or reject."""
        if not stop:
            return ()
        seqs = []
        try:
            for s in stop:
                if isinstance(s, (int, np.integer)):
                    seqs.append((int(s),))
                else:
                    t = tuple(int(x) for x in s)
                    if not t:
                        self._reject("invalid_stop: empty stop sequence")
                    seqs.append(t)
        except (TypeError, ValueError):
            self._reject("invalid_stop: stop must be a list of token "
                         "ids or token-id sequences")
        return tuple(seqs)

    def submit(self, prompt, *, max_new: Optional[int] = None,
               temperature: float = 0.0, deadline_s: Optional[float] = None,
               seed: Optional[int] = None, eos_id: Optional[int] = None,
               on_token: Optional[Callable[[int, int], None]] = None,
               spec: Optional[bool] = None, tenant: Optional[str] = None,
               priority: int = 0, session: Optional[str] = None,
               adapter: Optional[str] = None,
               grammar: Optional[str] = None,
               json_schema=None,
               stop=None,
               logprobs: int = 0,
               ) -> RequestHandle:
        """Admit a request or raise :class:`AdmissionError` (backpressure
        is synchronous — the caller learns NOW, not after a timeout).
        ``priority`` orders the queue (FIFO within a class; higher wins);
        ``session`` keys the host-tier multi-turn resume; ``adapter``
        names the per-tenant LoRA adapter the lane decodes through
        (must be loaded — else ``adapter_missing``); ``grammar`` (a
        regex) / ``json_schema`` (a schema mapping) constrain the output
        — compiled HERE, so an uncompilable grammar rejects
        ``invalid_grammar`` now, and a grammar requires ``eos_id`` (the
        automaton only terminates on EOS in an accept state); ``stop``
        is a list of stop sequences (token ids, or lists of token ids);
        ``logprobs`` asks for top-n (id, logprob) pairs per token."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        tg = self._compile_grammar(grammar, json_schema, eos_id)
        stop_seqs = self._norm_stop(stop)
        n_lp = int(logprobs or 0)
        if n_lp < 0:
            self._reject("invalid_logprobs")
        if n_lp > self.max_logprobs:
            self._reject(
                "logprobs_unavailable: asked top-%d, the engine computes "
                "top-%d (TPUDIST_SERVE_LOGPROBS)"
                % (n_lp, self.max_logprobs))
        # Deadline convention matches TPUDIST_SERVE_DEADLINE_S: ``None``
        # inherits the server default, ``<= 0`` means explicitly NO
        # deadline — the per-request opt-out when a default is set.
        if deadline_s is None:
            deadline = self.default_deadline_s
        else:
            deadline = float(deadline_s) if deadline_s > 0 else None
        resolved_max_new = (self.default_max_new if max_new is None
                            else int(max_new))
        # hashed OUTSIDE the lock (O(plen) work must not serialize
        # concurrent submitters behind one long prompt), and only when
        # an advisory peek says the request stands a chance — a rejected
        # submit must not pay O(plen) hashing it will throw away.  The
        # peek is racy by design: if the queue drains between here and
        # the lock, the request admits with an empty chain and simply
        # doesn't share (prefix reuse is opportunistic).
        hashes: tuple = ()
        if (self.prefix_hasher is not None
                and self._refuse_reason is None
                and len(self._q) < self.queue_limit
                and self.check_budget(len(prompt), resolved_max_new) is None):
            hashes = tuple(self.prefix_hasher(prompt))
        req = Request(
            prompt=prompt,
            max_new=resolved_max_new,
            temperature=float(temperature),
            deadline_s=deadline,
            seed=0 if seed is None else int(seed),
            eos_id=None if eos_id is None else int(eos_id),
            on_token=on_token,
            prefix_hashes=hashes,
            spec=spec,
            tenant=None if tenant is None else str(tenant),
            priority=int(priority),
            session=None if session is None else str(session),
            adapter=None if adapter is None else str(adapter),
            grammar=tg,
            stop=stop_seqs,
            logprobs=n_lp,
        )
        with self._lock:
            reason = self._refuse_reason
            if reason is None and len(self._q) >= self.queue_limit:
                reason = "queue_full"
            if reason is None:
                reason = self.check_budget(len(prompt), req.max_new)
            if reason is None and req.adapter is not None \
                    and self.check_adapter is not None:
                reason = self.check_adapter(req.adapter)
            if reason is None and self.admission_gate is not None:
                # the overload controller's reject-with-reason gate
                # (SLO-aware shedding, per-tenant fair share) — cheap
                # gauge reads by contract
                reason = self.admission_gate(req, len(self._q))
            if reason is not None:
                self.rejected += 1
                raise AdmissionError(reason)
            handle = RequestHandle(req, self._next_id)
            self._next_id += 1
            if self._q and self._q[-1].request.priority < req.priority:
                # priority insert: before the first strictly-lower-
                # priority entry, after every same-or-higher one (FIFO
                # within a class).  O(queue_limit), and the tail check
                # above keeps the common all-default-priority path O(1).
                for i, other in enumerate(self._q):
                    if other.request.priority < req.priority:
                        self._q.insert(i, handle)
                        break
            else:
                self._q.append(handle)
            self._work.notify_all()
            return handle

    # -- engine side --------------------------------------------------------

    def take(self, k: int, now: Optional[float] = None,
             admit: Optional[Callable[[RequestHandle], bool]] = None
             ) -> List[RequestHandle]:
        """Pop up to ``k`` admissible requests (FIFO).  Requests whose
        deadline already expired in the queue finish as ``"deadline"`` on
        the spot; they are returned too (already ``done``) so the caller
        can account for them, but they do not consume an admission slot.

        ``admit``: an extra per-request gate (the paged engine's
        free-block budget).  The FIRST refusal stops the take and the
        request stays at the queue head — deliberate head-of-line
        blocking, because skipping past it would starve large-footprint
        requests forever under steady small-request load."""
        if k <= 0:
            return []
        now = time.monotonic() if now is None else now
        out: List[RequestHandle] = []
        alive = 0
        with self._lock:
            while self._q and alive < k:
                h = self._q.popleft()
                if h._expired(now):
                    h._finish("deadline")
                    out.append(h)
                    continue
                if admit is not None and not admit(h):
                    self._q.appendleft(h)  # stays the FIFO head
                    break
                alive += 1
                out.append(h)
        return out

    def expire_queued(self, now: Optional[float] = None
                      ) -> List[RequestHandle]:
        """Finish (and remove) every queued request whose deadline has
        passed — called every engine iteration, so a queued request's
        deadline holds even while every slot is busy with long decodes
        (``take`` only runs when a slot frees).  Returns the expired
        handles for accounting."""
        now = time.monotonic() if now is None else now
        out: List[RequestHandle] = []
        with self._lock:
            keep: "collections.deque[RequestHandle]" = collections.deque()
            while self._q:
                h = self._q.popleft()
                if h._expired(now):
                    h._finish("deadline")
                    out.append(h)
                else:
                    keep.append(h)
            self._q = keep
        return out

    def head_info(self) -> Optional[dict]:
        """A peek at the queue head (no pop): the fields the server's
        preemption decision needs — is a HIGHER-priority request waiting
        than some decoding lane, and what footprint would it take.
        ``None`` on an empty queue."""
        with self._lock:
            if not self._q:
                return None
            r = self._q[0].request
            return {"priority": r.priority, "prompt_len": len(r.prompt),
                    "max_new": r.max_new,
                    "prefix_hashes": r.prefix_hashes,
                    "session": r.session}

    def shed(self, predicate: Callable[[RequestHandle], bool]
             ) -> List[RequestHandle]:
        """Finish (and remove) every queued request ``predicate`` marks
        — the overload controller's load-shedding half: queued
        lower-priority work ends with reason ``"shed_load"`` NOW so the
        protected class's SLO attainment can recover, instead of
        timing out one deadline at a time.  Returns the shed handles for
        accounting (the caller emits their ``request_finished``)."""
        out: List[RequestHandle] = []
        with self._lock:
            keep: "collections.deque[RequestHandle]" = collections.deque()
            while self._q:
                h = self._q.popleft()
                if predicate(h):
                    h._finish("shed_load")
                    out.append(h)
                else:
                    keep.append(h)
            self._q = keep
        return out

    def pending(self) -> int:
        with self._lock:
            return len(self._q)

    def wait_for_work(self, timeout: float) -> None:
        """Park the engine thread until a submit lands (or timeout — the
        loop also needs to notice drain/stop flags)."""
        with self._lock:
            if not self._q:
                self._work.wait(timeout)

    def refuse_new(self, reason: Optional[str]) -> None:
        """Turn admission off (``reason``, e.g. ``"draining"``) or back
        on (``None``).  Queued requests are unaffected — drain completes
        everything already admitted."""
        with self._lock:
            self._refuse_reason = reason
            self._work.notify_all()
