"""Prefill/decode disaggregation: separate worker pools with KV handoff.

The single-pool server interleaves prompt chunks and decode blocks on
ONE engine, so a burst of long prompts steals decode iterations from
every in-flight stream (bounded to one chunk per iteration by chunked
prefill, but still stolen).  This coordinator splits the two phases the
way disaggregated serving systems do (DistServe/Splitwise lineage):

- a **prefill pool** of workers that ONLY teacher-force prompts
  (``start_batch`` + ``advance_prefill``; their slots never decode);
- a **decode pool** of workers that ONLY run fused decode blocks;
- a bounded **handoff queue** between them carrying each finished
  prompt's KV package (:meth:`tpudist.serve.engine.SlotEngine.
  export_slot`): the KV lane, the SlotState row, and the budget
  shadows.  ``import_slot`` installs it in a free decode slot and the
  request continues BYTE-IDENTICALLY — the sampling stream is
  ``fold_in(key, count)``, indifferent to which engine or slot hosts
  the request (the oracle tests pin greedy and sampled continuation).

TTFT is now a prefill-pool number (token 0 is sampled from the final
prompt logits, in the prefill worker) and TPOT a decode-pool number;
the telemetry serving section splits them per pool, plus the
coordinator's own ``handoff_wait`` gap.

Transfer modes (``ServeConfig.handoff``): ``"device"`` passes the
device arrays through (in-mesh handoff — on one host a reference copy,
on a real mesh a device-to-device transfer scheduled by the runtime);
``"serial"`` round-trips every leaf through host bytes
(``serialize_package``/``deserialize_package``) — the stand-in for the
multi-process CPU rig, where KV crosses a process boundary as a
serialized block transfer.  Both modes are byte-preserving (int8 pools
re-quantize bit-exactly on import; tests pin it).

Thread contract mirrors :class:`tpudist.serve.server.InferenceServer`:
one engine thread drives every engine in both pools (the device
programs serialize anyway on one host), any number of threads submit,
SIGTERM/``close()`` drain everything admitted.  If a pool worker dies
(any engine-loop exception), the loop aborts every outstanding request
with reason ``"shutdown"`` — the same no-stranded-waiters contract as
the single-pool server; requests never hang on a dead pool.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

from tpudist.serve.engine import SlotEngine
from tpudist.serve.scheduler import AdmissionError, RequestHandle, Scheduler

_IDLE_WAIT_S = 0.01


def _np_dtype(name: str):
    """Resolve a dtype NAME back to a numpy dtype.  Names, not
    ``dtype.str``: the struct codes of the ml_dtypes family degrade to
    raw void ("<V2" for bfloat16), which would silently destroy a bf16
    KV lane on the wire."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def serialize_package(pkg: dict) -> dict:
    """Flatten a KV-handoff package to host bytes — what would ride the
    wire between a prefill process and a decode process.  Keeps the
    treedef (both ends share the engine geometry, so the structure is
    common knowledge; a cross-host protocol would pin it by schema).
    Byte-preserving for every lane dtype including bf16/int8 (tests pin
    the round trip)."""
    import jax
    import numpy as np

    flat, tree = jax.tree.flatten((pkg["lane"], pkg["state"]))
    blob = []
    for leaf in flat:
        a = np.asarray(leaf)
        blob.append((a.tobytes(), a.dtype.name, a.shape))
    return {"paged": pkg["paged"], "pos": pkg["pos"],
            "counts": pkg["counts"], "budget": pkg["budget"],
            "blob": blob, "tree": tree,
            "bytes": sum(len(b) for b, _, _ in blob)}


def deserialize_package(ser: dict) -> dict:
    """Inverse of :func:`serialize_package` (byte-preserving)."""
    import jax
    import numpy as np

    flat = [np.frombuffer(b, dtype=_np_dtype(d)).reshape(s)
            for b, d, s in ser["blob"]]
    lane, state = jax.tree.unflatten(ser["tree"], flat)
    return {"paged": ser["paged"], "pos": ser["pos"],
            "counts": ser["counts"], "budget": ser["budget"],
            "lane": lane, "state": state}


class DisaggServer:
    """Disaggregated continuous-batching server: prefill pool → KV
    handoff → decode pool.  Config rides the same
    :class:`tpudist.serve.server.ServeConfig` (``disagg=True`` selects
    this class in :func:`tpudist.serve.server.serve_forever`)."""

    def __init__(self, module, params, config=None, *,
                 install_signal_handler: bool = True):
        from tpudist.serve.server import ServeConfig

        self.config = config or ServeConfig.from_env()
        cfg = self.config
        shared = dict(
            prefill_pad=cfg.prefill_pad, paged=cfg.paged,
            kv_block=cfg.kv_block, kv_blocks=cfg.kv_blocks,
            kv_int8=cfg.kv_int8, mesh=cfg.mesh_config())
        p_slots = cfg.prefill_slots or cfg.num_slots
        # prefill workers keep the prefix cache (reuse saves prefill
        # compute — that is this pool's whole job); decode workers get
        # private blocks only (a handed-off lane never shares).
        self.prefill_pool: List[SlotEngine] = [
            SlotEngine(module, params, num_slots=p_slots, decode_block=1,
                       prefix_cache_blocks=cfg.prefix_cache_blocks,
                       attn_kernel="gather", **shared)
            for _ in range(max(1, cfg.prefill_workers))]
        # the DECODE pool owns the speculative draft (prefill workers
        # never decode, so a draft there is dead weight); handoff
        # packages are unchanged — an imported lane's draft context
        # starts cold and warms as it decodes (engine.import_slot doc)
        # the decode pool is where the paged-attention kernel earns its
        # keep (the bandwidth-bound hot path); prefill workers stay on
        # the gather path — they teacher-force, never decode
        self.decode_pool: List[SlotEngine] = [
            SlotEngine(module, params, num_slots=cfg.num_slots,
                       decode_block=cfg.decode_block,
                       prefix_cache_blocks=0,
                       spec_draft=cfg.resolve_spec_draft(module),
                       spec_k=cfg.spec_k, attn_kernel=cfg.attn_kernel,
                       **shared)
            for _ in range(max(1, cfg.decode_workers))]
        self.handoff_mode = cfg.handoff
        if self.handoff_mode not in ("device", "serial"):
            raise ValueError(
                f"handoff must be 'device' or 'serial', got {cfg.handoff!r}")
        #: bounded pending-handoff queue: (handle, package) — a full
        #: queue stalls exports (the lane waits in its prefill slot),
        #: which in turn backpressures admission via free prefill slots.
        self._handoff: "collections.deque[Tuple[RequestHandle, dict]]" = \
            collections.deque()
        self.handoff_limit = max(1, cfg.handoff_queue)
        pe, de = self.prefill_pool[0], self.decode_pool[0]

        def check_budget(plen: int, max_new: int) -> Optional[str]:
            return pe.check_budget(plen, max_new) \
                or de.check_budget(plen, max_new)

        hasher = None
        if cfg.paged and cfg.prefix_cache_blocks > 0:
            from tpudist.serve.paged_alloc import hash_chain

            bs = pe.paged_cfg.block_size
            hasher = lambda prompt: hash_chain(prompt, bs)  # noqa: E731
        self.scheduler = Scheduler(
            queue_limit=cfg.queue_limit, check_budget=check_budget,
            default_max_new=cfg.max_new, default_deadline_s=cfg.deadline_s,
            prefix_hasher=hasher)
        self._install_signal = install_signal_handler
        self._installed_preemption = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._draining = False
        #: (pool, worker, slot) → handle; pool ∈ {"prefill", "decode"}
        self._slot_handles: Dict[Tuple[str, int, int], RequestHandle] = {}
        self.completed = 0
        self.tokens_out = 0
        self.handoffs = 0
        self.handoff_bytes = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "DisaggServer":
        if self._thread is not None:
            raise RuntimeError("server already started")
        from tpudist import telemetry
        from tpudist.runtime import preemption

        telemetry.ensure_started()
        telemetry.event(
            "serve_disagg_config",
            prefill_workers=len(self.prefill_pool),
            decode_workers=len(self.decode_pool),
            prefill_slots=self.prefill_pool[0].num_slots,
            decode_slots=self.decode_pool[0].num_slots,
            handoff=self.handoff_mode,
            mesh=self.decode_pool[0].spmd_stats().get("mesh"))
        if self._install_signal:
            self._installed_preemption = preemption.install()
        self._thread = threading.Thread(
            target=self._loop, name="tpudist-serve-disagg", daemon=True)
        self._thread.start()
        return self

    def submit(self, prompt, *, max_new: Optional[int] = None,
               temperature: float = 0.0, deadline_s: Optional[float] = None,
               seed: Optional[int] = None, eos_id: Optional[int] = None,
               on_token=None, spec: Optional[bool] = None) -> RequestHandle:
        from tpudist import telemetry

        try:
            return self.scheduler.submit(
                prompt, max_new=max_new, temperature=temperature,
                deadline_s=deadline_s, seed=seed, eos_id=eos_id,
                on_token=on_token, spec=spec)
        except AdmissionError as e:
            telemetry.event("serve_rejected", reason=e.reason)
            raise

    def drain(self, timeout: Optional[float] = None) -> bool:
        self._stop.set()
        t = self._thread
        ok = True
        if t is not None:
            t.join(timeout)
            ok = not t.is_alive()
        if ok:
            self.scheduler.refuse_new("draining")
            self._abort_outstanding()
        return ok

    def close(self, timeout: Optional[float] = None) -> bool:
        ok = self.drain(timeout)
        if self._installed_preemption:
            from tpudist.runtime import preemption

            preemption.reset()
            self._installed_preemption = False
        return ok

    def stats(self) -> dict:
        dec = {"blocks": 0, "tokens": 0, "steps": 0,
               "dispatch_s": 0.0, "sync_s": 0.0, "kv_read_bytes": 0}
        for eng in self.decode_pool:
            for k, v in eng.decode_stats().items():
                dec[k] += v
        spec = {"enabled": self.decode_pool[0].spec, "blocks": 0,
                "lane_passes": 0, "tokens": 0, "accepted": 0,
                "drafted": 0, "rollbacks": 0,
                "draft_s": 0.0, "verify_s": 0.0, "sync_s": 0.0}
        for eng in self.decode_pool:
            st = eng.spec_stats()
            for k in ("blocks", "lane_passes", "tokens", "accepted",
                      "drafted", "rollbacks", "draft_s", "verify_s",
                      "sync_s"):
                spec[k] += st[k]
        spec["accepted_per_pass"] = (spec["tokens"] / spec["lane_passes"]
                                     if spec["lane_passes"] else None)
        spec["acceptance_rate"] = (spec["accepted"] / spec["drafted"]
                                   if spec["drafted"] else None)
        return {
            "completed": self.completed,
            "rejected": self.scheduler.rejected,
            "tokens_out": self.tokens_out,
            "pending": self.scheduler.pending(),
            "handoffs": self.handoffs,
            "handoff_bytes": self.handoff_bytes,
            "handoff_queued": len(self._handoff),
            "prefill_pool": {
                "workers": len(self.prefill_pool),
                "slots": self.prefill_pool[0].num_slots,
                "occupied": sum(e.num_occupied for e in self.prefill_pool),
                "compile_counts": self.prefill_pool[0].compile_counts(),
            },
            "decode_pool": {
                "workers": len(self.decode_pool),
                "slots": self.decode_pool[0].num_slots,
                "active": sum(e.num_active for e in self.decode_pool),
                "compile_counts": self.decode_pool[0].compile_counts(),
                "decode": dec,
                "spec": spec,
                "kv": self.decode_pool[0].kv_stats(),
            },
            "spmd": self.decode_pool[0].spmd_stats(),
        }

    # -- the engine loop ----------------------------------------------------

    def _should_drain(self) -> bool:
        if self._stop.is_set():
            return True
        from tpudist.runtime import preemption

        return preemption.requested()

    def _abort_outstanding(self) -> None:
        for key in list(self._slot_handles):
            h = self._slot_handles.pop(key)
            h._finish("shutdown")
            self._note_finished(h)
        while self._handoff:
            h, _ = self._handoff.popleft()
            h._finish("shutdown")
            self._note_finished(h)
        for h in self.scheduler.take(1 << 30):
            if not h.done:
                h._finish("shutdown")
            self._note_finished(h)

    def _loop(self) -> None:
        from tpudist import telemetry

        try:
            self._run_loop()
        except BaseException as e:
            # a dying pool worker must not strand waiters (module doc)
            telemetry.event("serve_loop_error", error=repr(e))
            raise
        finally:
            self.scheduler.refuse_new("draining")
            self._abort_outstanding()

    def _outstanding(self) -> int:
        return (self.scheduler.pending() + len(self._slot_handles)
                + len(self._handoff))

    def _run_loop(self) -> None:
        from tpudist import telemetry

        sched = self.scheduler
        while True:
            if not self._draining and self._should_drain():
                self._draining = True
                sched.refuse_new("draining")
                telemetry.event("serve_drain", pending=sched.pending(),
                                active=self._outstanding())
            now = time.monotonic()
            for key, h in list(self._slot_handles.items()):
                if h._expired(now):
                    self._finish_key(key, "deadline")
            # deadline sweep over the handoff queue, order-preserving
            kept = collections.deque()
            while self._handoff:
                h, pkg = self._handoff.popleft()
                if h._expired(now):
                    h._finish("deadline")
                    self._note_finished(h)
                else:
                    kept.append((h, pkg))
            self._handoff = kept
            for h in sched.expire_queued(now):
                self._note_finished(h)
            did_work = False
            did_work |= self._admit_prefill(now)
            did_work |= self._advance_prefill()
            did_work |= self._place_handoffs()
            did_work |= self._decode()
            if self._draining and self._outstanding() == 0:
                break
            if not did_work:
                if sched.pending() or self._handoff:
                    # gate-blocked (pool/slots full): nothing frees until
                    # a later iteration — don't spin the engine thread
                    time.sleep(_IDLE_WAIT_S)
                else:
                    sched.wait_for_work(_IDLE_WAIT_S)

    # -- prefill pool -------------------------------------------------------

    def _admit_prefill(self, now: float) -> bool:
        from tpudist import telemetry

        worked = False
        for w, eng in enumerate(self.prefill_pool):
            free = eng.free_slots()
            if not free:
                continue
            reserved, pinned = [0], []

            def _gate(h, _eng=eng, _reserved=reserved, _pinned=pinned):
                req = h.request
                got = _eng.kv_admission_probe(
                    len(req.prompt), req.max_new, req.prefix_hashes,
                    reserve=_reserved[0], protect=_pinned)
                if got is None:
                    return False
                # the decode pool must eventually take it too; reject
                # never — transient decode-pool pressure just queues the
                # package (bounded by the handoff queue)
                _reserved[0] += got[0]
                _pinned.extend(got[1])
                return True

            batch = self.scheduler.take(len(free), now, admit=_gate)
            alive = []
            for h in batch:
                if h.done:
                    self._note_finished(h)
                else:
                    alive.append(h)
            if not alive:
                continue
            worked = True
            items, t0 = [], time.monotonic()
            for h, slot in zip(alive, free):
                h.slot = slot
                h.t_admitted = t0
                items.append((slot, h.request.prompt, h.request.temperature,
                              h.request.seed, h.request.max_new,
                              h.request.prefix_hashes))
                self._slot_handles[("prefill", w, slot)] = h
            with telemetry.span("prefill", n=len(items), pool="prefill",
                                worker=w):
                firsts = eng.start_batch(items)
            for slot, tok in firsts.items():
                if tok is not None:
                    self._prefill_complete(w, slot, tok)
        return worked

    def _advance_prefill(self) -> bool:
        from tpudist import telemetry

        worked = False
        for w, eng in enumerate(self.prefill_pool):
            if not eng.prefilling_slots():
                continue
            worked = True
            with telemetry.span("prefill",
                                chunks=len(eng.prefilling_slots()),
                                pool="prefill", worker=w):
                done = eng.advance_prefill()
            for slot, tok in done.items():
                self._prefill_complete(w, slot, tok)
        return worked

    def _prefill_complete(self, w: int, slot: int, tok: int) -> None:
        """A prompt finished in prefill worker ``w``: deliver token 0
        (TTFT stamps here — in the prefill pool), then either finish
        (budget of 1) or export the lane for the decode pool."""
        key = ("prefill", w, slot)
        h = self._slot_handles[key]
        h.t_prefill_done = time.monotonic()
        eos = h.request.eos_id
        h._deliver(tok)
        self.tokens_out += 1
        eng = self.prefill_pool[w]
        if (eos is not None and tok == eos) \
                or len(h.tokens) >= h.request.max_new:
            del self._slot_handles[key]
            eng.evict(slot)
            h._finish("eos" if eos is not None and tok == eos else "length")
            self._note_finished(h)
            return
        if len(self._handoff) >= self.handoff_limit:
            # queue full: the lane waits in its prefill slot; retried on
            # a later iteration (the slot stays occupied → admission
            # backpressure).  Mark it ready by leaving decoding=True.
            return
        self._export(w, slot, h)

    def _export(self, w: int, slot: int, h: RequestHandle) -> None:
        eng = self.prefill_pool[w]
        pkg = eng.export_slot(slot)
        if self.handoff_mode == "serial":
            ser = serialize_package(pkg)
            self.handoff_bytes += ser["bytes"]
            pkg = ser
        eng.evict(slot)
        del self._slot_handles[("prefill", w, slot)]
        self._handoff.append((h, pkg))
        self.handoffs += 1

    def _retry_stalled_exports(self) -> bool:
        """Prefill slots whose export stalled on a full handoff queue
        (decoding=True but still in the prefill pool) retry here."""
        worked = False
        for w, eng in enumerate(self.prefill_pool):
            for slot in list(range(eng.num_slots)):
                key = ("prefill", w, slot)
                if (eng.decoding[slot] and key in self._slot_handles
                        and len(self._handoff) < self.handoff_limit):
                    self._export(w, slot, self._slot_handles[key])
                    worked = True
        return worked

    # -- handoff → decode pool ---------------------------------------------

    def _place_handoffs(self) -> bool:
        from tpudist import telemetry

        self._retry_stalled_exports()
        worked = False
        while self._handoff:
            h, pkg = self._handoff[0]
            placed = False
            for w, eng in enumerate(self.decode_pool):
                free = eng.free_slots()
                # gate on the serialized dict directly (pos/budget/paged
                # are top-level fields either way) — a full decode pool
                # must not pay a full-lane deserialization per blocked
                # loop iteration just to fail placement
                if not free or not eng.can_import(pkg):
                    continue
                self._handoff.popleft()
                raw = (deserialize_package(pkg)
                       if self.handoff_mode == "serial" else pkg)
                slot = free[0]
                t0 = time.monotonic()
                eng.import_slot(slot, raw, spec=h.request.spec)
                h.t_decode_start = time.monotonic()
                h.slot = slot
                telemetry.event(
                    "kv_handoff", worker=w, slot=slot,
                    mode=self.handoff_mode,
                    wait_s=round(h.handoff_wait_s or 0.0, 6),
                    import_s=round(h.t_decode_start - t0, 6))
                self._slot_handles[("decode", w, slot)] = h
                placed = worked = True
                break
            if not placed:
                break  # FIFO head-of-line: decode pool is full
        return worked

    # -- decode pool --------------------------------------------------------

    def _decode(self) -> bool:
        from tpudist import telemetry

        worked = False
        for w, eng in enumerate(self.decode_pool):
            for slot in eng.cache_full_slots():
                if ("decode", w, slot) in self._slot_handles:
                    self._finish_key(("decode", w, slot), "cache_full")
            if not eng.num_active:
                continue
            worked = True
            occ = eng.occupancy
            tele = telemetry.active()
            t0 = time.monotonic()
            info, blocks = eng.decode_auto()
            if tele is not None and info is not None:
                kv_occ, kv_resident = eng.kv_gauges()
                tags = {"occupancy": occ, "active": eng.num_active,
                        "k": info["k"], "tokens": info["tokens"],
                        "dispatch_s": round(info["dispatch_s"], 9),
                        "sync_s": round(info["sync_s"], 9),
                        "kv_block_occupancy": kv_occ,
                        "kv_bytes_resident": kv_resident,
                        "kv_read_bytes": info["kv_read_bytes"],
                        "pool": "decode", "worker": w}
                if info.get("spec"):
                    tags.update(accepted=info["accepted"],
                                drafted=info["drafted"],
                                rollbacks=info["rollbacks"],
                                draft_s=round(info["draft_s"], 9),
                                verify_s=round(info["verify_s"], 9))
                    tele.record_span("spec_verify", t0,
                                     time.monotonic() - t0, tags)
                else:
                    tele.record_span("decode_block", t0,
                                     time.monotonic() - t0, tags)
            for slot, toks in blocks.items():
                self._deliver_block(w, slot, toks)
        return worked

    def _deliver_block(self, w: int, slot: int, toks) -> None:
        h = self._slot_handles[("decode", w, slot)]
        eos = h.request.eos_id
        for tok in toks:
            h._deliver(tok)
            self.tokens_out += 1
            if eos is not None and tok == eos:
                self._finish_key(("decode", w, slot), "eos")
                return
            if len(h.tokens) >= h.request.max_new:
                self._finish_key(("decode", w, slot), "length")
                return

    def _finish_key(self, key, reason: str) -> None:
        pool, w, slot = key
        h = self._slot_handles.pop(key)
        (self.prefill_pool if pool == "prefill"
         else self.decode_pool)[w].evict(slot)
        h._finish(reason)
        self._note_finished(h)

    def _note_finished(self, h: RequestHandle) -> None:
        from tpudist import telemetry

        self.completed += 1
        telemetry.event(
            "request_finished", id=h.id, reason=h.finish_reason,
            prompt_len=int(len(h.request.prompt)), tokens_out=len(h.tokens),
            ttft_s=h.ttft_s, tpot_s=h.tpot_s, queue_wait_s=h.queue_wait_s,
            pool="disagg", handoff_wait_s=h.handoff_wait_s)
