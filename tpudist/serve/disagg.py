"""Prefill/decode disaggregation: separate worker pools with KV handoff.

The single-pool server interleaves prompt chunks and decode blocks on
ONE engine, so a burst of long prompts steals decode iterations from
every in-flight stream (bounded to one chunk per iteration by chunked
prefill, but still stolen).  This coordinator splits the two phases the
way disaggregated serving systems do (DistServe/Splitwise lineage):

- a **prefill pool** of workers that ONLY teacher-force prompts
  (``start_batch`` + ``advance_prefill``; their slots never decode);
- a **decode pool** of workers that ONLY run fused decode blocks;
- a bounded **handoff queue** between them carrying each finished
  prompt's KV package (:meth:`tpudist.serve.engine.SlotEngine.
  export_slot`): the KV lane, the SlotState row, and the budget
  shadows.  ``import_slot`` installs it in a free decode slot and the
  request continues BYTE-IDENTICALLY — the sampling stream is
  ``fold_in(key, count)``, indifferent to which engine or slot hosts
  the request (the oracle tests pin greedy and sampled continuation).

TTFT is now a prefill-pool number (token 0 is sampled from the final
prompt logits, in the prefill worker) and TPOT a decode-pool number;
the telemetry serving section splits them per pool, plus the
coordinator's own ``handoff_wait`` gap.

Transfer modes (``ServeConfig.handoff``): ``"device"`` passes the
device arrays through (in-mesh handoff — on one host a reference copy,
on a real mesh a device-to-device transfer scheduled by the runtime);
``"serial"`` round-trips every leaf through host bytes
(``serialize_package``/``deserialize_package``) — the stand-in for the
multi-process CPU rig, where KV crosses a process boundary as a
serialized block transfer.  Both modes are byte-preserving (int8 pools
re-quantize bit-exactly on import; tests pin it).

Thread contract mirrors :class:`tpudist.serve.server.InferenceServer`:
one engine thread drives every engine in both pools (the device
programs serialize anyway on one host), any number of threads submit,
SIGTERM/``close()`` drain everything admitted.

**Self-healing fleet** (``ServeConfig.recover``, default on): a pool
worker that dies mid-flight (any exception out of its engine calls —
injected via ``TPUDIST_FAULT=serve_worker_kill@...`` or real) no longer
takes the server down.  The loop marks the worker dead (``worker_lost``
telemetry), and every lane it was hosting continues on survivors:

- a **decode** lane replays its stashed handoff package on a surviving
  decode worker.  Decode is a pure function of ``(state, cache)`` and
  the per-slot ``fold_in(key, count)`` sampling stream — both ride IN
  the package — so re-importing and re-decoding reproduces the exact
  token sequence, greedy or sampled; the tokens the dead worker already
  delivered are dropped on re-emission (the replay-skip counter) and
  the stream continues BYTE-IDENTICALLY from the first new token
  (``lane_recovered`` telemetry);
- a **prefill** lane (no KV exported yet) requeues at the head of the
  admission line and re-prefills its prompt on a surviving prefill
  worker (same skip rule for a token 0 that was already delivered).

Only when a pool has NO survivors do its lanes finish, with reason
``"worker_lost"`` (never a silent hang).  The stashed packages cost one
extra copy of each in-flight decode lane's KV; ``recover=False``
restores the PR-7 behavior (any worker death aborts everything as
``"shutdown"``).

**Overload & graceful degradation** (``ServeConfig.host_tier``): both
pools park/resume through the handoff machinery — a finished session
turn's decode lane exports into the host-RAM KV tier
(:mod:`tpudist.serve.host_tier`) and the session's next turn resumes it
on a PREFILL worker (suffix-only teacher-forcing) before handing off to
decode like any import; a higher-priority handoff-queue head can
preempt a lower-priority decode lane into the tier (byte-identical
resume via the same placement path); and the SLO-aware overload
controller (:mod:`tpudist.serve.overload`) sheds lower-priority work
off the live attainment gauges.  See the ARCHITECTURE "Overload &
graceful degradation" section.

**Backpressure pool resize** (``ServeConfig.pool_resize`` iterations,
0 = off): a handoff queue that stays full for that many consecutive
loop iterations means the decode pool is the bottleneck — the prefill
pool's effective slot budget shrinks by one (admission backpressure
moves to the queue instead of piling KV into stalled prefill slots),
and grows back once the queue stays at most half full for as long
(``pool_resize`` telemetry events carry each move).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

from tpudist.serve.engine import SlotEngine
from tpudist.serve.scheduler import AdmissionError, RequestHandle, Scheduler
from tpudist.serve.server import (ReplicaKilled, _Observability,
                                  _compile_grammar_for)

_IDLE_WAIT_S = 0.01

#: Wire-format version of a serialized KV-handoff package.  Bumped
#: whenever the blob layout changes; :func:`deserialize_package` REJECTS
#: a missing or unsupported version with a clear error instead of
#: shape-crashing mid-import (mixed tpudist versions across pools, or a
#: replayed package from an old run).  v2 added the schema field itself
#: plus the blob integrity digest; v3 added the per-request ``trace_id``
#: (the cross-pool tracing join key).  v2 packages still DESERIALIZE
#: (their trace_id reads back ``None``) — the new field is additive and
#: outside the digested blob, so the old wire format stays valid.  v4
#: added the per-tenant ``adapter`` NAME (tpudist.serve.adapters) and a
#: ninth SlotState leaf (``adapter_id``) in the blob: pool block ids
#: are local, so the importing pool re-binds by NAME — v2/v3 packages
#: still deserialize (adapter reads back ``None``, the base-only path).
#: v5 added the structured-output ``grammar`` envelope field
#: (tpudist.constrain — the grammar travels by SOURCE so the importing
#: pool recompiles and re-binds in its own table pool) and the tenth and
#: eleventh SlotState leaves (``gidx``/``gstate``) in the blob; the
#: automaton STATE carries byte-faithfully while the pool-local block id
#: is overwritten at install.  v2..v4 packages still deserialize
#: (grammar reads back ``None`` — the importing engine installs the lane
#: unconstrained with a sentinel gidx and zero gstate).
HANDOFF_SCHEMA_VERSION = 5

#: Oldest wire format :func:`deserialize_package` accepts.
HANDOFF_SCHEMA_MIN = 2


class HandoffError(RuntimeError):
    """A serialized handoff package this pool must not import: wrong or
    missing ``schema_version`` (``reason="schema"``) or failed blob
    integrity check (``reason="corrupt"``).  The serving loop finishes
    the affected request with reason ``"handoff_corrupt"`` and keeps
    serving everyone else."""

    def __init__(self, msg: str, reason: str):
        super().__init__(msg)
        self.reason = reason


def _blob_digest(blob) -> str:
    """blake2b over every blob leaf — wire-corruption detection (a
    flipped byte in a KV lane would otherwise deserialize silently into
    garbage attention)."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for b, _, _ in blob:
        h.update(b)
    return h.hexdigest()


def _np_dtype(name: str):
    """Resolve a dtype NAME back to a numpy dtype.  Names, not
    ``dtype.str``: the struct codes of the ml_dtypes family degrade to
    raw void ("<V2" for bfloat16), which would silently destroy a bf16
    KV lane on the wire."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def serialize_package(pkg: dict) -> dict:
    """Flatten a KV-handoff package to host bytes — what would ride the
    wire between a prefill process and a decode process.  Keeps the
    treedef (both ends share the engine geometry, so the structure is
    common knowledge; a cross-host protocol would pin it by schema).
    Byte-preserving for every lane dtype including bf16/int8 (tests pin
    the round trip)."""
    import jax
    import numpy as np

    flat, tree = jax.tree.flatten((pkg["lane"], pkg["state"]))
    blob = []
    for leaf in flat:
        a = np.asarray(leaf)
        blob.append((a.tobytes(), a.dtype.name, a.shape))
    ser = {"schema_version": HANDOFF_SCHEMA_VERSION,
           "paged": pkg["paged"], "pos": pkg["pos"],
           "counts": pkg["counts"], "budget": pkg["budget"],
           "trace_id": pkg.get("trace_id"),
           "adapter": pkg.get("adapter"),
           "grammar": pkg.get("grammar"),
           "blob": blob, "tree": tree,
           "digest": _blob_digest(blob),
           "bytes": sum(len(b) for b, _, _ in blob)}
    # chaos harness: a due handoff_corrupt fault garbles the package
    # AFTER the digest is stamped — detectable wire corruption.  One
    # None-check when disarmed.
    from tpudist.runtime import faults

    faults.inject_handoff(ser)
    return ser


def check_package_schema(ser: dict) -> None:
    """Raise :class:`HandoffError` unless ``ser`` carries a supported
    ``schema_version`` (``HANDOFF_SCHEMA_MIN`` .. current — v2 streams
    without trace_ids still import) — the cheap envelope check a full
    decode pool runs per blocked iteration (no blob work)."""
    ver = ser.get("schema_version")
    if (not isinstance(ver, int)
            or not HANDOFF_SCHEMA_MIN <= ver <= HANDOFF_SCHEMA_VERSION):
        raise HandoffError(
            f"handoff package schema_version {ver!r} not in supported "
            f"range [{HANDOFF_SCHEMA_MIN}, {HANDOFF_SCHEMA_VERSION}] "
            "(missing = pre-versioning sender; out of range = mixed "
            "tpudist versions across pools) — rejected instead of "
            "shape-crashing mid-import",
            reason="schema")


def deserialize_package(ser: dict) -> dict:
    """Inverse of :func:`serialize_package` (byte-preserving).  Rejects
    a missing/mismatched ``schema_version`` and any blob whose integrity
    digest no longer matches (:class:`HandoffError`)."""
    import jax
    import numpy as np

    check_package_schema(ser)
    digest = ser.get("digest")
    if digest is not None and _blob_digest(ser["blob"]) != digest:
        raise HandoffError(
            "handoff package failed its integrity check (blob digest "
            "mismatch) — corrupted in transit; the request is finished "
            "with a reason instead of decoding garbage KV",
            reason="corrupt")
    flat = [np.frombuffer(b, dtype=_np_dtype(d)).reshape(s)
            for b, d, s in ser["blob"]]
    lane, state = jax.tree.unflatten(ser["tree"], flat)
    return {"paged": ser["paged"], "pos": ser["pos"],
            "counts": ser["counts"], "budget": ser["budget"],
            "trace_id": ser.get("trace_id"),  # None on a v2 package
            "adapter": ser.get("adapter"),  # None on a v2/v3 package
            "grammar": ser.get("grammar"),  # None on a v2..v4 package
            "lane": lane, "state": state}


class DisaggServer(_Observability):
    """Disaggregated continuous-batching server: prefill pool → KV
    handoff → decode pool.  Config rides the same
    :class:`tpudist.serve.server.ServeConfig` (``disagg=True`` selects
    this class in :func:`tpudist.serve.server.serve_forever`)."""

    _statusz_name = "serve-disagg"

    def __init__(self, module, params, config=None, *,
                 install_signal_handler: bool = True):
        from tpudist.serve.server import ServeConfig

        self.config = config or ServeConfig.from_env()
        cfg = self.config
        # structured output spans BOTH pools: the prefill engine masks
        # the first sampled token (insert/prefill_extend carry the
        # grammar tail), the decode pool recompiles and re-binds the
        # grammar by source at import (v5 envelope field)
        ccfg = None
        if cfg.constrain:
            from tpudist.constrain import ConstrainConfig, default_vocab

            ccfg = ConstrainConfig(
                vocab=default_vocab(int(module.vocab)),
                num_blocks=cfg.constrain_blocks,
                max_states=cfg.constrain_states)
        self.constrain_cfg = ccfg
        shared = dict(
            prefill_pad=cfg.prefill_pad, paged=cfg.paged,
            kv_block=cfg.kv_block, kv_blocks=cfg.kv_blocks,
            kv_int8=cfg.kv_int8, mesh=cfg.mesh_config(),
            # every pool engine carries the adapter pool: prefill
            # teacher-forces THROUGH the adapter (the exported KV
            # depends on it) and the decode pool re-binds by name on
            # import; load_adapter broadcasts to all of them
            adapters=cfg.adapters, adapter_blocks=cfg.adapter_blocks,
            adapter_rank=cfg.adapter_rank,
            constrain=ccfg, logprobs=cfg.logprobs)
        p_slots = cfg.prefill_slots or cfg.num_slots
        # prefill workers keep the prefix cache (reuse saves prefill
        # compute — that is this pool's whole job); decode workers get
        # private blocks only (a handed-off lane never shares).
        self.prefill_pool: List[SlotEngine] = [
            SlotEngine(module, params, num_slots=p_slots, decode_block=1,
                       prefix_cache_blocks=cfg.prefix_cache_blocks,
                       attn_kernel="gather",
                       # the prefill kernel is THIS pool's hot path; the
                       # fused-RoPE/LoRA kernels only ride here when it
                       # is on (the pool's decode arm stays gather)
                       prefill_kernel=cfg.prefill_kernel,
                       sample_kernel=cfg.sample_kernel,
                       fused_rope=cfg.fused_rope and cfg.prefill_kernel,
                       lora_kernel=cfg.lora_kernel and cfg.prefill_kernel,
                       **shared)
            for _ in range(max(1, cfg.prefill_workers))]
        # the DECODE pool owns the speculative draft (prefill workers
        # never decode, so a draft there is dead weight); handoff
        # packages are unchanged — an imported lane's draft context
        # starts cold and warms as it decodes (engine.import_slot doc)
        # the decode pool is where the paged-attention kernel earns its
        # keep (the bandwidth-bound hot path); prefill workers stay on
        # the gather path — they teacher-force, never decode
        self.decode_pool: List[SlotEngine] = [
            SlotEngine(module, params, num_slots=cfg.num_slots,
                       decode_block=cfg.decode_block,
                       prefix_cache_blocks=0,
                       spec_draft=cfg.resolve_spec_draft(module),
                       spec_k=cfg.spec_k, attn_kernel=cfg.attn_kernel,
                       prefill_kernel=cfg.prefill_kernel,
                       sample_kernel=cfg.sample_kernel,
                       fused_rope=cfg.fused_rope,
                       lora_kernel=cfg.lora_kernel,
                       **shared)
            for _ in range(max(1, cfg.decode_workers))]
        self.handoff_mode = cfg.handoff
        if self.handoff_mode not in ("device", "serial"):
            raise ValueError(
                f"handoff must be 'device' or 'serial', got {cfg.handoff!r}")
        #: bounded pending-handoff queue: (handle, package) — a full
        #: queue stalls exports (the lane waits in its prefill slot),
        #: which in turn backpressures admission via free prefill slots.
        self._handoff: "collections.deque[Tuple[RequestHandle, dict]]" = \
            collections.deque()
        self.handoff_limit = max(1, cfg.handoff_queue)
        pe, de = self.prefill_pool[0], self.decode_pool[0]

        def check_budget(plen: int, max_new: int) -> Optional[str]:
            return pe.check_budget(plen, max_new) \
                or de.check_budget(plen, max_new)

        hasher = None
        if cfg.paged and cfg.prefix_cache_blocks > 0:
            from tpudist.serve.paged_alloc import hash_chain

            bs = pe.paged_cfg.block_size
            hasher = lambda prompt: hash_chain(prompt, bs)  # noqa: E731
        self.scheduler = Scheduler(
            queue_limit=cfg.queue_limit, check_budget=check_budget,
            default_max_new=cfg.max_new, default_deadline_s=cfg.deadline_s,
            prefix_hasher=hasher,
            check_adapter=lambda name: (
                None if pe.has_adapter(name) else "adapter_missing"),
            compile_grammar_fn=(None if ccfg is None else (
                lambda regex, schema, eos: _compile_grammar_for(
                    ccfg, regex, schema, eos))),
            max_logprobs=de.n_lp)
        self._install_signal = install_signal_handler
        self._installed_preemption = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._draining = False
        #: (pool, worker, slot) → handle; pool ∈ {"prefill", "decode"}
        self._slot_handles: Dict[Tuple[str, int, int], RequestHandle] = {}
        self.completed = 0
        self.tokens_out = 0
        self.handoffs = 0
        self.handoff_bytes = 0
        # -- live observability plane (server._Observability) --------------
        self._init_observability()
        # -- self-healing fleet state (module doc: recovery contract) ------
        self.recover = bool(getattr(cfg, "recover", True))
        #: dead worker indices per pool — skipped by every loop phase
        self._dead: Dict[str, set] = {"prefill": set(), "decode": set()}
        #: (decode worker, slot) → (handoff package AS QUEUED, tokens the
        #: handle had at import time) — the replay stash a dead decode
        #: worker's lanes recover from.  Costs one extra copy of each
        #: in-flight lane's KV; dropped the moment the lane finishes.
        self._import_pkg: Dict[Tuple[int, int], Tuple[dict, int]] = {}
        # -- graceful degradation (host tier / preemption / shedding) ------
        # also (re)creates ``self._skip`` — handle.id → tokens to DROP
        # on re-emission: worker-loss replays AND host-tier re-prefill
        # fallbacks share the one duplicate-drop counter (presence marks
        # the handle as in-recovery/fallback)
        self._init_degradation(self.scheduler)
        #: prefill-replay line: lanes whose prefill worker died re-prefill
        #: from the prompt, ahead of fresh admissions
        self._requeue: "collections.deque[RequestHandle]" = \
            collections.deque()
        #: cumulative engine-call counter per (pool, worker) — the
        #: serve_worker_kill fault injection clock
        self._calls: Dict[Tuple[str, int], int] = {}
        self.workers_lost = 0
        self.lanes_recovered = 0
        # -- backpressure-driven pool resize -------------------------------
        self.pool_resize = max(0, int(getattr(cfg, "pool_resize", 0)))
        self._prefill_slots_total = p_slots * max(1, cfg.prefill_workers)
        #: effective prefill slot budget (across the pool) — shrinks
        #: under sustained handoff-queue backpressure, grows back on slack
        self._prefill_cap = self._prefill_slots_total
        self._bp_full = 0
        self._bp_free = 0
        self.pool_resizes = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "DisaggServer":
        if self._thread is not None:
            raise RuntimeError("server already started")
        from tpudist import telemetry
        from tpudist.runtime import faults, preemption

        # chaos harness: TPUDIST_FAULT's serve-side kinds
        # (serve_worker_kill / handoff_corrupt) arm with zero code
        # changes, exactly like the training loops arm at their entry
        faults.arm_from_env()
        telemetry.ensure_started()
        telemetry.event(
            "serve_disagg_config",
            prefill_workers=len(self.prefill_pool),
            decode_workers=len(self.decode_pool),
            prefill_slots=self.prefill_pool[0].num_slots,
            decode_slots=self.decode_pool[0].num_slots,
            handoff=self.handoff_mode,
            mesh=self.decode_pool[0].spmd_stats().get("mesh"))
        self._stamp_adapter_config()
        de0 = self.decode_pool[0]
        if de0.has_constrain() or de0.n_lp:
            cs = de0.constrain_stats()
            telemetry.event(
                "serve_constrain_config", enabled=cs["enabled"],
                blocks=cs.get("blocks"), max_states=cs.get("max_states"),
                pool_bytes=cs.get("pool_bytes"), logprobs=de0.n_lp)
        if self._capture is None:
            # TPUDIST_DISTILL_CAPTURE arms the live-traffic tap at the
            # same entry the faults grammar arms at — no code changes
            from tpudist.distill.capture import CaptureBuffer

            self._capture = CaptureBuffer.from_env()
        self._start_observability()
        if self._install_signal:
            self._installed_preemption = preemption.install()
        self._thread = threading.Thread(
            target=self._loop, name="tpudist-serve-disagg", daemon=True)
        self._thread.start()
        return self

    def submit(self, prompt, *, max_new: Optional[int] = None,
               temperature: float = 0.0, deadline_s: Optional[float] = None,
               seed: Optional[int] = None, eos_id: Optional[int] = None,
               on_token=None, spec: Optional[bool] = None,
               tenant: Optional[str] = None, priority: int = 0,
               session: Optional[str] = None,
               adapter: Optional[str] = None,
               grammar: Optional[str] = None, json_schema=None,
               stop=None, logprobs: int = 0) -> RequestHandle:
        from tpudist import telemetry

        # +1 BEFORE the handle is visible to the engine thread (see
        # InferenceServer.submit: a fast finish must never decrement
        # first and pin a phantom in-flight)
        tkey = None if tenant is None else str(tenant)
        self._track_tenant(tkey, +1)
        try:
            return self.scheduler.submit(
                prompt, max_new=max_new, temperature=temperature,
                deadline_s=deadline_s, seed=seed, eos_id=eos_id,
                on_token=on_token, spec=spec, tenant=tenant,
                priority=priority, session=session, adapter=adapter,
                grammar=grammar, json_schema=json_schema, stop=stop,
                logprobs=logprobs)
        except BaseException as e:
            self._track_tenant(tkey, -1)  # never admitted (ANY failure)
            if isinstance(e, AdmissionError):
                telemetry.event("serve_rejected", reason=e.reason)
            raise

    def drain(self, timeout: Optional[float] = None) -> bool:
        self._stop.set()
        t = self._thread
        ok = True
        if t is not None:
            t.join(timeout)
            ok = not t.is_alive()
        if ok:
            self.scheduler.refuse_new("draining")
            self._abort_outstanding()
        return ok

    def close(self, timeout: Optional[float] = None) -> bool:
        ok = self.drain(timeout)
        self._stop_observability()
        if self._installed_preemption:
            from tpudist.runtime import preemption

            preemption.reset()
            self._installed_preemption = False
        return ok

    def _adapter_engines(self) -> list:
        return list(self.prefill_pool) + list(self.decode_pool)

    # -- online draft distillation (decode pool owns the spec drafts) --------

    def draft_ref(self):
        alive = self._alive("decode")
        if not alive:
            return None
        eng = self.decode_pool[alive[0]]
        if eng.draft_module is None:
            return None
        return (eng.draft_module, eng.draft_params)

    def _swap_now(self, new_params) -> dict:
        """Broadcast the gated swap across every ALIVE decode worker —
        all-or-error like the adapter broadcast: the first engine
        validates geometry (same trees on every worker, so a pass there
        is a pass everywhere), and a divergent pool can never decode
        two different drafts (the handoff re-bind would make acceptance
        unattributable)."""
        alive = self._alive("decode")
        if not alive:
            raise RuntimeError("no alive decode worker to swap into")
        t0 = time.monotonic()
        rearmed = 0
        swaps = 0
        for w in alive:
            info = self.decode_pool[w].swap_draft(new_params)
            rearmed += int(info.get("lanes_rearmed", 0))
            swaps = info.get("draft_swaps", swaps)
        out = {"swapped": True, "lanes_rearmed": rearmed,
               "swap_s": round(time.monotonic() - t0, 6),
               "draft_swaps": swaps, "engines": len(alive)}
        self._note_swap(out)
        return out

    def _agg_spec_stats(self) -> dict:
        """Decode-pool-aggregated ``spec_stats()`` (the pool owns the
        drafts): counter sums, recomputed rates, per-adapter label
        merge, swap count — one shape for ``stats()`` and
        ``/statusz``."""
        spec = {"enabled": self.decode_pool[0].spec, "blocks": 0,
                "lane_passes": 0, "tokens": 0, "accepted": 0,
                "drafted": 0, "rollbacks": 0,
                "draft_s": 0.0, "verify_s": 0.0, "sync_s": 0.0,
                "draft_swaps": 0}
        by_adapter: dict = {}
        for eng in self.decode_pool:
            st = eng.spec_stats()
            for k in ("blocks", "lane_passes", "tokens", "accepted",
                      "drafted", "rollbacks", "draft_s", "verify_s",
                      "sync_s"):
                spec[k] += st.get(k, 0) or 0
            # broadcast keeps per-engine swap counters in lockstep: the
            # pool's LOGICAL swap count is the max, not the sum
            spec["draft_swaps"] = max(spec["draft_swaps"],
                                      int(st.get("draft_swaps", 0) or 0))
            for name, row in (st.get("by_adapter") or {}).items():
                tot = by_adapter.setdefault(
                    name, {"accepted": 0, "drafted": 0})
                tot["accepted"] += row["accepted"]
                tot["drafted"] += row["drafted"]
        spec["spec_k"] = self.decode_pool[0].spec_stats().get("spec_k")
        spec["accepted_per_pass"] = (spec["tokens"] / spec["lane_passes"]
                                     if spec["lane_passes"] else None)
        spec["acceptance_rate"] = (spec["accepted"] / spec["drafted"]
                                   if spec["drafted"] else None)
        if by_adapter:
            spec["by_adapter"] = {
                name: {**row, "acceptance_rate":
                       (row["accepted"] / row["drafted"]
                        if row["drafted"] else None)}
                for name, row in sorted(by_adapter.items())}
        return spec

    def _observability_gauges(self) -> dict:
        return {
            "tpudist_serve_prefill_workers": len(self.prefill_pool),
            "tpudist_serve_decode_workers": len(self.decode_pool),
            "tpudist_serve_handoff_queue_limit": self.handoff_limit,
            "tpudist_serve_queue_limit": self.config.queue_limit,
        }

    def _statusz_doc(self) -> dict:
        """``/statusz`` with per-pool sections: worker liveness, slot
        occupancy, the handoff queue's depth (the backpressure signal),
        KV residency of the decode pool, per-tenant in-flight."""
        from tpudist.utils.envutil import env_int

        def _pool(pool: str, engines: List[SlotEngine]) -> dict:
            alive = self._alive(pool)
            return {
                "workers": len(engines),
                "dead": sorted(self._dead[pool]),
                "slots_per_worker": engines[0].num_slots,
                "occupied": sum(engines[i].num_occupied for i in alive),
                "active": sum(engines[i].num_active for i in alive),
            }

        kv_occ, kv_resident = self.decode_pool[0].kv_gauges()
        return {
            "pools": {
                "prefill": {**_pool("prefill", self.prefill_pool),
                            "slot_cap": self._prefill_cap},
                "decode": _pool("decode", self.decode_pool),
            },
            "handoff": {
                "queued": len(self._handoff),
                "limit": self.handoff_limit,
                "total": self.handoffs,
                "bytes": self.handoff_bytes,
            },
            "queue": {
                "pending": self.scheduler.pending(),
                "limit": self.config.queue_limit,
                "rejected": self.scheduler.rejected,
            },
            "kv": {
                "bytes_resident": int(kv_resident),
                "block_occupancy": (None if kv_occ is None
                                    else round(float(kv_occ), 4)),
            },
            "recovery": {
                "workers_lost": self.workers_lost,
                "lanes_recovered": self.lanes_recovered,
                "requeued": len(self._requeue),
                "pool_resizes": self.pool_resizes,
            },
            # host-tier occupancy + overload state (absent when off)
            **({"host_tier": {**self._tier.stats(),
                              "parked_requests": len(self._parked),
                              "preemptions": self.preemptions,
                              "resumes_served": self.tier_resumes,
                              "corrupt": self.tier_corrupt}}
               if self._tier is not None else {}),
            **({"overload": self._ctrl.stats()}
               if self._ctrl is not None else {}),
            "completed": self.completed,
            "tokens_out": self.tokens_out,
            "tenants_in_flight": dict(self._tenant_inflight),
            **({"adapters": self.decode_pool[0].adapter_stats()}
               if self.decode_pool[0].adapters is not None else {}),
            # structured-output grammar pool + logprobs width (absent
            # when both are off)
            **({"constrained": {
                **self.decode_pool[0].constrain_stats(),
                "logprobs": self.decode_pool[0].n_lp}}
               if self.decode_pool[0].has_constrain()
               or self.decode_pool[0].n_lp else {}),
            # pool-aggregated speculation + distillation flywheel
            # (absent when off) — the swap gate's numbers, per operator
            **({"spec": self._spec_status(self._agg_spec_stats())}
               if self.decode_pool[0].spec else {}),
            **({"distill": self._distill_status()}
               if self._capture is not None else {}),
            "world": env_int("TPUDIST_NUM_PROCESSES", None),
            "generation": env_int("TPUDIST_RESTART_COUNT", 0),
            "draining": self._draining,
            "loop_error": self.loop_error,
        }

    def stats(self) -> dict:
        dec = {"blocks": 0, "tokens": 0, "steps": 0,
               "dispatch_s": 0.0, "sync_s": 0.0, "kv_read_bytes": 0}
        for eng in self.decode_pool:
            for k, v in eng.decode_stats().items():
                dec[k] += v
        spec = self._agg_spec_stats()
        return {
            "completed": self.completed,
            "rejected": self.scheduler.rejected,
            "tokens_out": self.tokens_out,
            "pending": self.scheduler.pending(),
            "handoffs": self.handoffs,
            "handoff_bytes": self.handoff_bytes,
            "handoff_queued": len(self._handoff),
            # fleet-recovery gauges (module doc)
            "workers_lost": self.workers_lost,
            "lanes_recovered": self.lanes_recovered,
            "requeued": len(self._requeue),
            "pool_resizes": self.pool_resizes,
            "preemptions": self.preemptions,
            "parked": len(self._parked),
            "host_tier": (None if self._tier is None
                          else self._tier.stats()),
            "overload": (None if self._ctrl is None
                         else self._ctrl.stats()),
            "prefill_pool": {
                "workers": len(self.prefill_pool),
                "dead": sorted(self._dead["prefill"]),
                "slots": self.prefill_pool[0].num_slots,
                "slot_cap": self._prefill_cap,
                "occupied": sum(e.num_occupied for e in self.prefill_pool),
                "compile_counts": self.prefill_pool[0].compile_counts(),
            },
            "decode_pool": {
                "workers": len(self.decode_pool),
                "dead": sorted(self._dead["decode"]),
                "slots": self.decode_pool[0].num_slots,
                "active": sum(e.num_active for e in self.decode_pool),
                "compile_counts": self.decode_pool[0].compile_counts(),
                "decode": dec,
                "spec": spec,
                "kv": self.decode_pool[0].kv_stats(),
            },
            "spmd": self.decode_pool[0].spmd_stats(),
            "adapters": self.decode_pool[0].adapter_stats(),
        }

    # -- the engine loop ----------------------------------------------------

    def _should_drain(self) -> bool:
        if self._stop.is_set():
            return True
        from tpudist.runtime import preemption

        return preemption.requested()

    def _abort_outstanding(self) -> None:
        self._abort_parked()
        for key in list(self._slot_handles):
            h = self._slot_handles.pop(key)
            h._finish("shutdown")
            self._note_finished(h)
        while self._handoff:
            h, _ = self._handoff.popleft()
            h._finish("shutdown")
            self._note_finished(h)
        while self._requeue:
            h = self._requeue.popleft()
            h._finish("shutdown")
            self._note_finished(h)
        for h in self.scheduler.take(1 << 30):
            if not h.done:
                h._finish("shutdown")
            self._note_finished(h)

    # -- worker-loss recovery ----------------------------------------------

    def _alive(self, pool: str) -> List[int]:
        pools = (self.prefill_pool if pool == "prefill"
                 else self.decode_pool)
        return [i for i in range(len(pools)) if i not in self._dead[pool]]

    def _tick(self, pool: str, w: int) -> None:
        """Count one engine interaction of ``(pool, worker)`` and let a
        due ``serve_worker_kill`` fault turn it into a death (raises —
        the caller's worker-lost handler takes it from there, the same
        path a real engine failure drives)."""
        from tpudist.runtime import faults

        key = (pool, w)
        self._calls[key] = n = self._calls.get(key, 0) + 1
        if faults.inject_serve_worker(0 if pool == "prefill" else 1, w, n):
            raise RuntimeError(
                f"injected serve_worker_kill: {pool} worker {w}")

    def _lose_worker(self, pool: str, w: int, exc: BaseException) -> None:
        """A pool worker's engine died mid-flight.  With recovery on:
        mark it dead, re-route every lane it hosted onto survivors —
        decode lanes replay their stashed handoff package (re-decode is
        byte-identical; already-delivered tokens drop via the replay-skip
        counter), prefill lanes requeue for a fresh prefill.  A pool with
        no survivors finishes its lanes as ``"worker_lost"``.  With
        ``recover=False`` the exception propagates and the loop aborts
        everything as ``"shutdown"`` (the PR-7 contract)."""
        if not self.recover:
            raise exc
        if w in self._dead[pool]:
            return
        from tpudist import telemetry

        self._dead[pool].add(w)
        self.workers_lost += 1
        keys = [k for k in self._slot_handles
                if k[0] == pool and k[1] == w]
        telemetry.event("worker_lost", pool=pool, worker=w,
                        error=repr(exc)[:200], lanes=len(keys))
        survivors = bool(self._alive(pool))
        for key in keys:
            _, _, slot = key
            h = self._slot_handles.pop(key)
            if pool == "decode":
                # close this residency's timeline segment — the replay
                # on the survivor opens the next one (the worker jump)
                if h.decode_segments and h.decode_segments[-1][2] is None:
                    h.decode_segments[-1][2] = time.monotonic()
                stash = self._import_pkg.pop((w, slot), None)
                if survivors and stash is not None:
                    pkg, l0 = stash
                    # everything the dead worker emitted since import
                    # re-emits on replay — drop exactly that many
                    self._skip[h.id] = max(0, len(h.tokens) - l0)
                    self._handoff.appendleft((h, pkg))
                    continue
            else:
                if survivors:
                    # nothing exported yet: re-prefill the prompt on a
                    # surviving worker (ahead of fresh admissions); a
                    # token 0 that was already delivered skips once
                    self._skip[h.id] = len(h.tokens)
                    self._requeue.append(h)
                    continue
            h._finish("worker_lost")
            self._note_finished(h)
        if not survivors:
            self._pool_collapsed(pool)

    def _pool_collapsed(self, pool: str) -> None:
        """A pool lost its LAST worker: nothing that depends on it can
        ever complete — finish the dependents loudly (``worker_lost``,
        never a hang) and refuse new admissions with the same reason.
        The serve path needs both pools, so either collapse is terminal
        for new work; already-decoding lanes on the OTHER pool still
        finish normally."""
        if pool == "decode":
            while self._handoff:
                h, _ = self._handoff.popleft()
                h._finish("worker_lost")
                self._note_finished(h)
            # parked preempted lanes need the decode pool to ever finish
            # — with no survivor they end loudly too (their tier bytes
            # release with them)
            while self._parked:
                hid, h = self._parked.popitem(last=False)
                if self._tier is not None:
                    self._tier.discard(("preempt", hid))
                h._finish("worker_lost")
                self._note_finished(h)
        else:
            while self._requeue:
                h = self._requeue.popleft()
                h._finish("worker_lost")
                self._note_finished(h)
        self.scheduler.refuse_new("worker_lost")
        for h in self.scheduler.take(1 << 30):
            if not h.done:
                h._finish("worker_lost")
            self._note_finished(h)

    def _reject_package(self, h: RequestHandle, e: "HandoffError") -> None:
        """A handoff package this pool must not import (schema mismatch
        or wire corruption): finish ITS request with a reason and keep
        serving everyone else."""
        from tpudist import telemetry

        telemetry.event("handoff_rejected", reason=e.reason,
                        error=str(e)[:200])
        h._finish("handoff_corrupt")
        self._note_finished(h)

    def _loop(self) -> None:
        from tpudist import telemetry

        try:
            self._run_loop()
        except BaseException as e:
            # a dying pool worker must not strand waiters (module doc)
            self.loop_error = repr(e)  # /healthz goes 503 on this
            telemetry.event("serve_loop_error", error=repr(e))
            if not isinstance(e, ReplicaKilled):
                raise
        finally:
            self.scheduler.refuse_new("draining")
            self._abort_outstanding()

    def _outstanding(self) -> int:
        return (self.scheduler.pending() + len(self._slot_handles)
                + len(self._handoff) + len(self._requeue)
                + len(self._parked))

    def _run_loop(self) -> None:
        from tpudist import telemetry

        sched = self.scheduler
        while True:
            self._beat = time.monotonic()  # /healthz heartbeat
            self._check_die()  # hard-stop poison (kill / replica_kill)
            # gated draft hot-swap lands HERE — the coordinator loop is
            # the only dispatcher into the decode pool, so a broadcast
            # between iterations lands between decode blocks on every
            # worker at once (no half-swapped pool is ever observable)
            self._apply_pending_swap()
            if not self._draining and self._should_drain():
                self._draining = True
                sched.refuse_new("draining")
                telemetry.event("serve_drain", pending=sched.pending(),
                                active=self._outstanding())
            now = time.monotonic()
            for key, h in list(self._slot_handles.items()):
                if h._expired(now):
                    self._finish_key(key, "deadline")
            # deadline sweep over the handoff queue, order-preserving
            kept = collections.deque()
            while self._handoff:
                h, pkg = self._handoff.popleft()
                if h._expired(now):
                    h._finish("deadline")
                    self._note_finished(h)
                else:
                    kept.append((h, pkg))
            self._handoff = kept
            self._expire_requeue(now)
            for h in sched.expire_queued(now):
                self._note_finished(h)
            # parked-lane deadlines + tier TTL, the live-gauge shed
            # tick, then decode-pool preemption / parked resume — host
            # decisions, all before placement so freed capacity is
            # usable this same iteration
            self._sweep_parked(now)
            self._shed_tick(now)
            self._maybe_preempt()
            self._resume_preempted()
            did_work = False
            did_work |= self._admit_prefill(now)
            did_work |= self._advance_prefill()
            did_work |= self._place_handoffs()
            did_work |= self._decode()
            if self.pool_resize:
                self._pool_resize_tick()
            if self._draining and self._outstanding() == 0:
                break
            if not did_work:
                if sched.pending() or self._handoff or self._requeue:
                    # gate-blocked (pool/slots full): nothing frees until
                    # a later iteration — don't spin the engine thread
                    time.sleep(_IDLE_WAIT_S)
                else:
                    sched.wait_for_work(_IDLE_WAIT_S)

    # -- priority preemption through the handoff machinery -------------------

    def _maybe_preempt(self) -> None:
        """Decode-pool preemption: when the handoff queue's HEAD
        outranks a decoding lane and no alive decode worker can place
        it, the lowest-priority decoding lane (ties: least progress)
        exports to the host tier mid-block and frees its slot+blocks —
        byte-identical continuation later through the same handoff
        placement every import rides."""
        if (self._tier is None or not self.config.preempt
                or self._draining or not self._handoff):
            return
        head_h, head_pkg = self._handoff[0]
        hp = head_h.request.priority
        for w in self._alive("decode"):
            eng = self.decode_pool[w]
            if eng.free_slots() and eng.can_import(head_pkg):
                return  # the head can already place — nothing to do
        cands = []
        for (pool, w, slot), h in self._slot_handles.items():
            if (pool == "decode" and w not in self._dead["decode"]
                    and self.decode_pool[w].decoding[slot]
                    and h.request.priority < hp
                    and h.id not in self._skip
                    and h.id not in self._tier_oversize):
                cands.append((w, slot, h))
        if not cands:
            return
        w, slot, victim = min(
            cands, key=lambda t: (t[2].request.priority,
                                  len(t[2].tokens)))
        eng = self.decode_pool[w]
        try:
            self._tick("decode", w)
            pkg = eng.export_slot(slot)
        except Exception as e:
            self._lose_worker("decode", w, e)
            return
        pkg["trace_id"] = victim.trace_id
        stored = self._tier_put(("preempt", victim.id), pkg, pinned=True,
                                kind="preempt")
        if stored is None:
            # tier can't hold the lane: placement just waits — and this
            # lane must not be re-exported every loop spin
            self._tier_oversize.add(victim.id)
            return
        del self._slot_handles[("decode", w, slot)]
        self._import_pkg.pop((w, slot), None)
        self._parked[victim.id] = victim
        self.preemptions += 1
        # close this residency's timeline segment — the resume opens
        # the next one (the same shape a worker-loss replay draws)
        if victim.decode_segments \
                and victim.decode_segments[-1][2] is None:
            victim.decode_segments[-1][2] = time.monotonic()
        self._tier_event("preempted", id=victim.id, worker=w, slot=slot,
                         priority=victim.request.priority,
                         by_priority=hp, bytes=stored,
                         trace_id=victim.trace_id)
        try:
            eng.evict(slot)
        except Exception as e:
            self._lose_worker("decode", w, e)

    def _resume_preempted(self) -> None:
        """Parked preempted lanes re-enter the HANDOFF QUEUE head as
        decode capacity frees (oldest first, unless the queue head
        outranks them) — resume rides the exact placement path every
        import rides.  A spilled or corrupt parked package degrades to
        a full re-prefill through the requeue line (``host_tier_corrupt``
        event; already-delivered tokens drop as duplicates)."""
        if self._tier is None or not self._parked:
            return
        while self._parked:
            hid, h = next(iter(self._parked.items()))
            if self._handoff \
                    and self._handoff[0][0].request.priority \
                    > h.request.priority:
                return  # the higher class places first
            ser = self._tier.peek(("preempt", hid))
            if ser is None or (
                    ser.get("digest") is not None
                    and _blob_digest(ser["blob"]) != ser["digest"]):
                # spilled (missing) or corrupt: full re-prefill fallback
                # — never a crash, never wrong bytes (duplicate-drop
                # keeps the stream byte-identical)
                del self._parked[hid]
                if ser is not None:
                    self._tier.get(("preempt", hid))
                    self.tier_corrupt += 1
                    self._tier_event("host_tier_corrupt", kind="preempt",
                                     trace_id=h.trace_id)
                self._skip[h.id] = len(h.tokens)
                self._requeue.append(h)
                continue
            if not self._alive("decode"):
                self._tier.get(("preempt", hid))
                del self._parked[hid]
                h._finish("worker_lost")
                self._note_finished(h)
                continue
            placeable = any(
                self.decode_pool[w].free_slots()
                and self.decode_pool[w].can_import(ser)
                for w in self._alive("decode"))
            if not placeable:
                return  # capacity not back yet — parked head-of-line
            self._tier.get(("preempt", hid))
            del self._parked[hid]
            pkg = (ser if self.handoff_mode == "serial"
                   else deserialize_package(ser))
            self._handoff.appendleft((h, pkg))
            self.tier_resumes += 1
            self._tier_event("session_resumed", park_kind="preempt",
                             id=h.id, trace_id=h.trace_id)

    # -- prefill pool -------------------------------------------------------

    def _pool_resize_tick(self) -> None:
        """Backpressure-driven prefill slot budget (module doc): a
        handoff queue pinned at its limit for ``pool_resize`` consecutive
        iterations shrinks the effective prefill slot budget by one
        (admission backpressure instead of KV piling up in stalled
        prefill slots); sustained slack (queue at most half full) grows
        it back."""
        from tpudist import telemetry

        q = len(self._handoff)
        if q >= self.handoff_limit:
            self._bp_full += 1
            self._bp_free = 0
            if self._bp_full >= self.pool_resize and self._prefill_cap > 1:
                self._prefill_cap -= 1
                self.pool_resizes += 1
                self._bp_full = 0
                telemetry.event("pool_resize", pool="prefill",
                                direction="shrink", cap=self._prefill_cap,
                                queued=q)
        elif q * 2 <= self.handoff_limit:
            self._bp_free += 1
            self._bp_full = 0
            if (self._bp_free >= self.pool_resize
                    and self._prefill_cap < self._prefill_slots_total):
                self._prefill_cap += 1
                self.pool_resizes += 1
                self._bp_free = 0
                telemetry.event("pool_resize", pool="prefill",
                                direction="grow", cap=self._prefill_cap,
                                queued=q)
        else:
            self._bp_full = 0
            self._bp_free = 0

    def _admit_prefill(self, now: float) -> bool:
        from tpudist import telemetry

        worked = False
        for w in self._alive("prefill"):
            eng = self.prefill_pool[w]
            free = eng.free_slots()
            # backpressure resize: cap the POOL-WIDE occupied prefill
            # slots at the current effective budget
            occupied = sum(self.prefill_pool[i].num_occupied
                           for i in self._alive("prefill"))
            free = free[:max(0, self._prefill_cap - occupied)]
            if not free:
                continue
            reserved, pinned = [0], []
            resume_pos: Dict[int, int] = {}

            def _gate(h, _eng=eng, _reserved=reserved, _pinned=pinned,
                      _resume=resume_pos):
                req = h.request
                if (self._tier is not None and req.session is not None
                        and h.id not in self._skip):
                    pos = self._tier.match(
                        self._session_key(req), req.prompt)
                    if pos is not None:
                        # host-tier session hit: resume reserves the
                        # FULL footprint on the PREFILL worker (the
                        # suffix teacher-forces there, then the lane
                        # hands off to the decode pool like any other)
                        got = _eng.kv_admission_probe(
                            len(req.prompt), req.max_new, (),
                            reserve=_reserved[0], protect=_pinned)
                        if got is None:
                            return False
                        _reserved[0] += got[0]
                        _resume[h.id] = pos
                        return True
                got = _eng.kv_admission_probe(
                    len(req.prompt), req.max_new, req.prefix_hashes,
                    reserve=_reserved[0], protect=_pinned)
                if got is None:
                    return False
                # the decode pool must eventually take it too; reject
                # never — transient decode-pool pressure just queues the
                # package (bounded by the handoff queue)
                _reserved[0] += got[0]
                _pinned.extend(got[1])
                return True

            # worker-lost replays re-prefill FIRST, ahead of fresh
            # admissions (their requests were admitted long ago)
            batch: List[RequestHandle] = []
            replay_blocked = False
            while self._requeue and len(batch) < len(free):
                if not _gate(self._requeue[0]):
                    # head-of-line, like the scheduler queue — and this
                    # WORKER takes no fresh admissions while its gate
                    # blocks the replay head, or steady small-request
                    # traffic would starve the recovered lane out of the
                    # very blocks it is waiting for
                    replay_blocked = True
                    break
                batch.append(self._requeue.popleft())
            if len(batch) < len(free) and not replay_blocked:
                batch += self.scheduler.take(
                    len(free) - len(batch), now, admit=_gate)
            alive = []
            for h in batch:
                if h.done:
                    self._note_finished(h)
                elif not eng.has_adapter(h.request.adapter):
                    # admitted, but the named adapter was unloaded while
                    # it queued: finish loudly (never serve base output
                    # for an adapter request)
                    h._finish("adapter_missing")
                    self._note_finished(h)
                else:
                    alive.append(h)
            if not alive:
                continue
            worked = True
            items, t0 = [], time.monotonic()
            for h, slot in zip(alive, free):
                if w in self._dead["prefill"]:
                    # the worker died placing an EARLIER candidate of
                    # this batch (a resume import killed it): the rest
                    # re-prefill via the requeue line on survivors
                    self._requeue.append(h)
                    continue
                h.slot = slot
                h.prefill_worker = w  # timeline attribution
                if h.t_admitted is None:
                    h.t_admitted = t0
                # a session hit resumes its parked lane on this prefill
                # worker (suffix-only teacher-forcing; falls back to a
                # fresh prefill on a spilled/corrupt package)
                if h.id in resume_pos \
                        and self._resume_session_prefill(w, slot, h):
                    continue
                items.append((slot, h.request.prompt, h.request.temperature,
                              h.request.seed, h.request.max_new,
                              h.request.prefix_hashes, None,
                              h.request.adapter, h.request.grammar))
                self._slot_handles[("prefill", w, slot)] = h
            if not items:
                continue
            from tpudist.constrain.registry import GrammarPoolFull
            from tpudist.serve.adapters import AdapterMissingError

            firsts = {}
            while items:
                try:
                    self._tick("prefill", w)
                    with telemetry.span("prefill", n=len(items),
                                        pool="prefill", worker=w):
                        firsts = eng.start_batch(items)
                    break
                except GrammarPoolFull:
                    # every grammar block on this prefill worker is
                    # pinned (start_batch rolled the dispatch back):
                    # defer the CONSTRAINED items through the requeue
                    # line, admit the free ones.  NOT a worker death.
                    keep = []
                    for it in items:
                        if it[8] is not None:
                            h2 = self._slot_handles.pop(
                                ("prefill", w, it[0]))
                            h2.slot = None
                            self._requeue.append(h2)
                        else:
                            keep.append(it)
                    telemetry.event("constrain_deferred",
                                    n=len(items) - len(keep))
                    items = keep
                except AdapterMissingError as e:
                    # a user thread unloaded the adapter between the
                    # recheck and the dispatch (whole-batch validation —
                    # nothing mutated): finish ITS requests, keep the
                    # rest.  NOT a worker death.
                    keep = []
                    for it in items:
                        if it[7] == e.adapter:
                            h2 = self._slot_handles.pop(
                                ("prefill", w, it[0]))
                            h2._finish("adapter_missing")
                            self._note_finished(h2)
                        else:
                            keep.append(it)
                    items = keep
                except Exception as e:  # worker died admitting: the lanes
                    # just registered recover through the standard path
                    self._lose_worker("prefill", w, e)
                    items = None
                    break
            if items is None:
                continue
            for slot, tok in firsts.items():
                if tok is not None:
                    self._prefill_complete(w, slot, tok)
        return worked

    def _resume_session_prefill(self, w: int, slot: int,
                                h: RequestHandle) -> bool:
        """Resume a parked session lane into prefill worker ``w``: the
        lane imports at its covered cursor and teacher-forces ONLY the
        new turn's suffix, then rides the ordinary handoff into the
        decode pool.  False on a missing/corrupt parked package (the
        caller falls back to a fresh prefill — degraded, never wrong)."""
        from tpudist.serve.host_tier import HostTierError

        eng = self.prefill_pool[w]
        req = h.request
        try:
            ser = self._tier.get(self._session_key(req))
            raw = deserialize_package(ser)  # digest verified here
        except HostTierError:
            return False  # raced a TTL sweep / LRU spill: fresh prefill
        except HandoffError as e:
            self.tier_corrupt += 1
            self._tier_event("host_tier_corrupt", kind="session",
                             error=str(e)[:120], trace_id=h.trace_id)
            return False
        if raw.get("adapter") != req.adapter:
            # the parked KV was written THROUGH its turn's adapter; a
            # turn binding a different adapter (or none) re-prefills
            # fresh — resuming would continue the wrong fine-tune's cache
            return False
        if raw.get("grammar") is not None or req.grammar is not None:
            # a parked lane's automaton state belongs to ITS turn; the
            # next turn starts at state 0 (or unconstrained) — fresh
            # prefill instead (degraded, never wrong bytes)
            return False
        t0 = time.monotonic()
        from tpudist.serve.adapters import AdapterMissingError

        try:
            self._tick("prefill", w)
            eng.resume_slot(slot, raw, req.prompt,
                            temperature=req.temperature, seed=req.seed,
                            max_new=req.max_new, spec=req.spec)
        except AdapterMissingError:
            # unloaded while parked: fall back to a fresh prefill (the
            # admission recheck then finishes it adapter_missing) — NOT
            # a worker death
            return False
        except Exception as e:
            # the worker died importing: register the lane first so the
            # standard recovery requeues it for a full re-prefill on a
            # survivor (nothing delivered yet — skip lands at 0)
            self._slot_handles[("prefill", w, slot)] = h
            self._lose_worker("prefill", w, e)
            return True  # handled — the caller must not also prefill it
        h.resumed = True
        self._slot_handles[("prefill", w, slot)] = h
        self.tier_resumes += 1
        self._tier_event("session_resumed", park_kind="turn", worker=w,
                         slot=slot, covered=int(raw["pos"]),
                         trace_id=h.trace_id,
                         import_s=round(time.monotonic() - t0, 6))
        return True

    def _advance_prefill(self) -> bool:
        from tpudist import telemetry

        worked = False
        for w in self._alive("prefill"):
            eng = self.prefill_pool[w]
            if not eng.prefilling_slots():
                continue
            worked = True
            try:
                self._tick("prefill", w)
                with telemetry.span("prefill",
                                    chunks=len(eng.prefilling_slots()),
                                    pool="prefill", worker=w):
                    done = eng.advance_prefill()
            except Exception as e:
                self._lose_worker("prefill", w, e)
                continue
            for slot, tok in done.items():
                self._prefill_complete(w, slot, tok)
        return worked

    def _prefill_complete(self, w: int, slot: int, tok: int) -> None:
        """A prompt finished in prefill worker ``w``: deliver token 0
        (TTFT stamps here — in the prefill pool), then either finish
        (budget of 1) or export the lane for the decode pool.  A
        recovered lane (re-prefilled after its worker died) skips the
        re-emission of a token 0 it already delivered."""
        from tpudist import telemetry

        key = ("prefill", w, slot)
        h = self._slot_handles.get(key)
        if h is None:
            # the worker died under an EARLIER completion of this same
            # batch (_export -> _lose_worker popped every lane it
            # hosted, this one included — it is already requeued/aborted)
            return
        h.t_prefill_done = time.monotonic()
        eos = h.request.eos_id
        eng = self.prefill_pool[w]
        if h.id in self._skip:
            # prefill replay complete: the lane is whole again
            replayed = self._skip.pop(h.id)
            self.lanes_recovered += 1
            telemetry.event("lane_recovered", pool="prefill", worker=w,
                            slot=slot, trace_id=h.trace_id,
                            replayed=replayed)
            if replayed > 0:
                # token 0 was already delivered by the lost worker —
                # the re-emission is a duplicate, drop it (its finish
                # checks ran at original delivery and did not fire,
                # else the lane would never have been requeued)
                tok = None
        if tok is not None:
            tg = h.request.grammar
            if tg is not None and not tg.token_allowed(h.gstate, tok):
                # the device mask makes this unreachable unless the pool
                # tables and the host shadow diverge — truncate BEFORE
                # the violating token delivers
                del self._slot_handles[key]
                eng.evict(slot)
                h._finish("grammar_violation")
                self._note_finished(h)
                return
            if tg is not None:
                h.gstate = tg.advance(h.gstate, tok)
            h._deliver(tok)
            if h.request.logprobs > 0:
                # token 0 is prefill-sampled: no logprobs row rides it
                h.logprobs.append(None)
            self.tokens_out += 1
            reason = None
            if eos is not None and tok == eos:
                reason = "eos"
            elif h.request.stop and any(
                    len(h.tokens) >= len(s)
                    and tuple(h.tokens[-len(s):]) == s
                    for s in h.request.stop):
                reason = "stop_sequence"
            elif len(h.tokens) >= h.request.max_new:
                reason = "session_resumed" if h.resumed else "length"
            if reason is not None:
                del self._slot_handles[key]
                if (self._tier is not None
                        and h.request.session is not None
                        and reason != "stop_sequence"
                        and eng.exportable(slot, len(h.tokens))):
                    # a max_new==1 turn finishes in-prefill: its lane
                    # still parks for the session's next turn
                    try:
                        self._tick("prefill", w)
                        self._park_session_lane(eng, slot, h)
                    except Exception as e:
                        h._finish(reason)
                        self._note_finished(h)
                        self._lose_worker("prefill", w, e)
                        return
                eng.evict(slot)
                h._finish(reason)
                self._note_finished(h)
                return
        if not self._alive("decode"):
            # decode pool collapsed: the remaining budget can never be
            # served — finish loudly instead of queueing forever
            del self._slot_handles[key]
            eng.evict(slot)
            h._finish("worker_lost")
            self._note_finished(h)
            return
        if len(self._handoff) >= self.handoff_limit:
            # queue full: the lane waits in its prefill slot; retried on
            # a later iteration (the slot stays occupied → admission
            # backpressure).  Mark it ready by leaving decoding=True.
            return
        self._export(w, slot, h)

    def _export(self, w: int, slot: int, h: RequestHandle) -> None:
        eng = self.prefill_pool[w]
        try:
            self._tick("prefill", w)
            pkg = eng.export_slot(slot)
            # the trace_id crosses the pool boundary IN the package (the
            # wire field is what joins the lifeline when the pools are
            # separate processes; schema v3)
            pkg["trace_id"] = h.trace_id
            if self.handoff_mode == "serial":
                ser = serialize_package(pkg)
                self.handoff_bytes += ser["bytes"]
                pkg = ser
            eng.evict(slot)
        except Exception as e:
            # the worker died exporting: the lane is still registered
            # under this key — standard recovery (full re-prefill on a
            # survivor; the already-delivered token 0 skips once)
            self._lose_worker("prefill", w, e)
            return
        del self._slot_handles[("prefill", w, slot)]
        self._handoff.append((h, pkg))
        self.handoffs += 1

    def _retry_stalled_exports(self) -> bool:
        """Prefill slots whose export stalled on a full handoff queue
        (decoding=True but still in the prefill pool) retry here."""
        worked = False
        for w in self._alive("prefill"):
            eng = self.prefill_pool[w]
            for slot in list(range(eng.num_slots)):
                key = ("prefill", w, slot)
                if (eng.decoding[slot] and key in self._slot_handles
                        and len(self._handoff) < self.handoff_limit):
                    if not self._alive("decode"):
                        break
                    self._export(w, slot, self._slot_handles[key])
                    worked = True
        return worked

    # -- handoff → decode pool ---------------------------------------------

    def _place_handoffs(self) -> bool:
        from tpudist import telemetry

        self._retry_stalled_exports()
        worked = False
        while self._handoff:
            h, pkg = self._handoff[0]
            if self.handoff_mode == "serial":
                # cheap envelope check first: a schema-mismatched package
                # must not wedge the queue head (or crash can_import on
                # missing fields) — finish ITS request, keep serving
                try:
                    check_package_schema(pkg)
                except HandoffError as e:
                    self._handoff.popleft()
                    self._reject_package(h, e)
                    worked = True
                    continue
            placed = False
            for w in self._alive("decode"):
                eng = self.decode_pool[w]
                free = eng.free_slots()
                # gate on the serialized dict directly (pos/budget/paged
                # are top-level fields either way) — a full decode pool
                # must not pay a full-lane deserialization per blocked
                # loop iteration just to fail placement
                if not free or not eng.can_import(pkg):
                    continue
                self._handoff.popleft()
                if self.handoff_mode == "serial":
                    try:
                        raw = deserialize_package(pkg)
                    except HandoffError as e:
                        # wire corruption (digest mismatch): this lane's
                        # KV is gone — a reason, not garbage attention
                        self._reject_package(h, e)
                        placed = worked = True
                        break
                else:
                    raw = pkg
                slot = free[0]
                t0 = time.monotonic()
                from tpudist.serve.adapters import AdapterMissingError

                from tpudist.constrain.registry import GrammarPoolFull

                try:
                    self._tick("decode", w)
                    eng.import_slot(slot, raw, spec=h.request.spec)
                except GrammarPoolFull:
                    # every grammar block on this decode worker is
                    # pinned: the package is intact — back to the queue
                    # head, stalled head-of-line (like a full pool) and
                    # retried next iteration as lanes finish.  NOT a
                    # worker death, and NOT placed (placed=True would
                    # spin this same head forever within one call).
                    self._handoff.appendleft((h, pkg))
                    placed = False
                    break
                except AdapterMissingError:
                    # the decode pool cannot re-bind the package's
                    # adapter name (unloaded while the lane crossed the
                    # queue): ITS request finishes loudly — the lane's
                    # KV is the fine-tune's, continuing base would be
                    # wrong bytes.  NOT a worker death.
                    h._finish("adapter_missing")
                    self._note_finished(h)
                    placed = worked = True
                    break
                except Exception as e:
                    # the worker died importing: the package is intact —
                    # back to the queue head, a survivor takes it
                    self._handoff.appendleft((h, pkg))
                    self._lose_worker("decode", w, e)
                    placed = worked = True
                    break
                if h.t_decode_start is None:
                    h.t_decode_start = time.monotonic()
                # one decode segment per residency: a replay after
                # worker loss opens a SECOND segment on the survivor —
                # the visible jump in the exported timeline
                h.decode_segments.append([w, time.monotonic(), None])
                h.slot = slot
                telemetry.event(
                    "kv_handoff", worker=w, slot=slot,
                    mode=self.handoff_mode, trace_id=h.trace_id,
                    wait_s=round(h.handoff_wait_s or 0.0, 6),
                    import_s=round(time.monotonic() - t0, 6))
                self._slot_handles[("decode", w, slot)] = h
                # replay stash: what a dead worker's lanes recover from.
                # A RECOVERY placement still owes _skip duplicates, so
                # the package-equivalent delivered count is len(tokens)
                # MINUS the pending skip — stashing raw len would make a
                # SECOND loss of this lane under-skip and re-deliver
                # already-streamed tokens
                self._import_pkg[(w, slot)] = (
                    pkg, len(h.tokens) - self._skip.get(h.id, 0))
                if h.id in self._skip:
                    # this IS a recovery placement — the lane continues
                    # byte-identically (re-decoded tokens up to the
                    # skip count drop as duplicates)
                    self.lanes_recovered += 1
                    telemetry.event("lane_recovered", pool="decode",
                                    worker=w, slot=slot,
                                    trace_id=h.trace_id,
                                    replayed=self._skip[h.id])
                    if self._skip[h.id] == 0:
                        del self._skip[h.id]
                placed = worked = True
                break
            if not placed:
                break  # FIFO head-of-line: decode pool is full
        return worked

    # -- decode pool --------------------------------------------------------

    def _decode(self) -> bool:
        from tpudist import telemetry

        worked = False
        for w in self._alive("decode"):
            eng = self.decode_pool[w]
            for slot in eng.cache_full_slots():
                if ("decode", w, slot) in self._slot_handles:
                    self._finish_key(("decode", w, slot), "cache_full")
            if not eng.num_active:
                continue
            worked = True
            occ = eng.occupancy
            tele = telemetry.active()
            t0 = time.monotonic()
            try:
                self._tick("decode", w)
                info, blocks = eng.decode_auto()
            except Exception as e:
                # the worker died mid-decode: its lanes replay their
                # stashed packages on survivors (byte-identical — module
                # doc), or the loop aborts if recovery is off
                self._lose_worker("decode", w, e)
                continue
            if tele is not None and info is not None:
                kv_occ, kv_resident = eng.kv_gauges()
                tags = {"occupancy": occ, "active": eng.num_active,
                        "k": info["k"], "tokens": info["tokens"],
                        "dispatch_s": round(info["dispatch_s"], 9),
                        "sync_s": round(info["sync_s"], 9),
                        "kv_block_occupancy": kv_occ,
                        "kv_bytes_resident": kv_resident,
                        "kv_read_bytes": info["kv_read_bytes"],
                        "pool": "decode", "worker": w}
                if info.get("spec"):
                    tags.update(accepted=info["accepted"],
                                drafted=info["drafted"],
                                rollbacks=info["rollbacks"],
                                draft_s=round(info["draft_s"], 9),
                                verify_s=round(info["verify_s"], 9))
                    if info.get("accept_by_adapter"):
                        # per-adapter accept labels ride the span —
                        # the metrics feeder turns them into the
                        # labeled acceptance gauges
                        tags["accept_by_adapter"] = \
                            info["accept_by_adapter"]
                    tele.record_span("spec_verify", t0,
                                     time.monotonic() - t0, tags)
                else:
                    tele.record_span("decode_block", t0,
                                     time.monotonic() - t0, tags)
            block_lp = (info or {}).get("logprobs") or {}
            for slot, toks in blocks.items():
                self._deliver_block(w, slot, toks, block_lp.get(slot))
        return worked

    def _deliver_block(self, w: int, slot: int, toks, lp=None) -> None:
        h = self._slot_handles.get(("decode", w, slot))
        if h is None:
            # the worker died delivering an EARLIER slot of this same
            # block (_finish_key's evict -> _lose_worker re-routed the
            # remaining lanes): these tokens re-emit on replay — do not
            # deliver them here too, the replay-skip count is already set
            return
        eos = h.request.eos_id
        tg = h.request.grammar
        if self._ctrl is not None:
            # the fairness gate's measurement: DELIVERED tokens/s per
            # tenant — replay/fallback duplicates are dropped below and
            # must not inflate the measured rate
            delivered = max(0, len(toks) - self._skip.get(h.id, 0))
            if delivered:
                self._ctrl.note_tokens(h.request.tenant, delivered)
        for i, tok in enumerate(toks):
            skip = self._skip.get(h.id, 0)
            if skip > 0:
                # replay of a recovered lane: this token was already
                # delivered by the lost worker — the re-emission is a
                # duplicate (its finish checks — and its shadow-automaton
                # advance — ran the first time)
                if skip == 1:
                    del self._skip[h.id]
                else:
                    self._skip[h.id] = skip - 1
                continue
            if tg is not None:
                if not tg.token_allowed(h.gstate, tok):
                    # defense in depth: unreachable unless the pool
                    # tables and the host shadow diverge — truncate
                    # BEFORE the violating token delivers
                    self._finish_key(("decode", w, slot),
                                     "grammar_violation")
                    return
                h.gstate = tg.advance(h.gstate, tok)
            h._deliver(tok)
            if h.request.logprobs > 0:
                n = h.request.logprobs
                row = lp[i] if lp is not None and i < len(lp) else None
                h.logprobs.append(None if row is None
                                  else (row[0][:n], row[1][:n]))
            self.tokens_out += 1
            if eos is not None and tok == eos:
                self._finish_key(("decode", w, slot), "eos")
                return
            if h.request.stop and any(
                    len(h.tokens) >= len(s)
                    and tuple(h.tokens[-len(s):]) == s
                    for s in h.request.stop):
                self._finish_key(("decode", w, slot), "stop_sequence")
                return
            if len(h.tokens) >= h.request.max_new:
                # a resumed turn's budget-completion is countable from
                # the finish reasons alone (the bench's resume column)
                self._finish_key(("decode", w, slot),
                                 "session_resumed" if h.resumed
                                 else "length")
                return

    def _finish_key(self, key, reason: str) -> None:
        pool, w, slot = key
        h = self._slot_handles.pop(key)
        if pool == "decode":
            self._import_pkg.pop((w, slot), None)
        # finish FIRST: once popped from _slot_handles this handle is
        # invisible to _abort_outstanding, so if the evict below kills
        # the worker with recovery OFF (_lose_worker re-raises), a
        # not-yet-finished handle would strand its waiter forever
        h._finish(reason)
        self._note_finished(h)
        if w not in self._dead[pool]:
            eng = (self.prefill_pool if pool == "prefill"
                   else self.decode_pool)[w]
            if (pool == "decode" and self._tier is not None
                    and h.request.session is not None
                    and reason in ("length", "eos", "session_resumed")
                    and eng.exportable(slot, len(h.tokens))):
                # park the finished turn's lane (host-tier session
                # tier) before the evict zeroes it — the export is an
                # engine call, so a death here rides the standard
                # worker-lost path (the handle is already finished)
                try:
                    self._tick("decode", w)
                    self._park_session_lane(eng, slot, h)
                except Exception as e:
                    self._lose_worker(pool, w, e)
                    return
            try:
                eng.evict(slot)
            except Exception as e:
                # the evict itself killed the worker: this handle is
                # already finished; its REMAINING lanes recover
                self._lose_worker(pool, w, e)

    def _note_finished(self, h: RequestHandle) -> None:
        from tpudist import telemetry
        from tpudist.telemetry import trace

        # the ONE cleanup point for recovery bookkeeping: every finish
        # path funnels here, so a recovering lane that ends early (a
        # deadline sweep while its replay waits in the queue, a drain)
        # can never leak its replay-skip entry or oversize-preempt memo
        self._skip.pop(h.id, None)
        self._tier_oversize.discard(h.id)
        self.completed += 1
        self._track_tenant(h.request.tenant, -1)
        if self._capture is not None:
            # the distillation flywheel's tap: the finished stream is
            # the training example (bounded ring, drops counted)
            self._capture.offer_handle(h)
        # close the last decode residency segment at the request's end
        if h.decode_segments and h.decode_segments[-1][2] is None:
            h.decode_segments[-1][2] = h.t_done
        telemetry.event(
            "request_finished", id=h.id, reason=h.finish_reason,
            prompt_len=int(len(h.request.prompt)), tokens_out=len(h.tokens),
            ttft_s=h.ttft_s, tpot_s=h.tpot_s, queue_wait_s=h.queue_wait_s,
            pool="disagg", handoff_wait_s=h.handoff_wait_s,
            trace_id=h.trace_id,
            **({"tenant": h.request.tenant} if h.request.tenant else {}),
            **({"adapter": h.request.adapter} if h.request.adapter else {}),
            **({"constrained": h.request.grammar.source["kind"]}
               if h.request.grammar is not None else {}),
            **({"stop_seqs": len(h.request.stop)} if h.request.stop
               else {}),
            **({"logprobs": h.request.logprobs} if h.request.logprobs
               else {}))
        # per-request lifeline (req_queue → req_prefill → req_handoff →
        # one req_decode per residency segment): the cross-pool trace
        trace.emit_request_lifeline(h)
