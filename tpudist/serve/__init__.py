"""Continuous-batching inference serving (the north star's "heavy
traffic" half — training alone was the repo's whole surface before this
subsystem).

Three layers, device-to-host:

- :mod:`tpudist.serve.engine` — ``SlotEngine``: fixed-shape slot lanes
  with on-device per-slot state, fused multi-token decode blocks (one
  dispatch + one host sync per K tokens), and chunked prefill (prompts
  past the pad admit and append chunk by chunk) — zero recompilation as
  requests churn;
- :mod:`tpudist.serve.scheduler` — bounded FIFO with admission control,
  deadline enforcement, reject-with-reason backpressure;
- :mod:`tpudist.serve.server` — ``InferenceServer``: threaded ingestion,
  streaming token callbacks, SIGTERM graceful drain, telemetry.

``ServeConfig(paged=True)`` swaps the dense per-slot arenas for a paged
KV cache — block pool + per-slot block tables
(:mod:`tpudist.models.paged`), host-side block accounting with
shared-prefix reuse and refcounts (:mod:`tpudist.serve.paged_alloc`),
optional int8 KV storage — decoupling slot count from ``max_len``.

``ServeConfig(mesh="DxM")`` runs the same four compiled programs SPMD
over a multi-chip mesh (:mod:`tpudist.serve.spmd`): params and KV
storage get TP/slot shardings, the host logic is unchanged, greedy
output stays byte-identical at every mesh shape.
``ServeConfig(disagg=True)`` splits prefill and decode into separate
worker pools with KV handoff between them
(:mod:`tpudist.serve.disagg`).
``ServeConfig(spec=True)`` adds speculative decoding: a small draft
model proposes K tokens per slot, the target verifies all of them in
ONE batched multi-token pass — fewer target passes per emitted token,
the lever past the measured decode HBM roofline.  Greedy output stays
byte-identical to the sequential oracle; per-request ``spec=False``
opts out in-batch.
``ServeConfig(host_tier=True)`` adds the overload-robustness layer
(:mod:`tpudist.serve.host_tier`, :mod:`tpudist.serve.overload`): idle
session lanes and priority-preempted decode lanes park in a
byte-budgeted host-RAM store and resume without recompute
(``submit(session=..., priority=...)``); ``ServeConfig(shed=True)``
turns the live per-tenant SLO-attainment gauges into load-shedding
decisions.

``ServeConfig(adapters=True)`` adds per-tenant adapters
(:mod:`tpudist.serve.adapters`, :mod:`tpudist.models.lora`): a paged
multi-LoRA factor pool next to the KV pool — ``load_adapter(name,
factors)`` + ``submit(adapter=name)`` decode ``base(x) +
gather(B)·gather(A)·x`` with each slot's rank-r factors gathered
in-graph, zero recompilation as tenants churn, bit-exact base path for
adapter-less lanes.

:class:`FleetRouter` (:mod:`tpudist.serve.router`) is the layer above
one server: a fleet front door over N replicas routing by session
affinity (resumes land where the KV parked), prefix-cache affinity
(rendezvous hashing on a prompt-prefix digest), then least-loaded
placement — with health-probed failover, spill-not-reject overflow, a
bounded duplicate-dropping retry path that keeps re-homed streams
byte-identical, and parked-session migration over the
``serialize_package`` wire format when a replica drains or dies.

``python -m tpudist.serve`` runs a self-contained CPU demo
(``--replicas N`` runs it through the fleet router).
"""

from tpudist.serve.adapters import (  # noqa: F401
    AdapterMissingError,
    AdapterPoolFull,
    AdapterRegistry,
)
from tpudist.serve.disagg import DisaggServer  # noqa: F401
from tpudist.serve.engine import SlotEngine  # noqa: F401
from tpudist.serve.host_tier import HostKVTier, HostTierError  # noqa: F401
from tpudist.serve.overload import OverloadController  # noqa: F401
from tpudist.serve.router import (  # noqa: F401
    FleetRouter,
    RouterConfig,
    RouterHandle,
)
from tpudist.serve.spmd import ServeMeshConfig  # noqa: F401
from tpudist.serve.scheduler import (  # noqa: F401
    AdmissionError,
    Request,
    RequestHandle,
    Scheduler,
)
from tpudist.serve.server import (  # noqa: F401
    InferenceServer,
    ServeConfig,
    serve_forever,
)
