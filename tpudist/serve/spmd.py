"""SPMD sharding for the slot-decode serving engine.

The four compiled serving programs (``insert_batch`` / ``prefill_extend``
/ ``decode_block`` / ``evict``) are ordinary ``jax.jit`` programs, so
running them over a multi-chip ``jax.sharding.Mesh`` is a LAYOUT change,
not a code change: params and the KV storage get ``NamedSharding``s, the
host engine keeps issuing the exact same fixed-shape programs, and the
XLA SPMD partitioner splits the work (veScale's eager-SPMD consistency
argument, arXiv:2509.07003 — single-device semantics preserved while
shardings, not programs, vary).  This module owns those layouts plus the
one place serving code *does* change shape: the overlapped TP MLP.

Mesh axes (``data × model``, either may be 1):

- ``model`` — tensor parallelism.  The KV cache/pool shards over the
  **kv-heads** axis (attention is per-head independent, so the dominant
  serving bytes split with zero cross-device reduction), and the
  column-parallel weight matrices (``qkv``, ``wi``, ``head`` — plus
  ``wo`` when the overlapped MLP runs) shard over their OUTPUT dim.
- ``data`` — slot parallelism: the dense per-slot cache arenas shard
  over the slot axis (each device group owns a slice of the lanes).
  The paged pool has no per-slot storage axis; ``data`` is a no-op
  there (pool shards over kv-heads only).

**The byte-identity invariant.**  Every dim these layouts shard is an
*output* or *batch* dim — never a contraction and never a
normalized-reduction dim — so the data movement the layout *requests*
is all slices/gathers (bit-exact).  The partitioner retains latitude in
how it re-replicates a sharded activation (the comm audit shows it
sometimes picks partial sums over a gather), so the contract is pinned
where it matters: the serving test suite asserts greedy output
byte-identical to the single-device sequential oracle at every
supported mesh shape, dense and paged, overlap routing on and off (the
same oracle the paged cache and the fused decode block had to meet).
This is also why the serving layout is
NOT :func:`tpudist.models.transformer.transformer_tp_sharding`: the
Megatron row-parallel halves (``proj``/``wo`` row-split) imply a psum
that reassociates the contraction — fine for training (bounded drift),
disqualifying for a serving engine whose acceptance oracle is bitwise.

**Overlapped TP decode.**  With the column layout alone, ``wi``'s
col-sharded product leaves the FFN activation sharded on ``d_ff``, and
the partitioner must move it before the ``wo`` matmul — whatever form
it picks (on the audited backend: reshard collective-permutes plus a
partial-sum all-reduce of the FFN output), those bytes are EXPOSED:
scheduled on the decode critical path, nothing hidden under compute.
:func:`serve_overlap_mlp_fn` instead routes
both FFN matmuls through :func:`tpudist.parallel.overlap.ag_matmul`
(``gather="rhs"``, the bit-exact column geometry): the weight shards
ride a ``ppermute`` ring one chunk per hop, each hop hidden under the
previous chunk's matmul, every hop tagged ``tpudist_overlap`` so
``benchmarks/comm_audit.py``'s ``serve_decode_tp_*`` regimes can prove
from optimized HLO that the decode path's collective bytes are
overlapped, not exposed.  Selection: the ``TPUDIST_SERVE_TP_OVERLAP``
knob (falls back to ``TPUDIST_OVERLAP``; off by default).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax

from tpudist.runtime.mesh import AXIS_DATA, AXIS_MODEL


@dataclasses.dataclass(frozen=True)
class ServeMeshConfig:
    """Declarative serving-mesh geometry (AMP-style: a future planner
    searches these fields, it does not rewrite engine code).

    ``shape``: ``"DxM"`` (data × model) or a bare ``"M"`` (pure TP,
    data = 1).  ``"1"``/``"1x1"``/empty mean no mesh (single device).
    """

    shape: str = "1"
    tp_overlap: Optional[str] = None  # None: knob decides; "off"/"ring"/...

    @property
    def dims(self) -> tuple:
        s = (self.shape or "1").strip().lower().replace("×", "x")
        parts = [p for p in s.split("x") if p]
        try:
            nums = [int(p) for p in parts]
        except ValueError:
            raise ValueError(
                f"serve mesh shape must be 'DxM' or 'M', got {self.shape!r}")
        if len(nums) == 1:
            nums = [1, nums[0]]
        if len(nums) != 2 or any(n < 1 for n in nums):
            raise ValueError(
                f"serve mesh shape must be 'DxM' or 'M', got {self.shape!r}")
        return tuple(nums)

    @property
    def n_devices(self) -> int:
        d, m = self.dims
        return d * m

    @property
    def enabled(self) -> bool:
        return self.n_devices > 1

    @classmethod
    def from_env(cls) -> "ServeMeshConfig":
        import os

        shape = os.environ.get("TPUDIST_SERVE_MESH", "").strip() or "1"
        overlap = os.environ.get("TPUDIST_SERVE_TP_OVERLAP", "").strip()
        return cls(shape=shape, tp_overlap=overlap or None)


def build_serve_mesh(cfg: ServeMeshConfig):
    """``jax.sharding.Mesh`` of shape ``(data, model)`` over the first
    ``data*model`` local devices, or ``None`` when the config is 1x1."""
    if not cfg.enabled:
        return None
    import numpy as np
    from jax.sharding import Mesh

    d, m = cfg.dims
    devs = jax.devices()
    if len(devs) < d * m:
        raise ValueError(
            f"serve mesh {d}x{m} needs {d * m} devices, have {len(devs)} "
            f"({devs[0].platform}); CPU rigs can emulate more via "
            "tpurun --devices-per-proc / "
            "--xla_force_host_platform_device_count")
    return Mesh(np.asarray(devs[:d * m]).reshape(d, m),
                axis_names=(AXIS_DATA, AXIS_MODEL))


def _axis_or_none(mesh, axis: str, dim_size: int):
    """``axis`` if the mesh has it, its size > 1, and it divides
    ``dim_size`` — else ``None`` (replicate).  Sharding an indivisible
    dim is an error in jax; replicating it is merely less parallel."""
    if axis not in mesh.axis_names:
        return None
    n = mesh.shape[axis]
    if n <= 1 or dim_size % n:
        return None
    return axis


def serve_param_sharding(mesh, params, *, overlap: bool = False):
    """NamedSharding pytree for serving params under the byte-identity
    invariant: column-parallel kernels (``qkv``, ``wi``, ``head``) split
    their OUTPUT dim over ``model``; ``wo`` joins them only when the
    overlapped MLP consumes it inside its own ``shard_map`` (the plain
    path would leave a d-sharded residual feeding LayerNorm — a split
    normalized reduction, exactly the thing the invariant forbids);
    ``proj``, embeddings, norms replicate."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    col_names = {"qkv", "wi", "head"} | ({"wo"} if overlap else set())

    def spec_for(path, leaf):
        keys = [k for k in (getattr(e, "key", getattr(e, "name", None))
                            for e in path) if isinstance(k, str)]
        if "kernel" in keys and any(k in col_names for k in keys) \
                and getattr(leaf, "ndim", 0) == 2:
            axis = _axis_or_none(mesh, AXIS_MODEL, leaf.shape[1])
            if axis is not None:
                return NamedSharding(mesh, P(None, axis))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_for, params)


def serve_spec_param_sharding(mesh, draft_params):
    """Serving layout for a speculative DRAFT model's parameters:
    column-parallel kernels shard over ``model`` where the axis divides
    (same byte-identity-safe column rule as the target), everything
    else replicates.  A draft is small by construction, so replication
    is always correct and usually cheap — the column split is taken
    opportunistically when the draft's head counts allow it (a
    weight-tied draft shares the target's already-sharded params and
    never reaches this function).  The draft CACHE reuses
    :func:`serve_cache_sharding` / :func:`serve_paged_sharding` — its
    arenas have the same axis meaning as the target's."""
    return serve_param_sharding(mesh, draft_params, overlap=False)


def serve_cache_sharding(mesh, cache):
    """Sharding pytree for a DENSE slot cache: the K/V arenas
    ``[num_slots, 1, n_kv, max_len, dh]`` shard slots over ``data`` and
    kv-heads over ``model``; the tiny meta leaves (cursors, position
    counters) replicate."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def spec(leaf):
        if getattr(leaf, "ndim", 0) == 5:
            data = _axis_or_none(mesh, AXIS_DATA, leaf.shape[0])
            model = _axis_or_none(mesh, AXIS_MODEL, leaf.shape[2])
            return NamedSharding(mesh, P(data, None, model))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec, cache)


def serve_paged_sharding(mesh, pkv):
    """Sharding pytree for a :class:`tpudist.models.paged.PagedKV`: the
    pools ``[L, num_blocks, n_kv, block_size, dh]`` shard kv-heads over
    ``model`` (block ids stay global — the host allocator is
    topology-oblivious); scales follow their pool's head axis; table and
    meta replicate (they are the host's decisions, uploaded as data)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    model = _axis_or_none(mesh, AXIS_MODEL, pkv.pool_k.shape[2])
    pool = NamedSharding(mesh, P(None, None, model))
    scale = NamedSharding(mesh, P(None, None, model))
    repl = NamedSharding(mesh, P())
    return type(pkv)(
        pool_k=pool, pool_v=pool, scale_k=scale, scale_v=scale,
        table=repl, meta=jax.tree.map(lambda _: repl, pkv.meta))


def serve_adapter_sharding(mesh, apool):
    """Sharding pytree for an :class:`tpudist.models.lora.AdapterPool`:
    the B factors whose OUTPUT dim aligns with a column-parallel kernel
    (``b_qkv`` with ``qkv``, ``b_wi`` with ``wi``) shard that dim over
    ``model`` where it divides — the same byte-identity-safe column
    rule as :func:`serve_param_sharding` (slices and gathers only,
    never a split contraction).  The tiny A factors (rank-r outputs)
    and ``b_wo`` (output feeds the replicated residual, like ``proj``)
    replicate."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())

    def col(leaf):
        axis = _axis_or_none(mesh, AXIS_MODEL, leaf.shape[-1])
        if axis is None:
            return repl
        return NamedSharding(mesh, P(None, None, None, axis))

    return type(apool)(
        a_qkv=repl, b_qkv=col(apool.b_qkv),
        a_wi=repl, b_wi=col(apool.b_wi),
        a_wo=repl, b_wo=repl)


def serve_state_sharding(mesh, state):
    """SlotState replicates everywhere: it is tiny (a handful of [S]
    vectors) and the host's admission/budget logic must read it the same
    from any process — the disaggregation coordinator included."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.tree.map(lambda _: NamedSharding(mesh, P()), state)


def resolve_serve_overlap(cfg: ServeMeshConfig) -> str:
    """The TP-overlap mode for a serving mesh: the config's explicit
    ``tp_overlap`` wins; otherwise ``TPUDIST_SERVE_TP_OVERLAP`` falls
    back to the shared ``TPUDIST_OVERLAP`` knob.  Same forgiving parse
    as :func:`tpudist.parallel.overlap.overlap_mode`."""
    import os

    from tpudist.parallel.overlap import overlap_mode

    v = cfg.tp_overlap
    if v is None:
        v = os.environ.get("TPUDIST_SERVE_TP_OVERLAP", "").strip() or None
    if v is not None:
        v = v.strip().lower()
        return v if v in ("ring", "bidir") else "off"
    return overlap_mode(None)


def serve_overlap_mlp_fn(mesh, *, axis_name: str = AXIS_MODEL,
                         mode: str = "ring"):
    """The overlapped TP decode/prefill MLP for
    ``create_transformer(mlp_fn=...)`` — decode-shaped collective
    matmul.

    Both FFN matmuls run :func:`tpudist.parallel.overlap.ag_matmul`
    with ``gather="rhs"``: the kernel is stored COLUMN-sharded
    (``wi: [d, ff/n]``, ``wo: [ff, d/n]`` per device), activations are
    replicated over the model axis (a decode batch is ``num_slots``
    rows — replicating it costs nothing; sharding weights is the HBM
    win), and each ring hop moves one kernel chunk while the previous
    chunk's matmul runs.  Column gathers assemble disjoint output
    chunks, so the result is **bit-exact** vs the dense MLP — the
    serving oracle stays byte-identical with the pipeline on.  Every
    hop carries the ``tpudist_overlap`` HLO tag the comm audit keys on.

    Returns ``None`` when ``mode`` is off or the mesh has no model
    axis > 1, so call sites keep the plain Dense path by default.
    """
    from jax.sharding import PartitionSpec as P

    from tpudist.parallel.overlap import ag_matmul, compat_shard_map

    if mode not in ("ring", "bidir"):
        return None
    if axis_name not in mesh.axis_names or mesh.shape[axis_name] <= 1:
        return None

    def body(p, x):
        b, s, d = x.shape
        t = x.reshape(b * s, d)
        h = ag_matmul(t, p["wi"], axis_name=axis_name, mode=mode,
                      gather="rhs")
        h = jax.nn.gelu(h)
        y = ag_matmul(h, p["wo"], axis_name=axis_name, mode=mode,
                      gather="rhs")
        return y.reshape(b, s, d).astype(x.dtype)

    param_specs = {"wi": P(None, axis_name), "wo": P(None, axis_name)}
    sharded = compat_shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P(None, None, None)),
        out_specs=P(None, None, None))

    def mlp_fn(params, x):
        return sharded(params, x)

    mlp_fn.overlap = mode
    mlp_fn.axis_name = axis_name
    return mlp_fn


def sharded_param_bytes(params, shardings) -> dict:
    """Accounting for ``spmd_stats``: total param bytes, the bytes that
    actually shard, and the per-device resident estimate."""
    import numpy as np
    from jax.sharding import NamedSharding

    total = sharded = per_dev = 0
    for leaf, sh in zip(
            jax.tree.leaves(params),
            jax.tree.leaves(shardings,
                            is_leaf=lambda x: isinstance(x, NamedSharding))):
        b = int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
        total += b
        axes = [a for a in tuple(sh.spec) if a is not None]
        if axes:
            sharded += b
            n = 1
            for a in axes:
                n *= sh.mesh.shape[a]
            per_dev += b // n
        else:
            per_dev += b
    return {"param_bytes_total": total, "param_bytes_sharded": sharded,
            "param_bytes_per_device": per_dev}
