"""SLO-aware admission control: measure, then schedule against the
measurement.

The live observability plane (PR 13) turned every ``request_finished``
into per-tenant SLO-attainment gauges (``tpudist_slo_attainment``, fed
from the declared ``TPUDIST_SLO_TTFT_MS``/``TPUDIST_SLO_TPOT_MS``
targets).  This module is the consumer those gauges were built for —
the serving loops' reject-with-reason gate stops guessing and acts on
what the registry measured (the AMP lesson: a measured cost model beats
heuristics; the DDP/FSDP-characterization lesson: schedule against the
measurement):

- **load shedding** — a *protected* priority class is declared
  (``shed_priority``; a tenant is protected while it has recent traffic
  at or above it).  When any protected tenant's LIVE attainment gauge
  falls below ``shed_attainment``, shedding activates: new
  lower-priority submits reject with reason ``"shed_load"`` and queued
  lower-priority work is finished with the same reason — overload
  degrades the bulk class explicitly instead of degrading everyone's
  SLO silently.  Every flip emits a ``shed_state`` event carrying the
  gauge values that drove it, so the decision is auditable from the
  telemetry stream alone;
- **per-tenant token-rate fairness** — an EWMA tokens/s rate per tenant;
  once the queue is under pressure (more than half full), a tenant
  drawing more than ``fair_share ×`` its equal share of the total
  measured rate rejects with reason ``"fair_share"`` (``0`` disables —
  the default).

Both gates are consulted synchronously at submit (under the scheduler
lock) and must stay cheap: the attainment read is a cached flag
refreshed by :meth:`OverloadController.tick` from the engine loop, and
the rate update is two float ops.
"""

from __future__ import annotations

import math
import time
from typing import Dict, Optional

#: Shed-state refresh cadence: gauge reads per tick are cheap, but the
#: engine loop can spin at kHz while idle — no need to rescan faster.
_TICK_EVERY_S = 0.05

#: A tenant stays "protected" this long after its last at-or-above-
#: protect-priority submit (a gold tenant that pauses between turns must
#: not lose its protection mid-conversation).
_PROTECT_WINDOW_S = 30.0


class OverloadController:
    """The SLO-aware shed + fair-share gate (module doc).

    Thread contract: ``gate``/``note_submit`` run under the scheduler
    lock from submit threads; ``tick``/``note_tokens`` from the engine
    loop.  Shared state is plain floats/dicts mutated GIL-atomically —
    a stale-by-one-tick read is fine, a lock on the submit path is not.
    """

    def __init__(self, *, shed: bool = True, shed_attainment: float = 0.9,
                 shed_priority: int = 1, fair_share: float = 0.0,
                 rate_window_s: float = 5.0, queue_limit: int = 64):
        self.queue_limit = int(queue_limit)
        self.shed = bool(shed)
        self.shed_attainment = float(shed_attainment)
        self.shed_priority = int(shed_priority)
        self.fair_share = float(fair_share)
        self.rate_window_s = float(rate_window_s)
        self.shed_active = False
        #: the gauge readings that drove the last flip (audit trail)
        self.last_attainment: Dict[str, float] = {}
        self.sheds = 0          # queued requests shed (server increments)
        self.shed_rejects = 0   # submits rejected "shed_load"
        self.fair_rejects = 0   # submits rejected "fair_share"
        self.flips = 0
        self._protected: Dict[str, float] = {}  # tenant -> last seen t
        self._rates: Dict[str, list] = {}  # tenant -> [ewma_tps, last_t]
        self._last_tick = 0.0
        #: fair-share threshold cache, refreshed by tick(): (per-tenant
        #: equal share × multiplier, active tenant count).  gate() runs
        #: under the scheduler lock on every submit — it must read two
        #: cached floats, never rebuild an O(#tenants) dict there.
        self._fair_threshold = 0.0
        self._fair_tenants = 0

    # -- submit-side (under the scheduler lock) ------------------------------

    def note_submit(self, priority: int, tenant: Optional[str],
                    now: Optional[float] = None) -> None:
        if priority >= self.shed_priority:
            self._protected[tenant or "default"] = \
                time.monotonic() if now is None else now

    def gate(self, req, pending: int) -> Optional[str]:
        """The scheduler's ``admission_gate``: a machine-readable reject
        reason, or ``None`` to admit.  Protected-class requests are
        never shed (that is the point); fair-share applies to everyone
        once the queue is under pressure."""
        self.note_submit(req.priority, req.tenant)
        if (self.shed and self.shed_active
                and req.priority < self.shed_priority):
            self.shed_rejects += 1
            return "shed_load"
        if (self.fair_share > 0 and pending * 2 >= self.queue_limit
                and self._fair_tenants > 1 and self._fair_threshold > 0):
            r = self._rates.get(req.tenant or "default")
            if r is not None and r[0] > self._fair_threshold:
                self.fair_rejects += 1
                return (f"fair_share: tenant {req.tenant or 'default'} "
                        f"at {r[0]:.1f} tok/s > {self.fair_share:.1f}x "
                        f"equal share over {self._fair_tenants} tenants")
        return None

    # -- engine-loop side ----------------------------------------------------

    def note_tokens(self, tenant: Optional[str], n: int,
                    now: Optional[float] = None) -> None:
        """Fold ``n`` delivered tokens into the tenant's EWMA tokens/s
        (half-life ``rate_window_s``) — the fairness gate's measurement."""
        now = time.monotonic() if now is None else now
        r = self._rates.get(tenant or "default")
        if r is None:
            self._rates[tenant or "default"] = [n / self.rate_window_s, now]
            return
        dt = max(now - r[1], 1e-6)
        decay = math.exp(-dt / self.rate_window_s)
        r[0] = r[0] * decay + n / self.rate_window_s
        r[1] = now

    def shed_predicate(self, handle) -> bool:
        """Queued-work shed rule: everything below the protected class."""
        return handle.request.priority < self.shed_priority

    def tick(self, now: Optional[float] = None) -> bool:
        """Refresh ``shed_active`` from the LIVE per-tenant attainment
        gauges (:func:`tpudist.telemetry.metrics.slo_attainment`) —
        called from the engine loop every iteration, rescans at most
        every ``_TICK_EVERY_S``.  Returns True when the state flipped
        (the server emits the ``shed_state`` event with the readings
        that drove it).  Also the upkeep point for the bounded
        controller state and the fair-share threshold cache — those
        refresh whether or not shedding is enabled."""
        now = time.monotonic() if now is None else now
        if now - self._last_tick < _TICK_EVERY_S:
            return False
        self._last_tick = now
        from tpudist.telemetry import metrics

        cutoff = now - _PROTECT_WINDOW_S
        # bounded state, the TENANT_LABEL_CAP discipline: stale
        # protection entries and fully-decayed rates prune here, so a
        # per-user-UUID tenant stream cannot grow the controller (or
        # the under-lock gate) without limit
        for t, ts in list(self._protected.items()):
            if ts < cutoff:
                del self._protected[t]
        for t, r in list(self._rates.items()):
            if r[0] < 1e-3 and now - r[1] > self.rate_window_s:
                del self._rates[t]
        live = [r[0] for r in self._rates.values() if r[0] > 0]
        self._fair_tenants = len(live)
        self._fair_threshold = (self.fair_share * sum(live) / len(live)
                                if live else 0.0)
        if not self.shed:
            return False
        protected = set(self._protected)
        gauges = metrics.slo_attainment()
        readings: Dict[str, float] = {}
        for (metric, tenant), value in gauges.items():
            if tenant in protected:
                readings[f"{metric}/{tenant}"] = value
        # past the registry's TENANT_LABEL_CAP, overflow tenants pool
        # under the "other" label — a protected tenant with NO gauge of
        # its own must read the pooled one, or its protection silently
        # evaporates at exactly the many-tenant scale this layer targets
        gauge_tenants = {t for _, t in gauges}
        if any(t not in gauge_tenants for t in protected):
            for (metric, tenant), value in gauges.items():
                if tenant == "other":
                    readings.setdefault(f"{metric}/other", value)
        worst = min(readings.values()) if readings else None
        want = worst is not None and worst < self.shed_attainment
        flipped = want != self.shed_active
        if flipped:
            self.shed_active = want
            self.last_attainment = dict(readings)
            self.flips += 1
        return flipped

    def stats(self) -> Dict[str, object]:
        return {
            "shed_enabled": self.shed,
            "shed_active": self.shed_active,
            "shed_attainment_target": self.shed_attainment,
            "shed_priority": self.shed_priority,
            "sheds": self.sheds,
            "shed_rejects": self.shed_rejects,
            "fair_rejects": self.fair_rejects,
            "flips": self.flips,
            "last_attainment": dict(self.last_attainment),
            "tenant_rates_tps": {t: round(r[0], 3)
                                 for t, r in self._rates.items()},
        }
