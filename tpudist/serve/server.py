"""Threaded serving front-end: ingestion, the engine loop, graceful drain.

Wiring (one picture)::

    submit() threads ──> Scheduler (bounded FIFO, admission)      host
                              │ take(free_slots)
                              ▼
    engine thread ───> SlotEngine.start_batch / advance_prefill   device
                       / decode_block / evict
                              │ token blocks
                              ▼
                       RequestHandle streaming callbacks, done events

One background thread drives the engine (the device programs are
serialized anyway — a thread per request would only add contention);
any number of caller threads submit.  Each loop iteration admits into
free slots (one fused prefill+scatter dispatch), feeds one prompt chunk
to every still-prefilling slot (chunked prefill — a long prompt stalls
decode by at most one chunk per iteration), then runs ONE fused decode
block (``K`` tokens per slot per dispatch, ``K`` picked from the host
shadow budgets).  Tokens stream per request as each block lands; a
request's ``eos_id`` truncates its block post-hoc (finish reason
``"eos"``).  Deadlines are enforced between blocks, so a request can
overshoot its deadline by at most one block.

SIGTERM reuses the training stack's preemption flag
(:mod:`tpudist.runtime.preemption`): the loop checks it every iteration
and, once set, stops admitting (new submits reject with ``"draining"``),
finishes everything already admitted — queued AND in-slot — then exits.
The same drain runs on :meth:`InferenceServer.close`, so a deploy
rollover never cuts a response mid-stream.

Telemetry (the PR-2 subsystem) brackets the device programs —
``prefill`` spans (admission batches and chunk feeds) and
``decode_block`` spans tagged with the batch occupancy gauge, the block
size ``k``, tokens emitted, and the dispatch-vs-host-sync attribution —
and stamps a ``request_finished`` event per request carrying
TTFT/TPOT/queue-wait, which the aggregator folds into the run report's
serving section (:mod:`tpudist.telemetry.aggregate`).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tpudist.serve.engine import SlotEngine
from tpudist.serve.scheduler import AdmissionError, RequestHandle, Scheduler

#: poll interval of an idle engine loop (also the latency to notice a
#: drain request while idle) — host-side only, no device work while idle.
_IDLE_WAIT_S = 0.01


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs; :meth:`from_env` reads the ``TPUDIST_SERVE_*``
    family (registered in ``tpudist.utils.envutil.ENV_VARS``)."""

    # measurement-driven planning (tpudist.plan): score the legal
    # engine configs against the frozen bench artifacts and fill every
    # performance knob left at its default; explicitly-set knobs win.
    # The chosen plan stamps into telemetry as ``plan_selected``.
    auto: bool = False
    num_slots: int = 4
    queue_limit: int = 64
    max_new: int = 64  # default per-request token budget
    prefill_pad: Optional[int] = None  # chunk size; None: min(max_len, 64)
    deadline_s: Optional[float] = None  # default per-request deadline
    decode_block: int = 8  # max fused decode tokens per dispatch (K)
    # -- paged KV cache (tpudist/models/paged.py) --------------------------
    paged: bool = False  # block pool + block tables instead of dense arenas
    kv_block: int = 16  # tokens per KV block (must divide max_len)
    # pool size in blocks; None = dense-equivalent bytes (num_slots ×
    # max_len / kv_block) — raise num_slots at fixed kv_blocks for the
    # capacity win
    kv_blocks: Optional[int] = None
    kv_int8: bool = False  # int8 KV storage + per-block scales
    prefix_cache_blocks: int = 0  # shared-prefix LRU cache bound (blocks)
    # decode attention path on the paged cache: "gather" (dense view
    # per dispatch) or "paged" (the Pallas paged-attention kernel —
    # block table walked in-kernel, decode bytes/token ∝ live KV)
    attn_kernel: str = "gather"
    # -- the kernel family's other members (tpudist/ops/) ------------------
    # prefill through the paged-prefill flash kernel (block table walked
    # AND written in-kernel — prefill bytes ∝ chunk + reused prefix,
    # not pool geometry); requires paged
    prefill_kernel: bool = False
    # fused in-kernel sampling tail (temperature + top-k/top-p mask +
    # grammar-mask gather + greedy argmax in one pass; works on every
    # engine shape)
    sample_kernel: bool = False
    # fused RoPE+QKV projection kernel on the kernel arms (requires
    # attn_kernel="paged" and/or prefill_kernel)
    fused_rope: bool = False
    # in-kernel LoRA gather-matmul on the kernel arms (requires
    # adapters and a kernel arm)
    lora_kernel: bool = False
    # -- SPMD serving mesh (tpudist/serve/spmd.py) -------------------------
    # "DxM" (data × model) or "M"; "1" = single device.  Declarative on
    # purpose (AMP-style): a planner searches this field, not the code.
    mesh: Optional[str] = None
    tp_overlap: Optional[str] = None  # off|ring|bidir; None = knob chain
    # -- prefill/decode disaggregation (tpudist/serve/disagg.py) -----------
    disagg: bool = False  # separate prefill + decode worker pools
    prefill_workers: int = 1
    decode_workers: int = 1
    prefill_slots: Optional[int] = None  # per prefill worker; None: num_slots
    handoff: str = "device"  # "device" (in-mesh) | "serial" (byte transfer)
    handoff_queue: int = 8  # bounded pending-handoff packages
    # self-healing fleet: a dead pool worker's lanes replay onto
    # survivors (stashed handoff packages — costs one extra copy of each
    # in-flight decode lane's KV); off = any worker death aborts all
    # outstanding work as "shutdown" (the pre-recovery behavior)
    recover: bool = True
    # backpressure pool resize: consecutive loop iterations the handoff
    # queue must stay full before the prefill slot budget shrinks by one
    # (and at most half-full before it grows back); 0 = off
    pool_resize: int = 0
    # -- host-RAM KV session tier + overload control -----------------------
    # (tpudist/serve/host_tier.py, tpudist/serve/overload.py)
    host_tier: bool = False  # park idle/preempted lanes in host RAM
    host_tier_bytes: int = 1 << 30  # tier byte budget (LRU spill beyond)
    host_tier_ttl_s: Optional[float] = None  # idle parked-session expiry
    # priority preemption: a higher-priority arrival may preempt a
    # strictly-lower-priority decode lane into the host tier (resume is
    # byte-identical); effective only with host_tier on
    preempt: bool = True
    # SLO-aware load shedding: when a protected tenant's LIVE attainment
    # gauge (TPUDIST_SLO_* targets, metrics registry) drops below the
    # target, lower-priority work rejects/sheds with reason "shed_load"
    shed: bool = False
    shed_attainment: float = 0.9  # attainment floor that trips shedding
    shed_priority: int = 1  # protected priority class (>= is protected)
    # per-tenant token-rate fairness: reject a tenant drawing more than
    # this multiple of its equal share once the queue is half full
    # (0 = off)
    fair_share: float = 0.0
    # -- per-tenant adapters (tpudist/serve/adapters.py) -------------------
    # paged multi-LoRA pool: per-request `adapter=` names decode through
    # base(x) + gather(B)·gather(A)·x, zero recompilation under churn
    adapters: bool = False
    adapter_blocks: int = 8  # resident-adapter capacity (one block each)
    adapter_rank: int = 8  # LoRA rank r shared by the pool
    # -- structured output (tpudist/constrain/) ----------------------------
    # grammar-constrained decoding: per-request ``grammar=`` (regex) /
    # ``json_schema=`` asks compile host-side into token-level FSAs
    # resident in a fixed device table pool — the mask rides the slot
    # programs as DATA, zero recompilation under grammar churn
    constrain: bool = False
    constrain_blocks: int = 4  # resident-grammar capacity (one block each)
    constrain_states: int = 64  # automaton state cap per compiled grammar
    # engine-wide top-n logprobs width per emitted token (0 = off); a
    # request asks any ``submit(logprobs=n)`` with n <= this — the
    # width is a compile-time constant, per-request asks are slices
    logprobs: int = 0
    # -- speculative decoding (draft-propose / batched target-verify) ------
    spec: bool = False  # draft proposes K, target verifies in one pass
    spec_k: int = 4  # drafted tokens per speculative block
    # tied-draft depth (target's first N layers; 0 = half the target
    # depth).  A separately-built draft (e.g. distilled) is passed
    # programmatically via ``spec_draft`` and wins over the layer tie.
    spec_draft_layers: int = 0
    spec_draft: Optional[object] = None  # (module, params); not env-loadable

    def resolve_spec_draft(self, module):
        """The engine-facing ``spec_draft`` argument (None = spec off):
        a programmatic ``(module, params)`` pair if one was injected,
        else the tied-layer count."""
        if not self.spec:
            return None
        if self.spec_draft is not None:
            return self.spec_draft
        layers = self.spec_draft_layers or max(1, int(module.n_layers) // 2)
        return int(layers)

    def mesh_config(self):
        """The engine-facing mesh spec (None when unset/1-device)."""
        if not self.mesh or self.mesh.strip() in ("", "1", "1x1"):
            return None
        from tpudist.serve.spmd import ServeMeshConfig

        return ServeMeshConfig(shape=self.mesh, tp_overlap=self.tp_overlap)

    @classmethod
    def from_env(cls) -> "ServeConfig":
        import os

        from tpudist.utils.envutil import (env_flag, env_int,
                                           env_positive_float)

        return cls(
            auto=env_flag("TPUDIST_SERVE_AUTO", False),
            num_slots=env_int("TPUDIST_SERVE_SLOTS", 4) or 4,
            queue_limit=env_int("TPUDIST_SERVE_QUEUE", 64) or 64,
            max_new=env_int("TPUDIST_SERVE_MAX_NEW", 64) or 64,
            prefill_pad=env_int("TPUDIST_SERVE_PREFILL_PAD", None),
            deadline_s=env_positive_float("TPUDIST_SERVE_DEADLINE_S", None),
            decode_block=env_int("TPUDIST_SERVE_DECODE_BLOCK", 8) or 8,
            paged=env_flag("TPUDIST_SERVE_PAGED", False),
            kv_block=env_int("TPUDIST_SERVE_KV_BLOCK", 16) or 16,
            kv_blocks=env_int("TPUDIST_SERVE_KV_BLOCKS", None),
            kv_int8=env_flag("TPUDIST_SERVE_KV_INT8", False),
            prefix_cache_blocks=env_int(
                "TPUDIST_SERVE_PREFIX_CACHE", 0) or 0,
            attn_kernel=os.environ.get(
                "TPUDIST_SERVE_ATTN_KERNEL", "").strip() or "gather",
            prefill_kernel=env_flag("TPUDIST_SERVE_PREFILL_KERNEL", False),
            sample_kernel=env_flag("TPUDIST_SERVE_SAMPLE_KERNEL", False),
            fused_rope=env_flag("TPUDIST_SERVE_FUSED_ROPE", False),
            lora_kernel=env_flag("TPUDIST_SERVE_LORA_KERNEL", False),
            mesh=os.environ.get("TPUDIST_SERVE_MESH", "").strip() or None,
            tp_overlap=os.environ.get(
                "TPUDIST_SERVE_TP_OVERLAP", "").strip() or None,
            disagg=env_flag("TPUDIST_SERVE_DISAGG", False),
            prefill_workers=env_int("TPUDIST_SERVE_PREFILL_WORKERS", 1) or 1,
            decode_workers=env_int("TPUDIST_SERVE_DECODE_WORKERS", 1) or 1,
            prefill_slots=env_int("TPUDIST_SERVE_PREFILL_SLOTS", None),
            handoff=os.environ.get(
                "TPUDIST_SERVE_HANDOFF", "").strip() or "device",
            handoff_queue=env_int("TPUDIST_SERVE_HANDOFF_QUEUE", 8) or 8,
            recover=env_flag("TPUDIST_SERVE_RECOVER", True),
            pool_resize=env_int("TPUDIST_SERVE_POOL_RESIZE", 0) or 0,
            host_tier=env_flag("TPUDIST_SERVE_HOST_TIER", False),
            host_tier_bytes=env_int("TPUDIST_HOST_TIER_BYTES",
                                    1 << 30) or (1 << 30),
            host_tier_ttl_s=env_positive_float(
                "TPUDIST_HOST_TIER_TTL_S", None),
            preempt=env_flag("TPUDIST_SERVE_PREEMPT", True),
            shed=env_flag("TPUDIST_SERVE_SHED", False),
            shed_attainment=env_positive_float(
                "TPUDIST_SERVE_SHED_ATTAINMENT", 0.9) or 0.9,
            # plain env_int (no `or`): 0 is a meaningful protected
            # class here ("protect default-priority, shed negatives"),
            # not an unset sentinel like the neighboring knobs
            shed_priority=env_int("TPUDIST_SERVE_SHED_PRIORITY", 1),
            fair_share=env_positive_float(
                "TPUDIST_SERVE_FAIR_SHARE", None) or 0.0,
            adapters=env_flag("TPUDIST_SERVE_ADAPTERS", False),
            adapter_blocks=env_int("TPUDIST_SERVE_ADAPTER_BLOCKS", 8) or 8,
            adapter_rank=env_int("TPUDIST_SERVE_ADAPTER_RANK", 8) or 8,
            constrain=env_flag("TPUDIST_SERVE_CONSTRAIN", False),
            constrain_blocks=env_int("TPUDIST_CONSTRAIN_BLOCKS", 4) or 4,
            constrain_states=env_int("TPUDIST_CONSTRAIN_STATES", 64) or 64,
            logprobs=env_int("TPUDIST_SERVE_LOGPROBS", 0) or 0,
            spec=env_flag("TPUDIST_SERVE_SPEC", False),
            spec_k=env_int("TPUDIST_SERVE_SPEC_K", 4) or 4,
            spec_draft_layers=env_int(
                "TPUDIST_SERVE_SPEC_DRAFT_LAYERS", 0) or 0,
        )


def _compile_grammar_for(ccfg, regex, json_schema, eos_id):
    """The scheduler-injected grammar compiler: closes over the engine's
    constrain geometry so admission can compile (LRU-cached) and reject
    synchronously without importing the engine."""
    from tpudist.constrain import compile_grammar

    return compile_grammar(regex=regex, json_schema=json_schema,
                           vocab=ccfg.vocab, eos_id=eos_id,
                           max_states=ccfg.max_states)


class ReplicaKilled(RuntimeError):
    """The engine loop stopped because :meth:`_Observability.kill` told
    it to — an INTENTIONAL hard stop (operator kill / the
    ``replica_kill`` chaos fault), not an unexpected error.  The loop
    records it exactly like any other death (``loop_error`` set,
    ``/healthz`` 503, outstanding work aborted ``shutdown``) but does
    not re-raise it into the threading excepthook: a deliberate stop is
    not a stack trace."""


class _Observability:
    """Shared live-observability wiring for both server flavors
    (:class:`InferenceServer` here, ``DisaggServer`` in
    :mod:`tpudist.serve.disagg`): the ``/healthz`` health check (engine
    thread ALIVE and loop-error-free and heartbeat FRESH — not merely
    "the HTTP thread answered"), ``/statusz`` registration against the
    process endpoint, and the ``slo_config`` stamp that makes declared
    targets visible to the post-hoc aggregator."""

    _statusz_name = "serve"

    def _init_observability(self) -> None:
        """State both server constructors share — every attribute the
        mixin's health/status methods read lives here, so a field added
        for one flavor cannot be missing on the other."""
        from tpudist.utils.envutil import env_positive_float

        #: the exception that killed the engine loop, if any — /healthz
        #: goes 503 on it (an HTTP thread answering while the loop is
        #: dead is the lie the healthz bugfix exists to kill)
        self.loop_error: Optional[str] = None
        #: engine-loop heartbeat (stamped every iteration, idle included)
        self._beat: Optional[float] = None
        #: /healthz staleness threshold for the heartbeat
        #: (TPUDIST_SERVE_HEALTH_STALE_S; tightened by tests).  The
        #: default must exceed the worst dispatch that legitimately
        #: blocks an iteration — the first request's XLA compile — or
        #: an orchestrator doing liveness restarts would kill a
        #: compiling server in a loop.  The hang WATCHDOG (with its own
        #: first-deadline slack) is the aggressive stall detector.
        self.health_stale_s = env_positive_float(
            "TPUDIST_SERVE_HEALTH_STALE_S", 300.0)
        #: hard-stop poison (:meth:`kill`): the engine loop raises on
        #: its next iteration when set — the crash twin of drain
        self._die: Optional[str] = None
        self._statusz_names: list = []
        #: tenant → in-flight count (submitted minus finished) for
        #: /statusz; mutated under _tenant_lock (ingestion + engine
        #: threads both write)
        self._tenant_inflight: Dict[str, int] = {}
        self._tenant_lock = threading.Lock()
        #: live-traffic capture ring (tpudist.distill) — both flavors'
        #: ``_note_finished`` offer through it when attached; None =
        #: disarmed (one attribute load + None check on the finish seam)
        self._capture = None
        #: pending draft hot-swap (tpudist.distill): a cross-thread
        #: ``swap_draft`` posts here and the ENGINE loop applies it
        #: between decode blocks — the compiled programs only ever see
        #: a consistent dparams tree
        self._swap_req: Optional[dict] = None
        self._swap_lock = threading.Lock()

    def _start_observability(self) -> None:
        from tpudist import telemetry
        from tpudist.telemetry import metrics, statusz

        targets = metrics.slo_targets()
        if targets["ttft_s"] or targets["tpot_s"]:
            telemetry.event(
                "slo_config",
                **({"ttft_ms": round(targets["ttft_s"] * 1e3, 3)}
                   if targets["ttft_s"] else {}),
                **({"tpot_ms": round(targets["tpot_s"] * 1e3, 3)}
                   if targets["tpot_s"] else {}))
        # static-geometry gauges: a scrape between server start and the
        # first request already answers "what is this process serving"
        if metrics.enabled_from_env():
            reg = metrics.registry()
            for name, value in self._observability_gauges().items():
                reg.gauge(name).set(value)
        srv = statusz.ensure_started()
        if srv is not None:
            self._statusz_names = [
                srv.register_health(self._statusz_name, self._health_check),
                srv.register_status(self._statusz_name, self._statusz_doc),
            ]

    def _stop_observability(self) -> None:
        from tpudist.telemetry import statusz

        srv = statusz.active()
        if srv is not None:
            for name in self._statusz_names:
                srv.unregister(name)
        self._statusz_names = []

    def _health_check(self):
        """(ok, detail) for ``/healthz``.  Unhealthy when the engine
        loop has aborted (``serve_loop_error``), its thread is gone, or
        its heartbeat is stale — the regression the hygiene pass pinned:
        liveness of the HTTP thread alone must never read as healthy."""
        t = self._thread
        alive = t is not None and t.is_alive()
        beat_age = (None if self._beat is None
                    else time.monotonic() - self._beat)
        stale = beat_age is not None and beat_age > self.health_stale_s
        ok = alive and self.loop_error is None and not stale
        return ok, {
            "engine_thread_alive": alive,
            "loop_error": self.loop_error,
            "beat_age_s": None if beat_age is None else round(beat_age, 3),
            "heartbeat_stale": stale,
            "draining": self._draining,
        }

    def kill(self, reason: str = "killed") -> None:
        """Hard-stop the engine loop NOW — the crash twin of
        :meth:`drain` (an operator's kill-9 equivalent, and what the
        ``replica_kill`` chaos fault drives at fleet scope).  The loop
        raises on its next iteration: in-flight and queued work aborts
        with reason ``"shutdown"``, ``loop_error`` is set, ``/healthz``
        goes 503.  Nothing is parked, nothing drains — recovery is the
        CALLER's job (the fleet router re-homes onto survivors)."""
        self._die = reason

    def _check_die(self) -> None:
        """Engine-loop poison check (one attribute load when alive) —
        both flavors call this at the top of every iteration."""
        if self._die:
            raise ReplicaKilled(f"replica killed: {self._die}")

    # -- fleet session migration (tpudist.serve.router) ----------------------
    # Drain-handoff hooks shared by both server flavors: a parked
    # session is also the unit of migration between replicas.  All
    # three are GIL-atomic tier reads/inserts (HostKVTier's cross-
    # thread contract), so a router thread may call them while the
    # engine loop runs.

    def parked_sessions(self) -> list:
        """``(tenant, session)`` pairs of every idle session currently
        parked in this replica's host tier (empty without a tier)."""
        if self._tier is None:
            return []
        return [(k[1], k[2]) for k in self._tier.session_keys()]

    def export_session(self, tenant, session) -> Optional[dict]:
        """A stashable copy of the parked package under ``(tenant,
        session)`` — the serialized wire-format blob plus its covered
        context — or ``None`` when nothing is parked there.  The copy
        is what a router re-homes onto a sibling replica when this one
        drains or dies."""
        if self._tier is None or session is None:
            return None
        key = ("sess", tenant if tenant else "default", str(session))
        return self._tier.export_entry(key)

    def adopt_session(self, tenant, session, stash: Optional[dict]) -> bool:
        """Install a session package exported from ANOTHER replica into
        this tier, so the session's next turn resumes here instead of
        re-prefilling.  Digest verification stays where it always was —
        the resume path's deserialize — so adopting a corrupt stash
        degrades to a full re-prefill, never imports wrong bytes.
        False when this replica has no tier, the stash is empty, or the
        package alone exceeds the tier budget (the turn re-prefills)."""
        if self._tier is None or session is None or not stash \
                or not isinstance(stash.get("ser"), dict):
            return False
        key = ("sess", tenant if tenant else "default", str(session))
        stored = self._tier.adopt(key, stash["ser"],
                                  context=stash.get("context"),
                                  kind=stash.get("kind", "turn"))
        return stored is not None

    def _track_tenant(self, tenant, delta: int) -> None:
        # submit threads race the engine thread here — one tiny lock
        # keeps the read-modify-write atomic (display-only data, but a
        # lost decrement would pin a phantom in-flight forever)
        key = tenant if tenant else "default"
        with self._tenant_lock:
            n = self._tenant_inflight.get(key, 0) + delta
            if n <= 0:
                self._tenant_inflight.pop(key, None)
            else:
                self._tenant_inflight[key] = n

    def _statusz_doc(self) -> dict:  # per-flavor
        raise NotImplementedError

    def _observability_gauges(self) -> Dict[str, float]:  # per-flavor
        return {}

    # -- online draft distillation (tpudist.distill) -------------------------
    # Shared by both server flavors: one capture tap, one hot-swap
    # surface.  The swap itself is per-flavor (_swap_now): one engine
    # here, a decode-pool broadcast on the disagg coordinator.

    def attach_capture(self, capture) -> None:
        """Attach a :class:`tpudist.distill.CaptureBuffer`: every
        finished request's (prompt, emitted) stream is offered to it
        from ``_note_finished`` (greedy and sampled lanes, tenant/
        adapter tags riding along).  ``start()`` attaches one
        automatically when ``TPUDIST_DISTILL_CAPTURE`` is on."""
        self._capture = capture

    @property
    def capture(self):
        return self._capture

    def draft_ref(self) -> Optional[tuple]:
        """``(draft_module, current_draft_params)`` of the serving
        draft, or ``None`` on a non-spec server — what the
        distillation lane warm-starts from and scores against."""
        raise NotImplementedError

    def _swap_now(self, new_params) -> dict:  # per-flavor
        raise NotImplementedError

    def swap_draft(self, new_params,
                   timeout: Optional[float] = 60.0) -> dict:
        """Hot-swap the speculative draft's params — the gated landing.

        Same geometry required (the engine raises on any tree/shape/
        dtype mismatch — every compile pin survives a legal swap).
        Thread-safe: with the engine loop running, the request parks in
        ``_swap_req`` and the LOOP applies it at its next iteration top
        — between decode blocks by construction, so no compiled
        program ever runs half-swapped — and this caller blocks until
        it lands (``TimeoutError`` past ``timeout``).  Without a live
        loop (engine idle before ``start()``, or tests driving
        ``step()`` by hand) the swap applies directly."""
        t = self._thread
        if t is None or not t.is_alive():
            return self._swap_now(new_params)
        req = {"params": new_params, "done": threading.Event(),
               "result": None, "error": None}
        with self._swap_lock:
            if self._swap_req is not None:
                raise RuntimeError("a draft swap is already pending")
            self._swap_req = req
        if not req["done"].wait(timeout):
            with self._swap_lock:
                if self._swap_req is req:
                    self._swap_req = None
            raise TimeoutError(
                f"draft swap not applied within {timeout}s (engine loop "
                "stalled?)")
        if req["error"] is not None:
            raise req["error"]
        return req["result"]

    def _apply_pending_swap(self) -> None:
        """Engine-loop seam (iteration top — between decode blocks):
        apply a parked swap and wake its poster.  One attribute load +
        None check when idle, like every other loop tax."""
        req = self._swap_req
        if req is None:
            return
        try:
            req["result"] = self._swap_now(req["params"])
        except BaseException as e:  # the poster gets the error, the
            req["error"] = e        # serving loop survives it
        finally:
            with self._swap_lock:
                self._swap_req = None
            req["done"].set()

    def _note_swap(self, info: dict) -> None:
        """The ``draft_swap`` event + counter feed, emitted by the
        flavor ``_swap_now`` implementations on an APPLIED swap."""
        from tpudist import telemetry

        telemetry.event("draft_swap",
                        lanes_rearmed=info.get("lanes_rearmed"),
                        swap_s=info.get("swap_s"),
                        draft_swaps=info.get("draft_swaps"),
                        **({"engines": info["engines"]}
                           if "engines" in info else {}))

    def _distill_status(self) -> dict:
        """The ``/statusz`` ``distill`` block (capture attached only):
        the capture ledger — drops counted, never silent."""
        return {"capture": self._capture.stats()}

    @staticmethod
    def _spec_status(st: dict) -> dict:
        """The ``/statusz`` ``spec`` block from ``spec_stats()`` — the
        same numbers the swap gate reads (acceptance, per-pass, swap
        count, per-adapter labels where bound)."""
        return {
            "spec_k": st.get("spec_k"),
            "blocks": st.get("blocks"),
            "acceptance_rate": st.get("acceptance_rate"),
            "accepted_per_pass": st.get("accepted_per_pass"),
            "rollbacks": st.get("rollbacks"),
            "draft_swaps": st.get("draft_swaps"),
            **({"by_adapter": st["by_adapter"]}
               if st.get("by_adapter") else {}),
        }

    # -- graceful degradation under overload (host tier + shedding) ---------
    # Shared by both server flavors, like the observability fields above:
    # a helper added for one flavor cannot be missing on the other.

    def _init_degradation(self, scheduler) -> None:
        """Host-RAM KV tier (``ServeConfig.host_tier``) + SLO-aware
        overload controller (``shed``/``fair_share``) — the machinery
        that turns "pool full" from a hard reject into a degraded-but-
        alive mode.  Installs the controller as the scheduler's
        admission gate."""
        cfg = self.config
        self._tier = None
        if getattr(cfg, "host_tier", False):
            from tpudist.serve.host_tier import HostKVTier

            self._tier = HostKVTier(cfg.host_tier_bytes,
                                    ttl_s=cfg.host_tier_ttl_s)
        self._ctrl = None
        if getattr(cfg, "shed", False) or getattr(cfg, "fair_share", 0) > 0:
            from tpudist.serve.overload import OverloadController

            self._ctrl = OverloadController(
                shed=cfg.shed, shed_attainment=cfg.shed_attainment,
                shed_priority=cfg.shed_priority, fair_share=cfg.fair_share,
                queue_limit=cfg.queue_limit)
            scheduler.admission_gate = self._ctrl.gate
        #: preempted handles parked in the host tier, insertion-ordered
        #: (resume order); their packages live in the tier under
        #: ``("preempt", handle.id)``
        self._parked: "collections.OrderedDict[int, RequestHandle]" = \
            collections.OrderedDict()
        #: handle.id -> tokens to DROP on re-emission after a re-prefill
        #: fallback (a lane whose parked package was spilled or corrupt
        #: re-decodes from scratch; the duplicate-drop keeps the stream
        #: byte-identical)
        self._skip: Dict[int, int] = {}
        #: handle ids whose preempt package the tier rejected as
        #: oversize — re-exporting the same lane every loop iteration
        #: (a full KV device-to-host copy + digest per spin) would
        #: collapse decode throughput; a lane's footprint only grows,
        #: so the rejection is permanent for its lifetime
        self._tier_oversize: set = set()
        self.preemptions = 0
        self.tier_resumes = 0
        self.tier_corrupt = 0

    @staticmethod
    def _session_key(req) -> tuple:
        # tenant-scoped on purpose: one tenant can never resume (or
        # collide with) another tenant's parked session context
        return ("sess", req.tenant or "default", req.session)

    def _tier_put(self, key: tuple, pkg: dict, **kw):
        """``HostKVTier.put`` + telemetry: any LRU spills the put forced
        become a ``host_tier_spill`` event (the tier itself has no
        telemetry seam — the scrape counter and the report's spill
        figure both feed off this event)."""
        t = self._tier
        s0 = t.spills
        stored = t.put(key, pkg, **kw)
        if t.spills > s0:
            self._tier_event("host_tier_spill", entries=t.spills - s0)
        return stored

    def _tier_event(self, name: str, **fields) -> None:
        """Emit a host-tier telemetry event with the tier's occupancy
        stamped on it — the metrics feeder turns those fields into the
        live ``tpudist_host_tier_bytes``/``_entries`` gauges, so the
        scrape tracks occupancy with no extra instrumentation seam."""
        from tpudist import telemetry

        if self._tier is not None:
            fields.setdefault("tier_bytes", self._tier.bytes_resident)
            fields.setdefault("tier_entries", self._tier.entries)
        telemetry.event(name, **fields)

    def _shed_tick(self, now: float) -> None:
        """Refresh the overload controller from the LIVE attainment
        gauges and shed queued lower-priority work while active.  Every
        state flip is stamped with the gauge readings that drove it —
        the decision is auditable from the stream alone."""
        ctrl = self._ctrl
        if ctrl is None or self._draining:
            return
        if ctrl.tick(now):
            self._tier_event(
                "shed_state", active=ctrl.shed_active,
                target=ctrl.shed_attainment,
                attainment={k: round(v, 4)
                            for k, v in ctrl.last_attainment.items()})
        if ctrl.shed_active:
            shed = self.scheduler.shed(ctrl.shed_predicate)
            ctrl.sheds += len(shed)
            for h in shed:
                self._note_finished(h)

    def _expire_requeue(self, now: float) -> None:
        """Deadline sweep over the re-prefill fallback line (both
        flavors own a ``_requeue`` deque): expired entries finish
        ``deadline`` in place, order preserved for the rest."""
        if not self._requeue:
            return
        kept: "collections.deque" = collections.deque()
        while self._requeue:
            h = self._requeue.popleft()
            if h._expired(now):
                h._finish("deadline")
                self._note_finished(h)
            else:
                kept.append(h)
        self._requeue = kept

    def _sweep_parked(self, now: float) -> None:
        """The deadline sweep covers PARKED lanes too: a preempted
        request expiring while offloaded releases its host bytes and
        finishes ``deadline`` NOW — it must not leak its tier entry (and
        strand its waiter) until LRU pressure happens to evict it.  Idle
        parked sessions (no live handle) expire by the tier TTL."""
        if self._tier is None:
            return
        expired = self._tier.sweep_expired(now)
        if expired:
            self._tier_event("session_expired", entries=len(expired))
        for hid in [hid for hid, h in self._parked.items()
                    if h._expired(now)]:
            h = self._parked.pop(hid)
            self._tier.discard(("preempt", hid))
            h._finish("deadline")
            self._note_finished(h)

    def _park_session_lane(self, eng, slot: int, h) -> None:
        """Export a finished turn's lane from ``eng`` and park it in the
        host tier under its session key, with the covered context
        (prompt + every delivered token) riding beside it — the next
        turn resumes only if its prompt extends that token-for-token."""
        req = h.request
        pkg = eng.export_slot(slot)
        pkg["trace_id"] = h.trace_id
        ctx = np.concatenate([np.asarray(req.prompt, np.int32),
                              np.asarray(h.tokens, np.int32)])
        stored = self._tier_put(self._session_key(req), pkg, context=ctx,
                                kind="turn")
        if stored is not None:
            self._tier_event("session_parked", park_kind="turn",
                             pos=int(pkg["pos"]), bytes=stored,
                             trace_id=h.trace_id)

    def _abort_parked(self) -> None:
        """Hard-stop path for parked preempted lanes: they can never
        resume — finish ``preempted`` (not ``shutdown``: telemetry must
        distinguish preemption victims from crash victims) and release
        their tier bytes."""
        while self._parked:
            hid, h = self._parked.popitem(last=False)
            if self._tier is not None:
                self._tier.discard(("preempt", hid))
            h._finish("preempted")
            self._note_finished(h)

    def _note_finished(self, h) -> None:  # per-flavor
        raise NotImplementedError

    # -- per-tenant adapters (tpudist.serve.adapters) ------------------------
    # Shared by both server flavors: one load/unload surface, one event
    # vocabulary (adapter_load / adapter_evict feed the live gauges and
    # the serving report's `adapters` section).

    def _adapter_engines(self) -> list:  # per-flavor
        raise NotImplementedError

    def load_adapter(self, name: str, factors) -> dict:
        """Load ``factors`` under ``name`` into EVERY pool engine (a
        disagg server broadcasts — prefill writes the adapted KV the
        decode pool continues from, and a handoff re-bind must find the
        name on the destination).  ALL-OR-NOTHING: a failure on any
        engine (pool full there) unloads the name from the ones already
        loaded — divergent residency would admit requests (the gate
        consults one engine) that then die ``adapter_missing`` at the
        other pool forever.  Emits one ``adapter_load`` (+ one
        ``adapter_evict`` per FIRST-engine LRU victim — the broadcast
        keeps the pools' load/unload sequences in lockstep, so their
        LRU lines match; per-engine events would inflate the counters
        by the engine count)."""
        from tpudist import telemetry

        engines = self._adapter_engines()
        if not engines or engines[0].adapters is None:
            raise RuntimeError(
                "server built without adapters (ServeConfig.adapters / "
                "TPUDIST_SERVE_ADAPTERS)")
        info = {}
        loaded = []
        try:
            for i, eng in enumerate(engines):
                ei = eng.load_adapter(name, factors)
                loaded.append(eng)
                if i == 0:
                    info = ei
        except BaseException:
            for eng in loaded:
                eng.unload_adapter(name)
            raise
        if info.get("evicted"):
            telemetry.event("adapter_evict", adapter=info["evicted"],
                            evict_kind="lru", resident=info["resident"])
        telemetry.event("adapter_load", adapter=name,
                        block=info.get("block"),
                        resident=info.get("resident"))
        return info

    def unload_adapter(self, name: str) -> dict:
        """Unload ``name`` from every pool engine: frees now when no
        lane holds it, else defers (new requests already reject
        ``adapter_missing``).  Emits ``adapter_evict``."""
        from tpudist import telemetry

        engines = self._adapter_engines()
        if not engines or engines[0].adapters is None:
            raise RuntimeError(
                "server built without adapters (ServeConfig.adapters / "
                "TPUDIST_SERVE_ADAPTERS)")
        info = {}
        for eng in engines:
            info = eng.unload_adapter(name)
        if info.get("known"):
            telemetry.event("adapter_evict", adapter=name,
                            evict_kind="unload",
                            freed=bool(info.get("freed")),
                            resident=info.get("resident"))
        return info

    def _stamp_adapter_config(self) -> None:
        """One ``serve_adapters_config`` event at server start (like
        ``serve_kv_config``): the static pool geometry the aggregator
        pairs with the load/evict stream."""
        engines = self._adapter_engines()
        if not engines or engines[0].adapters is None:
            return
        from tpudist import telemetry

        st = engines[0].adapter_stats()
        # "rank" is a RESERVED telemetry key (process rank) — the LoRA
        # rank travels as lora_rank
        telemetry.event("serve_adapters_config",
                        blocks=st["blocks_total"], lora_rank=st["rank"],
                        block_bytes=st["block_bytes"],
                        pool_bytes=st["pool_bytes"])


class InferenceServer(_Observability):
    """Continuous-batching server over a ``TransformerLM`` decode path.

    Usage::

        server = InferenceServer(module, params, ServeConfig(num_slots=8))
        server.start()
        h = server.submit(prompt_ids, max_new=32, on_token=stream_cb)
        h.wait(); print(h.tokens, h.finish_reason)
        server.close()          # graceful drain (same path as SIGTERM)
    """

    def __init__(self, module, params, config: Optional[ServeConfig] = None,
                 *, install_signal_handler: bool = True):
        self.config = config or ServeConfig.from_env()
        # structured output: the token vocabulary the grammar compiler
        # lowers against is an engine-level constant (token id → decoded
        # text); EOS stays per-request — compile_grammar wires its
        # accept-state column at compile time, not here
        ccfg = None
        if self.config.constrain:
            from tpudist.constrain import ConstrainConfig, default_vocab

            ccfg = ConstrainConfig(
                vocab=default_vocab(int(module.vocab)),
                num_blocks=self.config.constrain_blocks,
                max_states=self.config.constrain_states)
        self.constrain_cfg = ccfg
        self.engine = SlotEngine(
            module, params, num_slots=self.config.num_slots,
            prefill_pad=self.config.prefill_pad,
            decode_block=self.config.decode_block,
            paged=self.config.paged, kv_block=self.config.kv_block,
            kv_blocks=self.config.kv_blocks, kv_int8=self.config.kv_int8,
            prefix_cache_blocks=self.config.prefix_cache_blocks,
            attn_kernel=self.config.attn_kernel,
            prefill_kernel=self.config.prefill_kernel,
            sample_kernel=self.config.sample_kernel,
            fused_rope=self.config.fused_rope,
            lora_kernel=self.config.lora_kernel,
            mesh=self.config.mesh_config(),
            spec_draft=self.config.resolve_spec_draft(module),
            spec_k=self.config.spec_k,
            adapters=self.config.adapters,
            adapter_blocks=self.config.adapter_blocks,
            adapter_rank=self.config.adapter_rank,
            constrain=ccfg, logprobs=self.config.logprobs,
            auto=self.config.auto)
        hasher = None
        if self.config.paged and self.config.prefix_cache_blocks > 0:
            from tpudist.serve.paged_alloc import hash_chain

            bs = self.engine.paged_cfg.block_size
            hasher = lambda prompt: hash_chain(prompt, bs)  # noqa: E731
        self.scheduler = Scheduler(
            queue_limit=self.config.queue_limit,
            check_budget=self.engine.check_budget,
            default_max_new=self.config.max_new,
            default_deadline_s=self.config.deadline_s,
            prefix_hasher=hasher,
            check_adapter=lambda name: (
                None if self.engine.has_adapter(name)
                else "adapter_missing"),
            # grammar compilation runs OUTSIDE the scheduler lock (it is
            # O(states × vocab) host work); GrammarError subclasses
            # ValueError, so an uncompilable ask rejects synchronously
            compile_grammar_fn=(None if ccfg is None else (
                lambda regex, schema, eos: _compile_grammar_for(
                    ccfg, regex, schema, eos))),
            max_logprobs=self.engine.n_lp)
        self._install_signal = install_signal_handler
        self._installed_preemption = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._draining = False
        self._slot_handles: Dict[int, RequestHandle] = {}
        # counters (engine thread writes, stats() reads — GIL-atomic)
        self.completed = 0
        self.tokens_out = 0
        self._occupancy_sum = 0.0
        self._steps = 0
        # -- live observability plane (telemetry.statusz) ------------------
        self._init_observability()
        # -- graceful degradation (host tier / preemption / shedding) ------
        self._init_degradation(self.scheduler)
        #: re-prefill fallback line: lanes whose parked package was
        #: spilled or corrupt restart from the prompt ahead of fresh
        #: admissions (their requests were admitted long ago); the
        #: duplicate-drop counter in ``_skip`` keeps their streams
        #: byte-identical
        self._requeue: "collections.deque[RequestHandle]" = \
            collections.deque()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "InferenceServer":
        if self._thread is not None:
            raise RuntimeError("server already started")
        from tpudist import telemetry
        from tpudist.runtime import faults, preemption

        # chaos harness: arm TPUDIST_FAULT at the serving entry like the
        # training loops do at theirs (the serve-side kinds inject in
        # the disagg loop; arming here keeps the grammar's no-code-
        # changes contract uniform across servers)
        faults.arm_from_env()
        telemetry.ensure_started()
        # one config-stamp event: the static KV geometry the aggregator
        # pairs with the per-block occupancy gauges (block size, pool
        # bytes, bytes/pos — the denominator side of the capacity story)
        kv = self.engine.kv_stats()
        telemetry.event(
            "serve_kv_config", paged=kv["paged"], quantized=kv["quantized"],
            attn_kernel=kv["attn_kernel"],
            prefill_kernel=kv["prefill_kernel"],
            sample_kernel=kv["sample_kernel"],
            fused_rope=kv["fused_rope"], lora_kernel=kv["lora_kernel"],
            block_size=kv["block_size"], blocks_total=kv["blocks_total"],
            pool_bytes=kv["pool_bytes"], bytes_per_pos=kv["bytes_per_pos"],
            num_slots=self.engine.num_slots, max_len=self.engine.max_len)
        if getattr(self.engine, "plan", None) is not None:
            # auto-mode audit trail: the chosen plan + its predicted
            # TPOT/TTFT in the same stream as the measured spans
            telemetry.event("plan_selected", **self.engine.plan.stamp())
        self._stamp_adapter_config()
        if self.engine.has_constrain() or self.engine.n_lp:
            # the structured-output config stamp the aggregator pairs
            # with the per-request constrained tags
            cs = self.engine.constrain_stats()
            telemetry.event(
                "serve_constrain_config", enabled=cs["enabled"],
                blocks=cs.get("blocks"), max_states=cs.get("max_states"),
                pool_bytes=cs.get("pool_bytes"),
                logprobs=self.engine.n_lp)
        if self._capture is None:
            # TPUDIST_DISTILL_CAPTURE arms the live-traffic tap at the
            # same entry the faults grammar arms at — no code changes
            from tpudist.distill.capture import CaptureBuffer

            self._capture = CaptureBuffer.from_env()
        self._start_observability()
        if self._install_signal:
            # SIGTERM → drain: the same preemption flag the training loop
            # checkpoints on.  Off the main thread install degrades to a
            # warned no-op (preemption.py's contract) — close() still
            # drains explicitly.
            self._installed_preemption = preemption.install()
        self._thread = threading.Thread(
            target=self._loop, name="tpudist-serve", daemon=True)
        self._thread.start()
        return self

    def submit(self, prompt, *, max_new: Optional[int] = None,
               temperature: float = 0.0, deadline_s: Optional[float] = None,
               seed: Optional[int] = None, eos_id: Optional[int] = None,
               on_token: Optional[Callable[[int, int], None]] = None,
               spec: Optional[bool] = None, tenant: Optional[str] = None,
               priority: int = 0, session: Optional[str] = None,
               adapter: Optional[str] = None,
               grammar: Optional[str] = None, json_schema=None,
               stop=None, logprobs: int = 0,
               ) -> RequestHandle:
        """Thread-safe ingestion; raises :class:`AdmissionError` on
        backpressure/budget rejection (reason stamped into telemetry).
        ``spec=False`` opts this request out of speculative decoding on
        a spec-enabled server (mixed spec/non-spec traffic); ``tenant``
        labels the request in telemetry, per-tenant metrics/SLO
        attainment, and ``/statusz`` in-flight counts.  ``priority``
        orders the queue and (host tier on) can preempt a lower class's
        decode lane; ``session`` keys the host-tier multi-turn resume —
        a prompt extending a parked session's context token-for-token
        re-imports its KV instead of re-prefilling it.  ``adapter``
        names the per-tenant LoRA the lane decodes through (must be
        loaded via :meth:`load_adapter`; else ``adapter_missing``).

        Structured output: ``grammar`` (a regex over the decoded text)
        or ``json_schema`` constrains the emitted stream to the
        grammar's language — uncompilable asks reject synchronously
        (``invalid_grammar``), and a grammar requires ``eos_id``.
        ``stop`` is a list of token-id sequences (a bare int is a
        1-sequence) matched host-side on the delivered stream; a match
        finishes ``stop_sequence`` with the stop tokens kept in the
        output.  ``logprobs=n`` attaches the top-n ``(token_id,
        logprob)`` pairs per emitted token to ``handle.logprobs``
        (post-mask values on constrained lanes; ``n`` must not exceed
        the engine's compiled TPUDIST_SERVE_LOGPROBS width)."""
        from tpudist import telemetry

        # count the in-flight BEFORE the handle becomes visible to the
        # engine thread — scheduler.submit enqueues and notifies, so a
        # fast finish could otherwise decrement first (losing the -1)
        # and pin a phantom in-flight forever
        tkey = None if tenant is None else str(tenant)
        self._track_tenant(tkey, +1)
        try:
            return self.scheduler.submit(
                prompt, max_new=max_new, temperature=temperature,
                deadline_s=deadline_s, seed=seed, eos_id=eos_id,
                on_token=on_token, spec=spec, tenant=tenant,
                priority=priority, session=session, adapter=adapter,
                grammar=grammar, json_schema=json_schema, stop=stop,
                logprobs=logprobs)
        except BaseException as e:
            # never admitted — ANY failure (bad prompt included, not
            # just AdmissionError) must give the +1 back or the tenant
            # pins a phantom in-flight forever
            self._track_tenant(tkey, -1)
            if isinstance(e, AdmissionError):
                telemetry.event("serve_rejected", reason=e.reason)
            raise

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, finish everything admitted, stop the loop.
        Returns True once the engine thread exited (or never ran).

        With no live engine thread — server never started, or its loop
        already died — queued requests can never produce tokens: they
        finish with reason ``"shutdown"`` instead of hanging their
        waiters forever."""
        self._stop.set()
        t = self._thread
        ok = True
        if t is not None:
            t.join(timeout)
            ok = not t.is_alive()
        if ok:
            # After a graceful drain both are empty — this only bites on
            # the never-started / dead-loop paths.
            self.scheduler.refuse_new("draining")
            self._abort_outstanding()
        return ok

    def close(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown (drain) + handler restore."""
        ok = self.drain(timeout)
        self._stop_observability()
        if self._installed_preemption:
            from tpudist.runtime import preemption

            preemption.reset()
            self._installed_preemption = False
        return ok

    def _adapter_engines(self) -> list:
        return [self.engine]

    def draft_ref(self) -> Optional[tuple]:
        if self.engine.draft_module is None:
            return None
        return (self.engine.draft_module, self.engine.draft_params)

    def _swap_now(self, new_params) -> dict:
        info = self.engine.swap_draft(new_params)
        self._note_swap(info)
        return info

    def _observability_gauges(self) -> Dict[str, float]:
        kv = self.engine.kv_stats()
        return {
            "tpudist_serve_slots": self.engine.num_slots,
            "tpudist_serve_queue_limit": self.config.queue_limit,
            "tpudist_serve_kv_pool_bytes": kv["pool_bytes"],
        }

    def _statusz_doc(self) -> dict:
        """The ``/statusz`` section: current occupancy, KV residency,
        queue depth, world/generation identity, per-tenant in-flight."""
        from tpudist.utils.envutil import env_int

        eng = self.engine
        kv_occ, kv_resident = eng.kv_gauges()
        kv = eng.kv_stats()
        return {
            "slots": {
                "total": int(eng.num_slots),
                "active": int(eng.num_active),
                "prefilling": len(eng.prefilling_slots()),
                "occupancy": round(float(eng.occupancy), 4),
            },
            "queue": {
                "pending": self.scheduler.pending(),
                "limit": self.config.queue_limit,
                "rejected": self.scheduler.rejected,
            },
            "kv": {
                "paged": bool(kv["paged"]),
                "pool_bytes": kv["pool_bytes"],
                "bytes_resident": int(kv_resident),
                "block_occupancy": (None if kv_occ is None
                                    else round(float(kv_occ), 4)),
            },
            "completed": self.completed,
            "tokens_out": self.tokens_out,
            "tenants_in_flight": dict(self._tenant_inflight),
            # per-tenant adapter pool (absent when off)
            **({"adapters": self.engine.adapter_stats()}
               if self.engine.adapters is not None else {}),
            # structured-output grammar pool + logprobs width (absent
            # when both are off)
            **({"constrained": {**self.engine.constrain_stats(),
                                "logprobs": self.engine.n_lp}}
               if self.engine.has_constrain() or self.engine.n_lp
               else {}),
            # speculative decode + distillation flywheel (absent when
            # off) — the swap gate reads the SAME numbers shown here
            **({"spec": self._spec_status(self.engine.spec_stats())}
               if self.engine.spec else {}),
            **({"distill": self._distill_status()}
               if self._capture is not None else {}),
            # host-tier occupancy + overload state (None-free when off)
            **({"host_tier": {**self._tier.stats(),
                              "parked_requests": len(self._parked),
                              "preemptions": self.preemptions,
                              "resumes_served": self.tier_resumes,
                              "corrupt": self.tier_corrupt}}
               if self._tier is not None else {}),
            **({"overload": self._ctrl.stats()}
               if self._ctrl is not None else {}),
            "world": env_int("TPUDIST_NUM_PROCESSES", None),
            "generation": env_int("TPUDIST_RESTART_COUNT", 0),
            "draining": self._draining,
            "loop_error": self.loop_error,
        }

    def stats(self) -> dict:
        return {
            "completed": self.completed,
            "rejected": self.scheduler.rejected,
            "tokens_out": self.tokens_out,
            "pending": self.scheduler.pending(),
            "active": self.engine.num_active,
            "prefilling": len(self.engine.prefilling_slots()),
            "occupancy_mean": (self._occupancy_sum / self._steps
                               if self._steps else 0.0),
            "compile_counts": self.engine.compile_counts(),
            "decode": self.engine.decode_stats(),
            "spec": self.engine.spec_stats(),
            "kv": self.engine.kv_stats(),
            "constrain": self.engine.constrain_stats(),
            "spmd": self.engine.spmd_stats(),
            "adapters": self.engine.adapter_stats(),
            "preemptions": self.preemptions,
            "parked": len(self._parked),
            "host_tier": (None if self._tier is None
                          else self._tier.stats()),
            "overload": (None if self._ctrl is None
                         else self._ctrl.stats()),
        }

    # -- the engine loop ----------------------------------------------------

    def _should_drain(self) -> bool:
        if self._stop.is_set():
            return True
        from tpudist.runtime import preemption

        return preemption.requested()

    def _abort_outstanding(self) -> None:
        """Finish every request that can no longer be served (reason
        ``"shutdown"``; parked preempted lanes ``"preempted"``) — the
        hard-stop twin of the graceful drain."""
        for slot in list(self._slot_handles):
            h = self._slot_handles.pop(slot)
            h._finish("shutdown")
            self._note_finished(h)
        self._abort_parked()
        while self._requeue:
            h = self._requeue.popleft()
            h._finish("shutdown")
            self._note_finished(h)
        for h in self.scheduler.take(1 << 30):
            if not h.done:
                h._finish("shutdown")
            self._note_finished(h)

    def _loop(self) -> None:
        from tpudist import telemetry

        try:
            self._run_loop()
        except BaseException as e:
            # The loop must not die silently: a device error (OOM, a
            # budget-guard RuntimeError) would otherwise strand every
            # in-flight and queued handle in wait() forever while
            # submit() keeps admitting doomed work.
            self.loop_error = repr(e)  # /healthz goes 503 on this
            telemetry.event("serve_loop_error", error=repr(e))
            if not isinstance(e, ReplicaKilled):
                raise  # threading excepthook still reports the traceback
        finally:
            self.scheduler.refuse_new("draining")
            self._abort_outstanding()

    def _run_loop(self) -> None:
        from tpudist import telemetry

        eng, sched = self.engine, self.scheduler
        while True:
            self._beat = time.monotonic()  # /healthz heartbeat
            self._check_die()  # hard-stop poison (kill / replica_kill)
            # gated draft hot-swap lands HERE — between decode blocks
            # by construction (the loop is the only decode dispatcher)
            self._apply_pending_swap()
            if not self._draining and self._should_drain():
                self._draining = True
                sched.refuse_new("draining")
                telemetry.event("serve_drain", pending=sched.pending(),
                                active=eng.num_active)
            now = time.monotonic()
            # deadline enforcement: in-slot AND queued (the queue check
            # must not wait for a slot to free — all lanes can be busy
            # for far longer than a queued request's deadline).  A block
            # is atomic, so mid-decode expiry lands between blocks.
            for slot, h in list(self._slot_handles.items()):
                if h._expired(now):
                    self._finish_slot(slot, "deadline")
            # a decoding slot whose cache filled with budget unspent can
            # only mean the admission budget rule was bypassed — finish
            # it LOUDLY (reason "cache_full") instead of letting the next
            # decode block clamp writes onto max_len-1 and attend over
            # garbage, or crash the loop for every other tenant
            for slot in eng.cache_full_slots():
                if slot in self._slot_handles:
                    self._finish_slot(slot, "cache_full")
            for h in sched.expire_queued(now):
                self._note_finished(h)
            # deadline sweep over the re-prefill fallback line AND the
            # parked (host-tier) lanes — a request offloaded to host RAM
            # still owns its deadline (satellite: it releases its tier
            # bytes and finishes "deadline", never leaks until LRU)
            self._expire_requeue(now)
            self._sweep_parked(now)
            # SLO-aware load shedding off the live attainment gauges,
            # then priority preemption / parked-lane resume — all host
            # decisions, all BEFORE admission so a freed slot is usable
            # in this same iteration
            self._shed_tick(now)
            self._maybe_preempt()
            self._resume_preempted()
            # priority-ordered admission into free lanes: ONE fused
            # prefill+scatter dispatch for the whole admission batch.
            # The paged engine adds a second gate: the queue head is
            # taken only while its whole block footprint fits the pool
            # (reused prefix blocks discounted).
            free = eng.free_slots()
            if free:
                # the gate runs once per queued candidate within ONE
                # take; `reserved` carries the fresh blocks already
                # promised to earlier candidates of this same batch and
                # `pinned` the cached blocks they will reuse (counted
                # evictable by a naive peek, pinned the moment they
                # land) — the free list only learns about either at
                # start_batch
                reserved, pinned = [0], []
                resume_pos: Dict[int, int] = {}

                def _gate(h):
                    req = h.request
                    if (self._tier is not None and req.session is not None
                            and h.id not in self._skip):
                        pos = self._tier.match(
                            self._session_key(req), req.prompt)
                        if pos is not None:
                            # host-tier session hit: the resume reserves
                            # its FULL footprint (a resumed lane's
                            # context is private — no prefix sharing)
                            got = eng.kv_admission_probe(
                                len(req.prompt), req.max_new, (),
                                reserve=reserved[0], protect=pinned)
                            if got is None:
                                return False
                            reserved[0] += got[0]
                            resume_pos[h.id] = pos
                            return True
                    got = eng.kv_admission_probe(
                        len(req.prompt), req.max_new, req.prefix_hashes,
                        reserve=reserved[0], protect=pinned)
                    if got is None:
                        return False
                    reserved[0] += got[0]
                    pinned.extend(got[1])
                    return True

                # re-prefill fallbacks first (admitted long ago — the
                # disagg requeue discipline), head-of-line on a blocked
                # gate so steady fresh traffic can't starve them
                batch: List[RequestHandle] = []
                blocked = False
                while self._requeue and len(batch) < len(free):
                    if not _gate(self._requeue[0]):
                        blocked = True
                        break
                    batch.append(self._requeue.popleft())
                if not blocked and len(batch) < len(free):
                    batch += sched.take(len(free) - len(batch), now,
                                        admit=_gate)
                alive = []
                for h in batch:
                    if h.done:  # finished in-queue (deadline expired)
                        self._note_finished(h)
                    elif not eng.has_adapter(h.request.adapter):
                        # admitted, but the named adapter was unloaded
                        # while it queued — finish loudly, never serve
                        # base-model output for an adapter request
                        h._finish("adapter_missing")
                        self._note_finished(h)
                    else:
                        alive.append(h)
                if alive:
                    items, t0 = [], time.monotonic()
                    fresh: List[Tuple[RequestHandle, int]] = []
                    for h, slot in zip(alive, free):
                        h.slot = slot
                        if h.t_admitted is None:
                            h.t_admitted = t0
                        # a session hit resumes its parked lane instead
                        # of prefilling (falls back to fresh on a
                        # spilled/corrupt package — degraded, not wrong)
                        if h.id in resume_pos \
                                and self._resume_session(slot, h):
                            continue
                        fresh.append((h, slot))
                    if fresh:
                        from tpudist.serve.adapters import \
                            AdapterMissingError

                        from tpudist.constrain.registry import \
                            GrammarPoolFull

                        for h, slot in fresh:
                            items.append((slot, h.request.prompt,
                                          h.request.temperature,
                                          h.request.seed,
                                          h.request.max_new,
                                          h.request.prefix_hashes,
                                          h.request.spec,
                                          h.request.adapter,
                                          h.request.grammar))
                            self._slot_handles[slot] = h
                        firsts = {}
                        while items:
                            try:
                                with telemetry.span("prefill",
                                                    n=len(items)):
                                    firsts = eng.start_batch(items)
                                break
                            except GrammarPoolFull:
                                # every grammar block is pinned by a
                                # decoding lane (start_batch rolled the
                                # whole dispatch back): defer the
                                # CONSTRAINED items through the requeue
                                # line — they retry head-of-line as
                                # lanes finish — and admit the free ones
                                keep = []
                                for it in items:
                                    if it[8] is not None:
                                        h2 = self._slot_handles.pop(it[0])
                                        h2.slot = None
                                        self._requeue.append(h2)
                                    else:
                                        keep.append(it)
                                telemetry.event(
                                    "constrain_deferred",
                                    n=len(items) - len(keep))
                                items = keep
                            except AdapterMissingError as e:
                                # a user thread unloaded the adapter
                                # between the admission recheck and the
                                # dispatch (whole-batch validation, so
                                # nothing mutated): finish ITS requests
                                # loudly, admit the rest
                                keep = []
                                for it in items:
                                    if it[7] == e.adapter:
                                        h2 = self._slot_handles.pop(it[0])
                                        h2._finish("adapter_missing")
                                        self._note_finished(h2)
                                    else:
                                        keep.append(it)
                                items = keep
                        for slot, tok in firsts.items():
                            if tok is not None:
                                self._deliver_block(slot, [tok])
            # chunked prefill: one prompt chunk per prefilling slot per
            # iteration — long prompts never stall decode for more than
            # one chunk's worth of device time
            if eng.prefilling_slots():
                with telemetry.span("prefill",
                                    chunks=len(eng.prefilling_slots())):
                    done = eng.advance_prefill()
                for slot, tok in done.items():
                    self._deliver_block(slot, [tok])
            # one fused decode block over every decoding lane — the
            # speculative draft-propose/target-verify block when the
            # engine carries a draft (decode_auto falls back to the
            # plain block, draft-tracked, when speculation cannot run)
            if eng.num_active:
                occ = eng.occupancy
                active = eng.num_active
                tele = telemetry.active()
                t0 = time.monotonic()
                info, blocks = eng.decode_auto()
                if tele is not None and info is not None:
                    kv_occ, kv_resident = eng.kv_gauges()
                    tags = {"occupancy": occ, "active": active,
                            "k": info["k"], "tokens": info["tokens"],
                            "dispatch_s": round(info["dispatch_s"], 9),
                            "sync_s": round(info["sync_s"], 9),
                            # the KV capacity/bandwidth gauges: pool block
                            # occupancy (None on dense), resident bytes,
                            # and the bytes this block's attention streamed
                            "kv_block_occupancy": kv_occ,
                            "kv_bytes_resident": kv_resident,
                            "kv_read_bytes": info["kv_read_bytes"]}
                    if info.get("spec"):
                        # the spec_verify span: per-block acceptance +
                        # the draft/verify wall split the serving
                        # report's speculation section aggregates
                        tags.update(
                            accepted=info["accepted"],
                            drafted=info["drafted"],
                            rollbacks=info["rollbacks"],
                            draft_s=round(info["draft_s"], 9),
                            verify_s=round(info["verify_s"], 9))
                        if info.get("accept_by_adapter"):
                            # per-adapter accept labels ride the span —
                            # the metrics feeder turns them into the
                            # labeled acceptance gauges
                            tags["accept_by_adapter"] = \
                                info["accept_by_adapter"]
                        tele.record_span("spec_verify", t0,
                                         time.monotonic() - t0, tags)
                    else:
                        tele.record_span("decode_block", t0,
                                         time.monotonic() - t0, tags)
                self._occupancy_sum += occ
                self._steps += 1
                block_lp = (info or {}).get("logprobs") or {}
                for slot, toks in blocks.items():
                    self._deliver_block(slot, toks, block_lp.get(slot))
            elif eng.prefilling_slots():
                pass  # prefill work continues next iteration
            elif (self._draining and sched.pending() == 0
                    and not self._parked and not self._requeue):
                # drain completes parked/preempted work too: admission
                # is refused, so slots free up and the resume phases
                # above finish every offloaded lane before the loop ends
                break
            else:
                sched.wait_for_work(_IDLE_WAIT_S)

    def _deliver_block(self, slot: int, toks, lp=None) -> None:
        """Stream a token block to the slot's request, truncating
        post-hoc at its stop token or length budget (the device block is
        speculative past either — bounded by the block size).  A lane
        re-decoding after a re-prefill fallback (spilled/corrupt parked
        package) drops exactly its already-delivered duplicates first
        (``_skip``) — the stream stays byte-identical.

        ``lp`` is the block's top-n logprobs rows aligned with ``toks``
        (absent on prefill-sampled first tokens — those surface None).
        A constrained lane walks its grammar's host shadow automaton
        per delivered token; a token the shadow disallows truncates the
        stream BEFORE delivery and finishes ``grammar_violation`` —
        defense in depth, since the device-side mask makes a violating
        sample unreachable unless the pool tables and the shadow
        diverge.  A per-request stop sequence is suffix-matched on the
        delivered stream after each token (block-boundary straddles
        included, since the match runs on ``h.tokens``, not the block)
        and finishes ``stop_sequence`` with the stop tokens kept."""
        h = self._slot_handles[slot]
        eos = h.request.eos_id
        tg = h.request.grammar
        if self._ctrl is not None:
            # the fairness gate's measurement: DELIVERED tokens/s per
            # tenant — duplicates a fallback lane re-decodes are dropped
            # below and must not inflate its measured rate
            delivered = max(0, len(toks) - self._skip.get(h.id, 0))
            if delivered:
                self._ctrl.note_tokens(h.request.tenant, delivered)
        for i, tok in enumerate(toks):
            skip = self._skip.get(h.id, 0)
            if skip > 0:
                # a re-decoded duplicate was shadow-walked when it first
                # delivered — drop it (and its lp row) without advancing
                if skip == 1:
                    del self._skip[h.id]
                else:
                    self._skip[h.id] = skip - 1
                continue
            if tg is not None:
                if not tg.token_allowed(h.gstate, tok):
                    self._finish_slot(slot, "grammar_violation")
                    return
                h.gstate = tg.advance(h.gstate, tok)
            h._deliver(tok)
            if h.request.logprobs > 0:
                n = h.request.logprobs
                row = lp[i] if lp is not None and i < len(lp) else None
                h.logprobs.append(None if row is None
                                  else (row[0][:n], row[1][:n]))
            self.tokens_out += 1
            if eos is not None and tok == eos:
                self._finish_slot(slot, "eos")
                return
            if h.request.stop and any(
                    len(h.tokens) >= len(s)
                    and tuple(h.tokens[-len(s):]) == s
                    for s in h.request.stop):
                self._finish_slot(slot, "stop_sequence")
                return
            if len(h.tokens) >= h.request.max_new:
                # a resumed turn's budget-completion is countable from
                # the finish reasons alone (the bench's resume column)
                self._finish_slot(slot, "session_resumed" if h.resumed
                                  else "length")
                return

    def _finish_slot(self, slot: int, reason: str) -> None:
        h = self._slot_handles.pop(slot)
        if (self._tier is not None and h.request.session is not None
                and reason in ("length", "eos", "session_resumed")
                and self.engine.exportable(slot, len(h.tokens))):
            # park the finished turn's lane BEFORE the evict zeroes it:
            # the session's next turn resumes without recompute.  An
            # eos that fired mid-block leaves speculated tokens in the
            # cache beyond the delivered stream — exportable() refuses
            # those lanes, so a park can never carry diverged context.
            self._park_session_lane(self.engine, slot, h)
        self.engine.evict(slot)
        h._finish(reason)
        self._note_finished(h)

    def _resume_session(self, slot: int, h: RequestHandle) -> bool:
        """Serve this turn from its parked session lane (import + a
        suffix-only prefill).  False on a missing or corrupt package —
        the caller falls back to an ordinary fresh prefill (degraded,
        never wrong bytes)."""
        from tpudist.serve.disagg import HandoffError, deserialize_package
        from tpudist.serve.host_tier import HostTierError

        req = h.request
        try:
            ser = self._tier.get(self._session_key(req))
            raw = deserialize_package(ser)  # digest verified here
        except HostTierError:
            return False  # raced a TTL sweep / LRU spill: fresh prefill
        except HandoffError as e:
            self.tier_corrupt += 1
            self._tier_event("host_tier_corrupt", kind="session",
                             error=str(e)[:120], trace_id=h.trace_id)
            return False
        if raw.get("adapter") != req.adapter:
            # the parked KV was written THROUGH its turn's adapter; a
            # turn binding a different adapter (or none) must re-prefill
            # — resuming would continue from the wrong fine-tune's cache
            return False
        if raw.get("grammar") is not None or req.grammar is not None:
            # a parked lane's automaton state belongs to ITS turn
            # (mid-walk), while a constrained next turn must start at
            # state 0 — and an unconstrained next turn must not inherit
            # the parked mask.  Either way: fresh prefill (degraded,
            # never wrong bytes).
            return False
        t0 = time.monotonic()
        from tpudist.serve.adapters import AdapterMissingError

        try:
            self.engine.resume_slot(
                slot, raw, req.prompt, temperature=req.temperature,
                seed=req.seed, max_new=req.max_new, spec=req.spec)
        except AdapterMissingError:
            return False  # unloaded mid-iteration: the caller's fresh
            # prefill then finishes adapter_missing via the same race
        h.resumed = True
        self._slot_handles[slot] = h
        self.tier_resumes += 1
        self._tier_event("session_resumed", park_kind="turn", slot=slot,
                         covered=int(raw["pos"]), trace_id=h.trace_id,
                         import_s=round(time.monotonic() - t0, 6))
        return True

    def _maybe_preempt(self) -> None:
        """Priority preemption: when the queue head outranks a decoding
        lane and cannot admit (no free slot, or its KV footprint is
        blocked), the lowest-priority decoding lane (ties: least
        progress) exports to the host tier mid-block and requeues —
        byte-identical continuation later, since decode is a pure
        function of the packaged ``(state, cache)`` and the
        ``fold_in(key, count)`` stream."""
        if self._tier is None or not self.config.preempt \
                or self._draining:
            return
        head = self.scheduler.head_info()
        if head is None:
            return
        eng = self.engine
        if eng.free_slots() and eng.can_admit_kv(
                head["prompt_len"], head["max_new"],
                head["prefix_hashes"]):
            return  # the head can already admit — nothing to preempt for
        cands = [(slot, h) for slot, h in self._slot_handles.items()
                 if eng.decoding[slot]
                 and h.request.priority < head["priority"]
                 and h.id not in self._skip
                 and h.id not in self._tier_oversize]
        if not cands:
            return
        slot, victim = min(cands, key=lambda kv: (kv[1].request.priority,
                                                  len(kv[1].tokens)))
        self._preempt_slot(slot, victim, head["priority"])

    def _preempt_slot(self, slot: int, h: RequestHandle, by: int) -> None:
        pkg = self.engine.export_slot(slot)
        pkg["trace_id"] = h.trace_id
        stored = self._tier_put(("preempt", h.id), pkg, pinned=True,
                                kind="preempt")
        if stored is None:
            # tier can't hold the lane: admission just waits — and this
            # lane must not be re-exported every loop spin
            self._tier_oversize.add(h.id)
            return
        self.engine.evict(slot)
        del self._slot_handles[slot]
        self._parked[h.id] = h
        self.preemptions += 1
        self._tier_event("preempted", id=h.id, slot=slot,
                         priority=h.request.priority, by_priority=by,
                         bytes=stored, trace_id=h.trace_id)

    def _resume_preempted(self) -> None:
        """Parked preempted lanes re-import as capacity frees, oldest
        first, unless a strictly-higher-priority request is queued (the
        class that preempted them admits first).  A spilled or corrupt
        parked package degrades to a full re-prefill through the
        ``_requeue`` line — already-delivered tokens drop as duplicates,
        so the stream is still byte-identical."""
        if self._tier is None or not self._parked:
            return
        from tpudist.serve.disagg import HandoffError, deserialize_package

        eng = self.engine
        while self._parked:
            free = eng.free_slots()
            if not free:
                return
            hid, h = next(iter(self._parked.items()))
            head = self.scheduler.head_info()
            if head is not None and head["priority"] > h.request.priority:
                return  # the higher class admits first
            ser = self._tier.peek(("preempt", hid))
            if ser is None:
                # spilled under byte pressure: full re-prefill fallback
                del self._parked[hid]
                self._skip[h.id] = len(h.tokens)
                self._requeue.append(h)
                continue
            if not eng.can_import(ser):
                return  # blocks not free yet — parked head-of-line
            self._tier.get(("preempt", hid))
            del self._parked[hid]
            try:
                raw = deserialize_package(ser)
            except HandoffError as e:
                self.tier_corrupt += 1
                self._tier_event("host_tier_corrupt", kind="preempt",
                                 error=str(e)[:120], trace_id=h.trace_id)
                self._skip[h.id] = len(h.tokens)
                self._requeue.append(h)
                continue
            slot = free[0]
            from tpudist.serve.adapters import AdapterMissingError

            try:
                eng.import_slot(slot, raw, spec=h.request.spec)
            except AdapterMissingError:
                # the adapter was unloaded while the lane sat parked —
                # its KV is the fine-tune's, a base re-prefill would be
                # wrong bytes: finish loudly instead
                h._finish("adapter_missing")
                self._note_finished(h)
                continue
            self._slot_handles[slot] = h
            self.tier_resumes += 1
            self._tier_event("session_resumed", park_kind="preempt",
                             slot=slot, id=h.id, trace_id=h.trace_id)

    def _note_finished(self, h: RequestHandle) -> None:
        from tpudist import telemetry
        from tpudist.telemetry import trace

        # one cleanup point for the re-prefill duplicate-drop counter
        # and the oversize-preempt memo (a lane finishing early must
        # not leak either entry)
        self._skip.pop(h.id, None)
        self._tier_oversize.discard(h.id)
        self.completed += 1
        self._track_tenant(h.request.tenant, -1)
        if self._capture is not None:
            # the distillation flywheel's tap: the finished stream is
            # the training example (bounded ring, drops counted)
            self._capture.offer_handle(h)
        telemetry.event(
            "request_finished", id=h.id, reason=h.finish_reason,
            prompt_len=int(len(h.request.prompt)), tokens_out=len(h.tokens),
            ttft_s=h.ttft_s, tpot_s=h.tpot_s, queue_wait_s=h.queue_wait_s,
            trace_id=h.trace_id,
            **({"tenant": h.request.tenant} if h.request.tenant else {}),
            **({"adapter": h.request.adapter} if h.request.adapter else {}),
            **({"constrained": h.request.grammar.source["kind"]}
               if h.request.grammar is not None else {}),
            **({"stop_seqs": len(h.request.stop)} if h.request.stop
               else {}),
            **({"logprobs": h.request.logprobs} if h.request.logprobs
               else {}))
        # per-request lifeline spans (req_queue/req_prefill/req_decode)
        # for the cross-pool trace join + Chrome export
        trace.emit_request_lifeline(h)


def serve_forever(module, params, config: Optional[ServeConfig] = None):
    """Start a server and return it (the embedding entry — the CLI demo
    in ``__main__`` owns its own loop).  ``config.disagg`` selects the
    prefill/decode-disaggregated coordinator
    (:class:`tpudist.serve.disagg.DisaggServer`) — same submit/close
    surface, two engine pools with KV handoff behind it."""
    cfg = config or ServeConfig.from_env()
    if cfg.disagg:
        from tpudist.serve.disagg import DisaggServer

        return DisaggServer(module, params, cfg).start()
    return InferenceServer(module, params, cfg).start()
