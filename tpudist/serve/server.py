"""Threaded serving front-end: ingestion, the engine loop, graceful drain.

Wiring (one picture)::

    submit() threads ──> Scheduler (bounded FIFO, admission)      host
                              │ take(free_slots)
                              ▼
    engine thread ───> SlotEngine.start_batch / advance_prefill   device
                       / decode_block / evict
                              │ token blocks
                              ▼
                       RequestHandle streaming callbacks, done events

One background thread drives the engine (the device programs are
serialized anyway — a thread per request would only add contention);
any number of caller threads submit.  Each loop iteration admits into
free slots (one fused prefill+scatter dispatch), feeds one prompt chunk
to every still-prefilling slot (chunked prefill — a long prompt stalls
decode by at most one chunk per iteration), then runs ONE fused decode
block (``K`` tokens per slot per dispatch, ``K`` picked from the host
shadow budgets).  Tokens stream per request as each block lands; a
request's ``eos_id`` truncates its block post-hoc (finish reason
``"eos"``).  Deadlines are enforced between blocks, so a request can
overshoot its deadline by at most one block.

SIGTERM reuses the training stack's preemption flag
(:mod:`tpudist.runtime.preemption`): the loop checks it every iteration
and, once set, stops admitting (new submits reject with ``"draining"``),
finishes everything already admitted — queued AND in-slot — then exits.
The same drain runs on :meth:`InferenceServer.close`, so a deploy
rollover never cuts a response mid-stream.

Telemetry (the PR-2 subsystem) brackets the device programs —
``prefill`` spans (admission batches and chunk feeds) and
``decode_block`` spans tagged with the batch occupancy gauge, the block
size ``k``, tokens emitted, and the dispatch-vs-host-sync attribution —
and stamps a ``request_finished`` event per request carrying
TTFT/TPOT/queue-wait, which the aggregator folds into the run report's
serving section (:mod:`tpudist.telemetry.aggregate`).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, Optional

from tpudist.serve.engine import SlotEngine
from tpudist.serve.scheduler import AdmissionError, RequestHandle, Scheduler

#: poll interval of an idle engine loop (also the latency to notice a
#: drain request while idle) — host-side only, no device work while idle.
_IDLE_WAIT_S = 0.01


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs; :meth:`from_env` reads the ``TPUDIST_SERVE_*``
    family (registered in ``tpudist.utils.envutil.ENV_VARS``)."""

    num_slots: int = 4
    queue_limit: int = 64
    max_new: int = 64  # default per-request token budget
    prefill_pad: Optional[int] = None  # chunk size; None: min(max_len, 64)
    deadline_s: Optional[float] = None  # default per-request deadline
    decode_block: int = 8  # max fused decode tokens per dispatch (K)
    # -- paged KV cache (tpudist/models/paged.py) --------------------------
    paged: bool = False  # block pool + block tables instead of dense arenas
    kv_block: int = 16  # tokens per KV block (must divide max_len)
    # pool size in blocks; None = dense-equivalent bytes (num_slots ×
    # max_len / kv_block) — raise num_slots at fixed kv_blocks for the
    # capacity win
    kv_blocks: Optional[int] = None
    kv_int8: bool = False  # int8 KV storage + per-block scales
    prefix_cache_blocks: int = 0  # shared-prefix LRU cache bound (blocks)
    # decode attention path on the paged cache: "gather" (dense view
    # per dispatch) or "paged" (the Pallas paged-attention kernel —
    # block table walked in-kernel, decode bytes/token ∝ live KV)
    attn_kernel: str = "gather"
    # -- SPMD serving mesh (tpudist/serve/spmd.py) -------------------------
    # "DxM" (data × model) or "M"; "1" = single device.  Declarative on
    # purpose (AMP-style): a planner searches this field, not the code.
    mesh: Optional[str] = None
    tp_overlap: Optional[str] = None  # off|ring|bidir; None = knob chain
    # -- prefill/decode disaggregation (tpudist/serve/disagg.py) -----------
    disagg: bool = False  # separate prefill + decode worker pools
    prefill_workers: int = 1
    decode_workers: int = 1
    prefill_slots: Optional[int] = None  # per prefill worker; None: num_slots
    handoff: str = "device"  # "device" (in-mesh) | "serial" (byte transfer)
    handoff_queue: int = 8  # bounded pending-handoff packages
    # self-healing fleet: a dead pool worker's lanes replay onto
    # survivors (stashed handoff packages — costs one extra copy of each
    # in-flight decode lane's KV); off = any worker death aborts all
    # outstanding work as "shutdown" (the pre-recovery behavior)
    recover: bool = True
    # backpressure pool resize: consecutive loop iterations the handoff
    # queue must stay full before the prefill slot budget shrinks by one
    # (and at most half-full before it grows back); 0 = off
    pool_resize: int = 0
    # -- speculative decoding (draft-propose / batched target-verify) ------
    spec: bool = False  # draft proposes K, target verifies in one pass
    spec_k: int = 4  # drafted tokens per speculative block
    # tied-draft depth (target's first N layers; 0 = half the target
    # depth).  A separately-built draft (e.g. distilled) is passed
    # programmatically via ``spec_draft`` and wins over the layer tie.
    spec_draft_layers: int = 0
    spec_draft: Optional[object] = None  # (module, params); not env-loadable

    def resolve_spec_draft(self, module):
        """The engine-facing ``spec_draft`` argument (None = spec off):
        a programmatic ``(module, params)`` pair if one was injected,
        else the tied-layer count."""
        if not self.spec:
            return None
        if self.spec_draft is not None:
            return self.spec_draft
        layers = self.spec_draft_layers or max(1, int(module.n_layers) // 2)
        return int(layers)

    def mesh_config(self):
        """The engine-facing mesh spec (None when unset/1-device)."""
        if not self.mesh or self.mesh.strip() in ("", "1", "1x1"):
            return None
        from tpudist.serve.spmd import ServeMeshConfig

        return ServeMeshConfig(shape=self.mesh, tp_overlap=self.tp_overlap)

    @classmethod
    def from_env(cls) -> "ServeConfig":
        import os

        from tpudist.utils.envutil import (env_flag, env_int,
                                           env_positive_float)

        return cls(
            num_slots=env_int("TPUDIST_SERVE_SLOTS", 4) or 4,
            queue_limit=env_int("TPUDIST_SERVE_QUEUE", 64) or 64,
            max_new=env_int("TPUDIST_SERVE_MAX_NEW", 64) or 64,
            prefill_pad=env_int("TPUDIST_SERVE_PREFILL_PAD", None),
            deadline_s=env_positive_float("TPUDIST_SERVE_DEADLINE_S", None),
            decode_block=env_int("TPUDIST_SERVE_DECODE_BLOCK", 8) or 8,
            paged=env_flag("TPUDIST_SERVE_PAGED", False),
            kv_block=env_int("TPUDIST_SERVE_KV_BLOCK", 16) or 16,
            kv_blocks=env_int("TPUDIST_SERVE_KV_BLOCKS", None),
            kv_int8=env_flag("TPUDIST_SERVE_KV_INT8", False),
            prefix_cache_blocks=env_int(
                "TPUDIST_SERVE_PREFIX_CACHE", 0) or 0,
            attn_kernel=os.environ.get(
                "TPUDIST_SERVE_ATTN_KERNEL", "").strip() or "gather",
            mesh=os.environ.get("TPUDIST_SERVE_MESH", "").strip() or None,
            tp_overlap=os.environ.get(
                "TPUDIST_SERVE_TP_OVERLAP", "").strip() or None,
            disagg=env_flag("TPUDIST_SERVE_DISAGG", False),
            prefill_workers=env_int("TPUDIST_SERVE_PREFILL_WORKERS", 1) or 1,
            decode_workers=env_int("TPUDIST_SERVE_DECODE_WORKERS", 1) or 1,
            prefill_slots=env_int("TPUDIST_SERVE_PREFILL_SLOTS", None),
            handoff=os.environ.get(
                "TPUDIST_SERVE_HANDOFF", "").strip() or "device",
            handoff_queue=env_int("TPUDIST_SERVE_HANDOFF_QUEUE", 8) or 8,
            recover=env_flag("TPUDIST_SERVE_RECOVER", True),
            pool_resize=env_int("TPUDIST_SERVE_POOL_RESIZE", 0) or 0,
            spec=env_flag("TPUDIST_SERVE_SPEC", False),
            spec_k=env_int("TPUDIST_SERVE_SPEC_K", 4) or 4,
            spec_draft_layers=env_int(
                "TPUDIST_SERVE_SPEC_DRAFT_LAYERS", 0) or 0,
        )


class _Observability:
    """Shared live-observability wiring for both server flavors
    (:class:`InferenceServer` here, ``DisaggServer`` in
    :mod:`tpudist.serve.disagg`): the ``/healthz`` health check (engine
    thread ALIVE and loop-error-free and heartbeat FRESH — not merely
    "the HTTP thread answered"), ``/statusz`` registration against the
    process endpoint, and the ``slo_config`` stamp that makes declared
    targets visible to the post-hoc aggregator."""

    _statusz_name = "serve"

    def _init_observability(self) -> None:
        """State both server constructors share — every attribute the
        mixin's health/status methods read lives here, so a field added
        for one flavor cannot be missing on the other."""
        from tpudist.utils.envutil import env_positive_float

        #: the exception that killed the engine loop, if any — /healthz
        #: goes 503 on it (an HTTP thread answering while the loop is
        #: dead is the lie the healthz bugfix exists to kill)
        self.loop_error: Optional[str] = None
        #: engine-loop heartbeat (stamped every iteration, idle included)
        self._beat: Optional[float] = None
        #: /healthz staleness threshold for the heartbeat
        #: (TPUDIST_SERVE_HEALTH_STALE_S; tightened by tests).  The
        #: default must exceed the worst dispatch that legitimately
        #: blocks an iteration — the first request's XLA compile — or
        #: an orchestrator doing liveness restarts would kill a
        #: compiling server in a loop.  The hang WATCHDOG (with its own
        #: first-deadline slack) is the aggressive stall detector.
        self.health_stale_s = env_positive_float(
            "TPUDIST_SERVE_HEALTH_STALE_S", 300.0)
        self._statusz_names: list = []
        #: tenant → in-flight count (submitted minus finished) for
        #: /statusz; mutated under _tenant_lock (ingestion + engine
        #: threads both write)
        self._tenant_inflight: Dict[str, int] = {}
        self._tenant_lock = threading.Lock()

    def _start_observability(self) -> None:
        from tpudist import telemetry
        from tpudist.telemetry import metrics, statusz

        targets = metrics.slo_targets()
        if targets["ttft_s"] or targets["tpot_s"]:
            telemetry.event(
                "slo_config",
                **({"ttft_ms": round(targets["ttft_s"] * 1e3, 3)}
                   if targets["ttft_s"] else {}),
                **({"tpot_ms": round(targets["tpot_s"] * 1e3, 3)}
                   if targets["tpot_s"] else {}))
        # static-geometry gauges: a scrape between server start and the
        # first request already answers "what is this process serving"
        if metrics.enabled_from_env():
            reg = metrics.registry()
            for name, value in self._observability_gauges().items():
                reg.gauge(name).set(value)
        srv = statusz.ensure_started()
        if srv is not None:
            self._statusz_names = [
                srv.register_health(self._statusz_name, self._health_check),
                srv.register_status(self._statusz_name, self._statusz_doc),
            ]

    def _stop_observability(self) -> None:
        from tpudist.telemetry import statusz

        srv = statusz.active()
        if srv is not None:
            for name in self._statusz_names:
                srv.unregister(name)
        self._statusz_names = []

    def _health_check(self):
        """(ok, detail) for ``/healthz``.  Unhealthy when the engine
        loop has aborted (``serve_loop_error``), its thread is gone, or
        its heartbeat is stale — the regression the hygiene pass pinned:
        liveness of the HTTP thread alone must never read as healthy."""
        t = self._thread
        alive = t is not None and t.is_alive()
        beat_age = (None if self._beat is None
                    else time.monotonic() - self._beat)
        stale = beat_age is not None and beat_age > self.health_stale_s
        ok = alive and self.loop_error is None and not stale
        return ok, {
            "engine_thread_alive": alive,
            "loop_error": self.loop_error,
            "beat_age_s": None if beat_age is None else round(beat_age, 3),
            "heartbeat_stale": stale,
            "draining": self._draining,
        }

    def _track_tenant(self, tenant, delta: int) -> None:
        # submit threads race the engine thread here — one tiny lock
        # keeps the read-modify-write atomic (display-only data, but a
        # lost decrement would pin a phantom in-flight forever)
        key = tenant if tenant else "default"
        with self._tenant_lock:
            n = self._tenant_inflight.get(key, 0) + delta
            if n <= 0:
                self._tenant_inflight.pop(key, None)
            else:
                self._tenant_inflight[key] = n

    def _statusz_doc(self) -> dict:  # per-flavor
        raise NotImplementedError

    def _observability_gauges(self) -> Dict[str, float]:  # per-flavor
        return {}


class InferenceServer(_Observability):
    """Continuous-batching server over a ``TransformerLM`` decode path.

    Usage::

        server = InferenceServer(module, params, ServeConfig(num_slots=8))
        server.start()
        h = server.submit(prompt_ids, max_new=32, on_token=stream_cb)
        h.wait(); print(h.tokens, h.finish_reason)
        server.close()          # graceful drain (same path as SIGTERM)
    """

    def __init__(self, module, params, config: Optional[ServeConfig] = None,
                 *, install_signal_handler: bool = True):
        self.config = config or ServeConfig.from_env()
        self.engine = SlotEngine(
            module, params, num_slots=self.config.num_slots,
            prefill_pad=self.config.prefill_pad,
            decode_block=self.config.decode_block,
            paged=self.config.paged, kv_block=self.config.kv_block,
            kv_blocks=self.config.kv_blocks, kv_int8=self.config.kv_int8,
            prefix_cache_blocks=self.config.prefix_cache_blocks,
            attn_kernel=self.config.attn_kernel,
            mesh=self.config.mesh_config(),
            spec_draft=self.config.resolve_spec_draft(module),
            spec_k=self.config.spec_k)
        hasher = None
        if self.config.paged and self.config.prefix_cache_blocks > 0:
            from tpudist.serve.paged_alloc import hash_chain

            bs = self.engine.paged_cfg.block_size
            hasher = lambda prompt: hash_chain(prompt, bs)  # noqa: E731
        self.scheduler = Scheduler(
            queue_limit=self.config.queue_limit,
            check_budget=self.engine.check_budget,
            default_max_new=self.config.max_new,
            default_deadline_s=self.config.deadline_s,
            prefix_hasher=hasher)
        self._install_signal = install_signal_handler
        self._installed_preemption = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._draining = False
        self._slot_handles: Dict[int, RequestHandle] = {}
        # counters (engine thread writes, stats() reads — GIL-atomic)
        self.completed = 0
        self.tokens_out = 0
        self._occupancy_sum = 0.0
        self._steps = 0
        # -- live observability plane (telemetry.statusz) ------------------
        self._init_observability()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "InferenceServer":
        if self._thread is not None:
            raise RuntimeError("server already started")
        from tpudist import telemetry
        from tpudist.runtime import faults, preemption

        # chaos harness: arm TPUDIST_FAULT at the serving entry like the
        # training loops do at theirs (the serve-side kinds inject in
        # the disagg loop; arming here keeps the grammar's no-code-
        # changes contract uniform across servers)
        faults.arm_from_env()
        telemetry.ensure_started()
        # one config-stamp event: the static KV geometry the aggregator
        # pairs with the per-block occupancy gauges (block size, pool
        # bytes, bytes/pos — the denominator side of the capacity story)
        kv = self.engine.kv_stats()
        telemetry.event(
            "serve_kv_config", paged=kv["paged"], quantized=kv["quantized"],
            attn_kernel=kv["attn_kernel"],
            block_size=kv["block_size"], blocks_total=kv["blocks_total"],
            pool_bytes=kv["pool_bytes"], bytes_per_pos=kv["bytes_per_pos"],
            num_slots=self.engine.num_slots, max_len=self.engine.max_len)
        self._start_observability()
        if self._install_signal:
            # SIGTERM → drain: the same preemption flag the training loop
            # checkpoints on.  Off the main thread install degrades to a
            # warned no-op (preemption.py's contract) — close() still
            # drains explicitly.
            self._installed_preemption = preemption.install()
        self._thread = threading.Thread(
            target=self._loop, name="tpudist-serve", daemon=True)
        self._thread.start()
        return self

    def submit(self, prompt, *, max_new: Optional[int] = None,
               temperature: float = 0.0, deadline_s: Optional[float] = None,
               seed: Optional[int] = None, eos_id: Optional[int] = None,
               on_token: Optional[Callable[[int, int], None]] = None,
               spec: Optional[bool] = None, tenant: Optional[str] = None,
               ) -> RequestHandle:
        """Thread-safe ingestion; raises :class:`AdmissionError` on
        backpressure/budget rejection (reason stamped into telemetry).
        ``spec=False`` opts this request out of speculative decoding on
        a spec-enabled server (mixed spec/non-spec traffic); ``tenant``
        labels the request in telemetry, per-tenant metrics/SLO
        attainment, and ``/statusz`` in-flight counts."""
        from tpudist import telemetry

        # count the in-flight BEFORE the handle becomes visible to the
        # engine thread — scheduler.submit enqueues and notifies, so a
        # fast finish could otherwise decrement first (losing the -1)
        # and pin a phantom in-flight forever
        tkey = None if tenant is None else str(tenant)
        self._track_tenant(tkey, +1)
        try:
            return self.scheduler.submit(
                prompt, max_new=max_new, temperature=temperature,
                deadline_s=deadline_s, seed=seed, eos_id=eos_id,
                on_token=on_token, spec=spec, tenant=tenant)
        except BaseException as e:
            # never admitted — ANY failure (bad prompt included, not
            # just AdmissionError) must give the +1 back or the tenant
            # pins a phantom in-flight forever
            self._track_tenant(tkey, -1)
            if isinstance(e, AdmissionError):
                telemetry.event("serve_rejected", reason=e.reason)
            raise

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, finish everything admitted, stop the loop.
        Returns True once the engine thread exited (or never ran).

        With no live engine thread — server never started, or its loop
        already died — queued requests can never produce tokens: they
        finish with reason ``"shutdown"`` instead of hanging their
        waiters forever."""
        self._stop.set()
        t = self._thread
        ok = True
        if t is not None:
            t.join(timeout)
            ok = not t.is_alive()
        if ok:
            # After a graceful drain both are empty — this only bites on
            # the never-started / dead-loop paths.
            self.scheduler.refuse_new("draining")
            self._abort_outstanding()
        return ok

    def close(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown (drain) + handler restore."""
        ok = self.drain(timeout)
        self._stop_observability()
        if self._installed_preemption:
            from tpudist.runtime import preemption

            preemption.reset()
            self._installed_preemption = False
        return ok

    def _observability_gauges(self) -> Dict[str, float]:
        kv = self.engine.kv_stats()
        return {
            "tpudist_serve_slots": self.engine.num_slots,
            "tpudist_serve_queue_limit": self.config.queue_limit,
            "tpudist_serve_kv_pool_bytes": kv["pool_bytes"],
        }

    def _statusz_doc(self) -> dict:
        """The ``/statusz`` section: current occupancy, KV residency,
        queue depth, world/generation identity, per-tenant in-flight."""
        from tpudist.utils.envutil import env_int

        eng = self.engine
        kv_occ, kv_resident = eng.kv_gauges()
        kv = eng.kv_stats()
        return {
            "slots": {
                "total": int(eng.num_slots),
                "active": int(eng.num_active),
                "prefilling": len(eng.prefilling_slots()),
                "occupancy": round(float(eng.occupancy), 4),
            },
            "queue": {
                "pending": self.scheduler.pending(),
                "limit": self.config.queue_limit,
                "rejected": self.scheduler.rejected,
            },
            "kv": {
                "paged": bool(kv["paged"]),
                "pool_bytes": kv["pool_bytes"],
                "bytes_resident": int(kv_resident),
                "block_occupancy": (None if kv_occ is None
                                    else round(float(kv_occ), 4)),
            },
            "completed": self.completed,
            "tokens_out": self.tokens_out,
            "tenants_in_flight": dict(self._tenant_inflight),
            "world": env_int("TPUDIST_NUM_PROCESSES", None),
            "generation": env_int("TPUDIST_RESTART_COUNT", 0),
            "draining": self._draining,
            "loop_error": self.loop_error,
        }

    def stats(self) -> dict:
        return {
            "completed": self.completed,
            "rejected": self.scheduler.rejected,
            "tokens_out": self.tokens_out,
            "pending": self.scheduler.pending(),
            "active": self.engine.num_active,
            "prefilling": len(self.engine.prefilling_slots()),
            "occupancy_mean": (self._occupancy_sum / self._steps
                               if self._steps else 0.0),
            "compile_counts": self.engine.compile_counts(),
            "decode": self.engine.decode_stats(),
            "spec": self.engine.spec_stats(),
            "kv": self.engine.kv_stats(),
            "spmd": self.engine.spmd_stats(),
        }

    # -- the engine loop ----------------------------------------------------

    def _should_drain(self) -> bool:
        if self._stop.is_set():
            return True
        from tpudist.runtime import preemption

        return preemption.requested()

    def _abort_outstanding(self) -> None:
        """Finish every request that can no longer be served (reason
        ``"shutdown"``) — the hard-stop twin of the graceful drain."""
        for slot in list(self._slot_handles):
            h = self._slot_handles.pop(slot)
            h._finish("shutdown")
            self._note_finished(h)
        for h in self.scheduler.take(1 << 30):
            if not h.done:
                h._finish("shutdown")
            self._note_finished(h)

    def _loop(self) -> None:
        from tpudist import telemetry

        try:
            self._run_loop()
        except BaseException as e:
            # The loop must not die silently: a device error (OOM, a
            # budget-guard RuntimeError) would otherwise strand every
            # in-flight and queued handle in wait() forever while
            # submit() keeps admitting doomed work.
            self.loop_error = repr(e)  # /healthz goes 503 on this
            telemetry.event("serve_loop_error", error=repr(e))
            raise  # threading excepthook still reports the traceback
        finally:
            self.scheduler.refuse_new("draining")
            self._abort_outstanding()

    def _run_loop(self) -> None:
        from tpudist import telemetry

        eng, sched = self.engine, self.scheduler
        while True:
            self._beat = time.monotonic()  # /healthz heartbeat
            if not self._draining and self._should_drain():
                self._draining = True
                sched.refuse_new("draining")
                telemetry.event("serve_drain", pending=sched.pending(),
                                active=eng.num_active)
            now = time.monotonic()
            # deadline enforcement: in-slot AND queued (the queue check
            # must not wait for a slot to free — all lanes can be busy
            # for far longer than a queued request's deadline).  A block
            # is atomic, so mid-decode expiry lands between blocks.
            for slot, h in list(self._slot_handles.items()):
                if h._expired(now):
                    self._finish_slot(slot, "deadline")
            # a decoding slot whose cache filled with budget unspent can
            # only mean the admission budget rule was bypassed — finish
            # it LOUDLY (reason "cache_full") instead of letting the next
            # decode block clamp writes onto max_len-1 and attend over
            # garbage, or crash the loop for every other tenant
            for slot in eng.cache_full_slots():
                if slot in self._slot_handles:
                    self._finish_slot(slot, "cache_full")
            for h in sched.expire_queued(now):
                self._note_finished(h)
            # FIFO-with-budget admission into free lanes: ONE fused
            # prefill+scatter dispatch for the whole admission batch.
            # The paged engine adds a second gate: the queue head is
            # taken only while its whole block footprint fits the pool
            # (reused prefix blocks discounted).
            free = eng.free_slots()
            if free:
                # the gate runs once per queued candidate within ONE
                # take; `reserved` carries the fresh blocks already
                # promised to earlier candidates of this same batch and
                # `pinned` the cached blocks they will reuse (counted
                # evictable by a naive peek, pinned the moment they
                # land) — the free list only learns about either at
                # start_batch
                reserved, pinned = [0], []

                def _gate(h):
                    req = h.request
                    got = eng.kv_admission_probe(
                        len(req.prompt), req.max_new, req.prefix_hashes,
                        reserve=reserved[0], protect=pinned)
                    if got is None:
                        return False
                    reserved[0] += got[0]
                    pinned.extend(got[1])
                    return True

                batch = sched.take(len(free), now, admit=_gate)
                alive = []
                for h in batch:
                    if h.done:  # finished in-queue (deadline expired)
                        self._note_finished(h)
                    else:
                        alive.append(h)
                if alive:
                    items, t0 = [], time.monotonic()
                    for h, slot in zip(alive, free):
                        h.slot = slot
                        h.t_admitted = t0
                        items.append((slot, h.request.prompt,
                                      h.request.temperature, h.request.seed,
                                      h.request.max_new,
                                      h.request.prefix_hashes,
                                      h.request.spec))
                        self._slot_handles[slot] = h
                    with telemetry.span("prefill", n=len(items)):
                        firsts = eng.start_batch(items)
                    for slot, tok in firsts.items():
                        if tok is not None:
                            self._deliver_block(slot, [tok])
            # chunked prefill: one prompt chunk per prefilling slot per
            # iteration — long prompts never stall decode for more than
            # one chunk's worth of device time
            if eng.prefilling_slots():
                with telemetry.span("prefill",
                                    chunks=len(eng.prefilling_slots())):
                    done = eng.advance_prefill()
                for slot, tok in done.items():
                    self._deliver_block(slot, [tok])
            # one fused decode block over every decoding lane — the
            # speculative draft-propose/target-verify block when the
            # engine carries a draft (decode_auto falls back to the
            # plain block, draft-tracked, when speculation cannot run)
            if eng.num_active:
                occ = eng.occupancy
                active = eng.num_active
                tele = telemetry.active()
                t0 = time.monotonic()
                info, blocks = eng.decode_auto()
                if tele is not None and info is not None:
                    kv_occ, kv_resident = eng.kv_gauges()
                    tags = {"occupancy": occ, "active": active,
                            "k": info["k"], "tokens": info["tokens"],
                            "dispatch_s": round(info["dispatch_s"], 9),
                            "sync_s": round(info["sync_s"], 9),
                            # the KV capacity/bandwidth gauges: pool block
                            # occupancy (None on dense), resident bytes,
                            # and the bytes this block's attention streamed
                            "kv_block_occupancy": kv_occ,
                            "kv_bytes_resident": kv_resident,
                            "kv_read_bytes": info["kv_read_bytes"]}
                    if info.get("spec"):
                        # the spec_verify span: per-block acceptance +
                        # the draft/verify wall split the serving
                        # report's speculation section aggregates
                        tags.update(
                            accepted=info["accepted"],
                            drafted=info["drafted"],
                            rollbacks=info["rollbacks"],
                            draft_s=round(info["draft_s"], 9),
                            verify_s=round(info["verify_s"], 9))
                        tele.record_span("spec_verify", t0,
                                         time.monotonic() - t0, tags)
                    else:
                        tele.record_span("decode_block", t0,
                                         time.monotonic() - t0, tags)
                self._occupancy_sum += occ
                self._steps += 1
                for slot, toks in blocks.items():
                    self._deliver_block(slot, toks)
            elif eng.prefilling_slots():
                pass  # prefill work continues next iteration
            elif self._draining and sched.pending() == 0:
                break
            else:
                sched.wait_for_work(_IDLE_WAIT_S)

    def _deliver_block(self, slot: int, toks) -> None:
        """Stream a token block to the slot's request, truncating
        post-hoc at its stop token or length budget (the device block is
        speculative past either — bounded by the block size)."""
        h = self._slot_handles[slot]
        eos = h.request.eos_id
        for tok in toks:
            h._deliver(tok)
            self.tokens_out += 1
            if eos is not None and tok == eos:
                self._finish_slot(slot, "eos")
                return
            if len(h.tokens) >= h.request.max_new:
                self._finish_slot(slot, "length")
                return

    def _finish_slot(self, slot: int, reason: str) -> None:
        h = self._slot_handles.pop(slot)
        self.engine.evict(slot)
        h._finish(reason)
        self._note_finished(h)

    def _note_finished(self, h: RequestHandle) -> None:
        from tpudist import telemetry
        from tpudist.telemetry import trace

        self.completed += 1
        self._track_tenant(h.request.tenant, -1)
        telemetry.event(
            "request_finished", id=h.id, reason=h.finish_reason,
            prompt_len=int(len(h.request.prompt)), tokens_out=len(h.tokens),
            ttft_s=h.ttft_s, tpot_s=h.tpot_s, queue_wait_s=h.queue_wait_s,
            trace_id=h.trace_id,
            **({"tenant": h.request.tenant} if h.request.tenant else {}))
        # per-request lifeline spans (req_queue/req_prefill/req_decode)
        # for the cross-pool trace join + Chrome export
        trace.emit_request_lifeline(h)


def serve_forever(module, params, config: Optional[ServeConfig] = None):
    """Start a server and return it (the embedding entry — the CLI demo
    in ``__main__`` owns its own loop).  ``config.disagg`` selects the
    prefill/decode-disaggregated coordinator
    (:class:`tpudist.serve.disagg.DisaggServer`) — same submit/close
    surface, two engine pools with KV handoff behind it."""
    cfg = config or ServeConfig.from_env()
    if cfg.disagg:
        from tpudist.serve.disagg import DisaggServer

        return DisaggServer(module, params, cfg).start()
    return InferenceServer(module, params, cfg).start()
