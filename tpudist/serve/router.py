"""Fleet front door: affinity routing over replicas with health-probed
failover, session migration on replica death, and spill-not-reject
overload.

Everything below one server is PR 1-15 machinery; this module is the
layer ABOVE it — a router over N ``InferenceServer``/``DisaggServer``
replicas that keeps serving when any one of them dies or saturates.
Routing is a pure host decision riding as data (the router never
touches a compiled program — compile pins stay flat per replica under
arbitrary routing churn):

- **session affinity** — a ``submit(session=)`` resume lands on the
  replica holding the parked KV (the host tier's no-recompute resume
  only helps if the turn arrives where the lane parked);
- **prefix-cache affinity** — requests sharing a prompt prefix hash to
  the same replica (rendezvous hashing on a blake2b prefix digest —
  stable under replica death: only the dead replica's keys move), so
  its shared-prefix LRU block cache actually hits;
- **least-loaded placement** — otherwise, the replica with the lowest
  load score from its scraped live gauges (queue depth, slot/KV
  occupancy, router-side in-flight).

The robustness core, in failure order:

- **health probing** — each replica is probed off its ``/healthz``
  backend (:meth:`_Observability._health_check`: engine thread alive,
  loop-error-free, heartbeat fresh) every ``probe_s``; a replica is
  marked DEAD after ``probe_failures`` consecutive failures, and dead
  replicas re-probe on exponential backoff (a flapping replica must not
  eat the probe budget);
- **spill, not reject** — a replica rejecting admission
  (queue/KV/shed backpressure) spills the request to the next-best
  sibling (paying a re-prefill there) while ANY replica has headroom;
  only a whole-fleet rejection surfaces to the caller, with the
  shed-path reason passed through (``shed_load`` wins over transient
  reasons so the PR-14 overload story is visible at fleet scope);
- **retries with duplicate-drop** — a request whose replica dies
  mid-serve re-homes onto a survivor with a bounded, backoff-spaced
  retry budget: the full prompt resubmits with identical sampling
  parameters (decode is a pure function of the packaged state and the
  ``fold_in(key, count)`` stream, so the replay is byte-identical) and
  exactly the already-delivered tokens drop as duplicates.  The
  abandoned per-replica attempt is finished ``router_spill`` (visible
  in telemetry); the caller-facing handle finishes with the sibling's
  reason.  Only when no healthy sibling can take the lane within the
  budget does the handle finish ``replica_lost``;
- **session migration** — parked sessions ride the existing
  ``serialize_package`` wire format one level up: after each finished
  turn the router stashes a copy of the parked package
  (``export_session``), and when the owning replica drains or dies the
  stash is adopted into a survivor's host tier (``adopt_session``) so
  the session's next turn RESUMES there.  A missing or corrupt stash
  degrades to a full re-prefill on the survivor — the digest check
  stays where it always was, in the resume path's deserialize — never
  a wrong byte, never a hang.

Chaos: ``TPUDIST_FAULT=replica_kill@nth:N`` kills replica N's engine
loop at the router's probe tick (``faults.inject_replica_kill``) —
the in-process twin of a replica host dying, driving this exact
failover path with zero test-only seams.

Thread contract: any number of ingestion threads call :meth:`submit`;
one router thread runs the probe/failover tick; each replica keeps its
own engine thread.  All router state sits behind one lock.  Token
forwarding runs on replica engine threads but appends through a
generation gate, so an orphaned attempt that keeps streaming (a hung —
not dead — loop) can never interleave duplicates into a re-homed
stream.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
import warnings
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tpudist.serve.scheduler import AdmissionError

#: token count of the router-side prefix digest: requests agreeing on
#: their first PREFIX_TOKENS tokens (the shared system prompt) route to
#: the same replica.  Deliberately independent of any replica's KV
#: block size — the router must not reach into engine geometry.
_PREFIX_TOKENS = 16

#: inner finish reasons that mean "the REPLICA failed", not the request
#: — the re-home triggers (a dead loop aborts its work as shutdown; a
#: collapsed pool finishes worker_lost; a parked preempted lane cut off
#: by the crash finishes preempted).
_RETRY_REASONS = ("shutdown", "worker_lost", "preempted")

#: dead-replica re-probe backoff: doubles from probe_s per failed
#: re-probe, capped at this many multiples of probe_s.
_BACKOFF_CAP = 40.0


@dataclasses.dataclass
class RouterConfig:
    """Fleet-router knobs; :meth:`from_env` reads the
    ``TPUDIST_ROUTER_*`` family (registered in
    ``tpudist.utils.envutil.ENV_VARS``)."""

    #: fleet size a launch rig should build (the router itself takes an
    #: explicit replica list; this knob sizes env-driven rigs like the
    #: ``python -m tpudist.serve --replicas`` demo)
    replicas: int = 2
    probe_s: float = 0.05  # health-probe interval per healthy replica
    probe_failures: int = 3  # consecutive failures before marked dead
    retries: int = 2  # per-request re-home budget after replica death
    retry_backoff_s: float = 0.05  # re-home backoff base (doubles)
    spill: bool = True  # overflow to a sibling instead of rejecting
    stash: bool = True  # router-side parked-package stash (migration)
    #: routing policy: "affinity" (session → prefix → least-loaded) or
    #: "rr" (plain round-robin — the bench's comparison arm and an
    #: escape hatch when affinity itself is suspected)
    policy: str = "affinity"

    @classmethod
    def from_env(cls) -> "RouterConfig":
        import os

        from tpudist.utils.envutil import (env_flag, env_int,
                                           env_positive_float)

        return cls(
            replicas=env_int("TPUDIST_ROUTER_REPLICAS", 2) or 2,
            probe_s=env_positive_float("TPUDIST_ROUTER_PROBE_S", 0.05)
            or 0.05,
            probe_failures=env_int("TPUDIST_ROUTER_PROBE_FAILURES", 3) or 3,
            retries=env_int("TPUDIST_ROUTER_RETRIES", 2) or 2,
            retry_backoff_s=env_positive_float(
                "TPUDIST_ROUTER_RETRY_BACKOFF_S", 0.05) or 0.05,
            spill=env_flag("TPUDIST_ROUTER_SPILL", True),
            stash=env_flag("TPUDIST_ROUTER_STASH", True),
            policy=os.environ.get(
                "TPUDIST_ROUTER_POLICY", "").strip() or "affinity",
        )


class _Replica:
    """Router-side view of one replica: health state machine + the
    load gauges scraped from its ``/statusz`` backend."""

    def __init__(self, index: int, server):
        self.index = index
        self.server = server
        self.up = True
        self.draining = False
        self.fails = 0  # consecutive probe failures
        self.next_probe = 0.0
        self.backoff_s: Optional[float] = None
        self.routed = 0  # requests this replica was chosen for
        self.deaths = 0

    def health_ok(self) -> bool:
        """One probe against the replica's ``/healthz`` backend (a
        raising probe counts as a failure — a dead loop may leave any
        state behind)."""
        try:
            return bool(self.server._health_check()[0])
        except Exception:
            return False

    def saturated(self) -> bool:
        """Queue at its bound — the next submit would reject
        ``queue_full`` (prefix affinity yields to the spill path)."""
        try:
            return self.server.scheduler.pending() \
                >= self.server.config.queue_limit
        except Exception:
            return True

    def load_score(self) -> float:
        """Least-loaded placement score off the scraped live gauges:
        queue fraction + slot occupancy + KV block occupancy (flavor-
        tolerant reads — the disagg doc shapes its sections per pool).
        An unreachable scrape sorts last."""
        try:
            doc = self.server._statusz_doc()
        except Exception:
            return float("inf")
        q = doc.get("queue") or {}
        score = float(q.get("pending", 0)) / max(1, int(q.get("limit", 1)))
        slots = doc.get("slots") or {}
        occ = slots.get("occupancy")
        if occ is None:
            pools = doc.get("pools") or {}
            dec = pools.get("decode") or {}
            cap = max(1, int(dec.get("workers", 1))
                      * int(dec.get("slots_per_worker", 1)))
            occ = float(dec.get("active", 0)) / cap if dec else 0.0
        score += float(occ or 0.0)
        kv = doc.get("kv") or {}
        kv_occ = kv.get("block_occupancy")
        if isinstance(kv_occ, (int, float)):
            score += float(kv_occ)
        return score


class RouterHandle:
    """The caller's view of a fleet-routed request: same streamed-token
    / ``done`` / finish-reason surface as ``RequestHandle``, plus the
    routing trail (``replica``, ``attempts``, ``spilled``).  Survives
    re-homing: the handle is stable while inner per-replica attempts
    come and go beneath it."""

    def __init__(self, prompt: np.ndarray, kwargs: dict,
                 on_token: Optional[Callable[[int, int], None]],
                 skey: Optional[tuple], pkey: Optional[str]):
        self.prompt = prompt
        self.kwargs = kwargs  # resubmission parameters, verbatim
        self.on_token = on_token
        self.skey = skey  # (tenant_label, session) or None
        self.pkey = pkey  # router-side prefix digest or None
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self._done = threading.Event()
        now = time.monotonic()
        self.t_submit = now
        self.t_first_token: Optional[float] = None
        self.t_last_token: Optional[float] = None
        self.t_done: Optional[float] = None
        #: current inner per-replica attempt (None while parked in the
        #: router's retry line)
        self.inner = None
        self.replica: Optional[int] = None
        #: forwarding generation: bumped on every re-home so an
        #: orphaned attempt's late tokens are ignored, never appended
        self.gen = 0
        self.attempts = 0
        self.retries_used = 0
        self.next_try = 0.0
        self.spilled = False
        self.resumed = False

    # -- caller side --------------------------------------------------------

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def tpot_s(self) -> Optional[float]:
        if (self.t_first_token is None or self.t_last_token is None
                or len(self.tokens) < 2):
            return None
        return ((self.t_last_token - self.t_first_token)
                / (len(self.tokens) - 1))

    @property
    def trace_id(self) -> Optional[str]:
        """The CURRENT inner attempt's trace id (each re-home attempt
        mints its own — the per-replica lifelines join on it)."""
        return None if self.inner is None else self.inner.trace_id

    @property
    def logprobs(self) -> list:
        """Per-token top-n logprob rows from the CURRENT inner attempt
        (``submit(logprobs=n)``).  A re-homed attempt re-decodes the
        stream byte-identically from the prompt, so the final attempt's
        rows cover the whole delivered stream."""
        return [] if self.inner is None else list(self.inner.logprobs)

    # -- router side --------------------------------------------------------

    def _expired(self, now: float) -> bool:
        d = self.kwargs.get("deadline_s")
        return d is not None and d > 0 and (now - self.t_submit) > d

    def remaining_deadline(self, now: float) -> Optional[float]:
        """Deadline budget left for a re-homed inner attempt (the outer
        deadline is relative to the ORIGINAL submit).  ``None`` when
        the request carries no deadline; <= 0 means already expired."""
        d = self.kwargs.get("deadline_s")
        if d is None or d <= 0:
            return None
        return d - (now - self.t_submit)

    def _forwarder(self, skip: int) -> Callable[[int, int], None]:
        """Token forwarder for one inner attempt: drops the first
        ``skip`` tokens (the duplicate-drop on a re-homed replay — the
        resubmitted stream is byte-identical, so dropping exactly the
        delivered count keeps the outer stream exact), and ignores
        everything once the handle re-homes again (generation gate)."""
        gen = self.gen
        state = [int(skip)]

        def cb(tok: int, _idx: int) -> None:
            if gen != self.gen:
                return  # orphaned attempt still streaming — ignore
            if state[0] > 0:
                state[0] -= 1
                return
            self._deliver(int(tok))

        return cb

    def _deliver(self, tok: int) -> None:
        now = time.monotonic()
        if self.t_first_token is None:
            self.t_first_token = now
        self.t_last_token = now
        self.tokens.append(tok)
        cb = self.on_token
        if cb is not None:
            try:
                cb(tok, len(self.tokens) - 1)
            except Exception as e:  # a user callback must not kill a loop
                warnings.warn(f"on_token callback raised: {e!r}",
                              RuntimeWarning, stacklevel=2)

    def _finish(self, reason: str) -> None:
        if self._done.is_set():
            return
        self.finish_reason = reason
        self.t_done = time.monotonic()
        self._done.set()


class FleetRouter:
    """Front door over N replicas (module doc has the whole story).

    Usage::

        fleet = [InferenceServer(module, params, cfg).start()
                 for _ in range(3)]
        router = FleetRouter(fleet, RouterConfig()).start()
        h = router.submit(prompt_ids, session="chat-1", max_new=32)
        h.wait(); print(h.tokens, h.finish_reason, h.replica)
        router.close()      # drains every replica

    The replicas are already-started server objects — the router owns
    routing and failover, not replica construction (a launch rig builds
    the fleet; the ``--replicas`` demo in ``tpudist.serve.__main__`` is
    the in-process version)."""

    def __init__(self, replicas, config: Optional[RouterConfig] = None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.config = config or RouterConfig.from_env()
        if self.config.policy not in ("affinity", "rr"):
            raise ValueError(
                f"unknown router policy {self.config.policy!r} "
                "(expected 'affinity' or 'rr')")
        self._replicas = [_Replica(i, s) for i, s in enumerate(replicas)]
        self._lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closing = False
        self._ticks = 0
        #: (tenant_label, session) -> replica index holding the parked KV
        self._session_home: Dict[tuple, int] = {}
        #: (tenant_label, session) -> exported package stash (migration)
        self._stash: Dict[tuple, dict] = {}
        #: live outer handles, insertion-ordered by id
        self._inflight: Dict[int, RouterHandle] = {}
        self._retry_q: List[RouterHandle] = []
        #: (skey, replica index, give-up time): session turns whose
        #: park had not landed yet when the handle finished (parking
        #: runs on the engine loop just AFTER the done event) — the
        #: tick re-tries the export until it sticks
        self._pending_export: List[tuple] = []
        self._next_id = 0
        # lifetime counters (stats() + the fleet report section)
        self.routed = 0
        self.routes_by_kind: Dict[str, int] = {}
        self.spills = 0
        self.retries = 0
        self.migrations = 0
        self.replica_deaths = 0
        self.lost = 0
        self.errors = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FleetRouter":
        if self._thread is not None:
            raise RuntimeError("router already started")
        from tpudist import telemetry
        from tpudist.runtime import faults

        # the replica_kill chaos kind arms at the router entry, like
        # every serving loop arms at its own
        faults.arm_from_env()
        telemetry.ensure_started()
        telemetry.event(
            "router_config", replicas=len(self._replicas),
            policy=self.config.policy, probe_s=self.config.probe_s,
            probe_failures=self.config.probe_failures,
            retries=self.config.retries, spill=self.config.spill,
            stash=self.config.stash)
        now = time.monotonic()
        for rep in self._replicas:
            self._probe(rep, now)
        self._thread = threading.Thread(
            target=self._loop, name="tpudist-router", daemon=True)
        self._thread.start()
        return self

    def close(self, timeout: Optional[float] = None) -> bool:
        """Graceful fleet shutdown: stop routing, drain every replica
        (in-flight work finishes and propagates), then stop the router
        thread and finish anything still unresolved (``shutdown`` —
        same contract as a single server's hard-stop path)."""
        with self._lock:
            self._closing = True
        ok = True
        for rep in self._replicas:
            try:
                ok = rep.server.close(timeout) and ok
            except Exception:
                ok = False
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            ok = ok and not t.is_alive()
        with self._lock:
            for outer in list(self._inflight.values()):
                inner = outer.inner
                if inner is not None and inner.done and not outer.done:
                    self._finish_outer(outer)
                elif not outer.done:
                    outer._finish("shutdown")
            self._inflight.clear()
            self._retry_q.clear()
        return ok

    def drain_replica(self, index: int,
                      timeout: Optional[float] = None) -> bool:
        """Take one replica out of rotation gracefully: stop routing to
        it, MIGRATE its parked sessions onto survivors through the
        stash-free live path (export from its tier, adopt into the
        target's), then drain it — in-flight work finishes in place.
        The deploy-rollover story at fleet scope."""
        rep = self._replicas[index]
        with self._lock:
            rep.draining = True
            for tenant, session in rep.server.parked_sessions():
                self._migrate_session(
                    (tenant, session),
                    stash=rep.server.export_session(tenant, session),
                    exclude={index}, reason="drain")
        ok = rep.server.close(timeout)
        with self._lock:
            rep.up = False
        return ok

    # -- ingestion ----------------------------------------------------------

    def submit(self, prompt, *, max_new: Optional[int] = None,
               temperature: float = 0.0,
               deadline_s: Optional[float] = None,
               seed: Optional[int] = None, eos_id: Optional[int] = None,
               on_token: Optional[Callable[[int, int], None]] = None,
               spec: Optional[bool] = None, tenant: Optional[str] = None,
               priority: int = 0, session: Optional[str] = None,
               adapter: Optional[str] = None,
               grammar: Optional[str] = None, json_schema=None,
               stop=None, logprobs: int = 0) -> RouterHandle:
        """Route and admit one request; raises :class:`AdmissionError`
        only when the WHOLE fleet rejects (the sheddiest reason passes
        through — ``shed_load`` wins so fleet saturation is
        distinguishable from one replica's bad moment).  Structured
        output (``grammar``/``json_schema``/``stop``/``logprobs``)
        passes through verbatim — each replica compiles/validates in
        its own scheduler, and a failover resubmission carries the ask
        unchanged."""
        if self._closing:
            raise AdmissionError("draining")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        skey = (None if session is None
                else (tenant if tenant else "default", str(session)))
        pkey = None
        if len(prompt):
            head = prompt[:_PREFIX_TOKENS]
            pkey = hashlib.blake2b(head.tobytes(),
                                   digest_size=8).hexdigest()
        kwargs = dict(max_new=max_new, temperature=temperature,
                      deadline_s=deadline_s, seed=seed, eos_id=eos_id,
                      spec=spec, tenant=tenant, priority=priority,
                      session=session, adapter=adapter,
                      grammar=grammar, json_schema=json_schema,
                      stop=stop, logprobs=logprobs)
        outer = RouterHandle(prompt, kwargs, on_token, skey, pkey)
        with self._lock:
            outer.id = self._next_id
            self._next_id += 1
            if skey is not None:
                home = self._session_home.get(skey)
                if home is not None and not self._replicas[home].up:
                    # home replica died since the last turn: re-home
                    # the parked package from the stash now (lazy twin
                    # of the eager migration at death — covers races)
                    self._rehome_session(skey)
            self._route_and_submit(outer, skip=0)
            self._inflight[outer.id] = outer
        return outer

    # -- routing ------------------------------------------------------------

    def _ups(self, exclude=()) -> List[_Replica]:
        return [r for r in self._replicas
                if r.up and not r.draining and r.index not in exclude]

    @staticmethod
    def _rendezvous(pkey: str, index: int) -> str:
        # highest-random-weight hashing: each (prefix, replica) pair
        # gets a stable score — a dead replica reshuffles ONLY its own
        # keys, every other prefix keeps its cache-warm home
        return hashlib.blake2b(f"{pkey}|{index}".encode(),
                               digest_size=8).hexdigest()

    def _pick(self, skey, pkey, exclude=()) -> Tuple[Optional[_Replica],
                                                     Optional[str]]:
        """(replica, affinity kind) or (None, None) when no healthy
        replica remains.  Order: session home → prefix rendezvous
        (yielding to the spill path when saturated) → least-loaded;
        ``policy="rr"`` replaces the whole ladder with round-robin."""
        ups = self._ups(exclude)
        if not ups:
            return None, None
        if self.config.policy == "rr":
            # rr REPLACES all three affinity keys (the bench comparison
            # arm must not quietly keep session stickiness)
            r = ups[self.routed % len(ups)]
            return r, "rr"
        if skey is not None:
            home = self._session_home.get(skey)
            for r in ups:
                if r.index == home:
                    return r, "session"
        if pkey is not None:
            best = max(ups, key=lambda r: self._rendezvous(pkey, r.index))
            if not best.saturated():
                return best, "prefix"
            # the cache-warm target is full: pre-emptive spill to the
            # least-loaded sibling (paying its re-prefill) rather than
            # bouncing off a known-full queue
            rest = [r for r in ups if r is not best] or ups
            chosen = min(rest, key=lambda r: r.load_score())
            return chosen, ("spill" if chosen is not best else "prefix")
        return min(ups, key=lambda r: r.load_score()), "least_loaded"

    def _route_and_submit(self, outer: RouterHandle, skip: int) -> None:
        """One fleet-wide placement attempt: pick, submit, spill to the
        next-best sibling on rejection while any replica has headroom.
        Raises :class:`AdmissionError` with the passthrough reason when
        the whole fleet rejects."""
        from tpudist import telemetry

        tried: List[int] = []
        last_reason: Optional[str] = None
        shed_seen = False
        while True:
            rep, kind = self._pick(outer.skey, outer.pkey, exclude=tried)
            if rep is None:
                if shed_seen:
                    raise AdmissionError("shed_load")
                raise AdmissionError(last_reason or "no_healthy_replica")
            try:
                self._submit_to(rep, outer, skip)
            except AdmissionError as e:
                last_reason = e.reason
                shed_seen = shed_seen or e.reason.startswith("shed_load")
                tried.append(rep.index)
                if not self.config.spill:
                    raise
                continue
            if tried or kind == "spill":
                # landed on a sibling off the affinity target — either
                # pre-emptively (its queue was known-full) or after it
                # rejected — the spill, paying a re-prefill there
                outer.spilled = True
                self.spills += 1
                telemetry.event("router_spill", replica=rep.index,
                                rejected=tried, reason=last_reason)
                kind = "spill"
            self.routed += 1
            rep.routed += 1
            self.routes_by_kind[kind] = self.routes_by_kind.get(kind, 0) + 1
            if outer.skey is not None:
                self._session_home[outer.skey] = rep.index
            # route_kind, not kind: ``kind`` is a reserved record field
            telemetry.event("router_route", replica=rep.index,
                            route_kind=kind, id=outer.id)
            return

    def _submit_to(self, rep: _Replica, outer: RouterHandle,
                   skip: int) -> None:
        now = time.monotonic()
        deadline = outer.remaining_deadline(now)
        if deadline is not None and deadline <= 0:
            outer._finish("deadline")
            return
        kw = dict(outer.kwargs)
        if kw.get("deadline_s") is not None:
            kw["deadline_s"] = deadline
        outer.gen += 1
        inner = rep.server.submit(outer.prompt, on_token=outer._forwarder(skip), **kw)
        outer.inner = inner
        outer.replica = rep.index
        outer.attempts += 1

    # -- the router tick (probe / watch / retry) ----------------------------

    def _loop(self) -> None:
        from tpudist import telemetry

        while not self._stop.wait(self.config.probe_s):
            try:
                with self._lock:
                    self._tick(time.monotonic())
            except Exception as e:  # the tick must never die silently
                self.errors += 1
                telemetry.event("router_error", error=repr(e)[:200])

    def _tick(self, now: float) -> None:
        from tpudist.runtime import faults

        self._ticks += 1
        # chaos: a due replica_kill hard-stops that replica's engine
        # loop — the probe/failover machinery below takes it from there
        idx = faults.inject_replica_kill(self._ticks)
        if idx is not None and 0 <= idx < len(self._replicas):
            self._replicas[idx].server.kill("replica_kill fault")
        for rep in self._replicas:
            if not rep.draining and now >= rep.next_probe:
                self._probe(rep, now)
        for item in list(self._pending_export):
            skey, idx, give_up = item
            rep = self._replicas[idx]
            stash = None
            if rep.up:
                try:
                    stash = rep.server.export_session(*skey)
                except Exception:
                    stash = None
            if stash is not None:
                self._stash[skey] = stash
                self._session_home[skey] = idx
            if stash is not None or now > give_up or not rep.up:
                self._pending_export.remove(item)
        self._watch(now)
        self._run_retries(now)

    def _probe(self, rep: _Replica, now: float) -> bool:
        from tpudist import telemetry

        ok = rep.health_ok()
        if ok:
            if not rep.up:
                rep.up = True
                telemetry.event("replica_health", replica=rep.index,
                                up=True, ups=len(self._ups()))
            rep.fails = 0
            rep.backoff_s = None
            rep.next_probe = now + self.config.probe_s
            return True
        rep.fails += 1
        if rep.up and rep.fails >= self.config.probe_failures:
            self._mark_down(rep, now)
        if rep.up:
            rep.next_probe = now + self.config.probe_s
        else:
            # exponential backoff on re-probing a dead replica
            base = rep.backoff_s or self.config.probe_s
            rep.backoff_s = min(base * 2.0,
                                _BACKOFF_CAP * self.config.probe_s)
            rep.next_probe = now + rep.backoff_s
        return False

    def _mark_down(self, rep: _Replica, now: float) -> None:
        """Replica declared dead: re-home its parked sessions from the
        stash and queue every in-flight lane it held for re-homing onto
        survivors (duplicate-drop keeps their streams byte-identical)."""
        from tpudist import telemetry

        rep.up = False
        rep.deaths += 1
        rep.backoff_s = self.config.probe_s
        rep.next_probe = now + rep.backoff_s
        self.replica_deaths += 1
        telemetry.event("replica_health", replica=rep.index, up=False,
                        fails=rep.fails, ups=len(self._ups()))
        for skey, home in list(self._session_home.items()):
            if home == rep.index:
                self._rehome_session(skey)
        for outer in list(self._inflight.values()):
            if outer.replica == rep.index and not outer.done:
                inner = outer.inner
                if inner is not None and not inner.done:
                    # the orphaned attempt: mark it loudly (its replica
                    # may be hung, not dead — a zombie delivery is
                    # filtered by the outer's generation gate)
                    inner._finish("router_spill")
                if outer.inner is not None:
                    outer.inner = None
                    outer.gen += 1
                    self._queue_retry(outer, now, immediate=True)

    def _watch(self, now: float) -> None:
        """Propagate finished inner attempts to their outer handles —
        or re-home them when the finish was the replica's death, not
        the request's own."""
        for outer in list(self._inflight.values()):
            inner = outer.inner
            if inner is None or not inner.done:
                continue
            reason = inner.finish_reason
            rep = self._replicas[outer.replica]
            if reason in _RETRY_REASONS and not self._closing:
                # crash-shaped finish: confirm against the replica's
                # health NOW (no waiting for the probe cadence — and a
                # gracefully-drained replica stays healthy, so its
                # shutdowns propagate instead of looping)
                if rep.up and not rep.health_ok():
                    self._mark_down(rep, now)
                if not rep.up:
                    if outer.inner is not None:
                        outer.inner = None
                        outer.gen += 1
                        self._queue_retry(outer, now, immediate=True)
                    continue
            self._finish_outer(outer)

    def _finish_outer(self, outer: RouterHandle) -> None:
        inner = outer.inner
        outer.resumed = outer.resumed or bool(getattr(inner, "resumed",
                                                      False))
        outer._finish(inner.finish_reason)
        self._inflight.pop(outer.id, None)
        if outer in self._retry_q:
            self._retry_q.remove(outer)
        # refresh the migration stash with the just-parked turn (the
        # finished lane parked BEFORE the handle finished, so the
        # export below sees it)
        if (self.config.stash and outer.skey is not None
                and outer.finish_reason in ("length", "eos",
                                            "session_resumed")):
            rep = self._replicas[outer.replica]
            tenant, session = outer.skey
            try:
                stash = rep.server.export_session(tenant, session)
            except Exception:
                stash = None
            if stash is not None:
                self._stash[outer.skey] = stash
                self._session_home[outer.skey] = rep.index
            else:
                # the park is still in flight on the engine loop —
                # re-export from the tick until it lands (bounded; a
                # never-parking lane just ages out)
                self._pending_export.append(
                    (outer.skey, rep.index, time.monotonic() + 2.0))

    def _queue_retry(self, outer: RouterHandle, now: float,
                     immediate: bool = False) -> None:
        if outer not in self._retry_q:
            outer.next_try = now if immediate else (
                now + self.config.retry_backoff_s)
            self._retry_q.append(outer)

    def _run_retries(self, now: float) -> None:
        from tpudist import telemetry

        for outer in list(self._retry_q):
            if outer.done:
                self._retry_q.remove(outer)
                self._inflight.pop(outer.id, None)
                continue
            if now < outer.next_try:
                continue
            if outer._expired(now):
                outer._finish("deadline")
                self._retry_q.remove(outer)
                self._inflight.pop(outer.id, None)
                continue
            skip = len(outer.tokens)
            try:
                self._route_and_submit(outer, skip=skip)
            except AdmissionError as e:
                outer.retries_used += 1
                no_ups = not self._ups()
                if outer.retries_used > self.config.retries or no_ups:
                    # fleet-level passthrough: the PR-14 shed reason
                    # survives the hop; everything else is the fleet
                    # failing this lane
                    self._retry_q.remove(outer)
                    self._inflight.pop(outer.id, None)
                    self.lost += 1
                    if e.reason.startswith("shed_load"):
                        outer._finish("shed_load")
                    else:
                        outer._finish("replica_lost")
                else:
                    outer.next_try = now + (self.config.retry_backoff_s
                                            * (2 ** outer.retries_used))
                continue
            if outer.done:
                # _submit_to expired it (deadline) without an attempt
                self._retry_q.remove(outer)
                self._inflight.pop(outer.id, None)
                continue
            self._retry_q.remove(outer)
            self.retries += 1
            telemetry.event("router_retry", id=outer.id,
                            replica=outer.replica, skip=skip,
                            attempt=outer.attempts)

    # -- session migration --------------------------------------------------

    def _rehome_session(self, skey: tuple) -> None:
        """Dead-home path: adopt the stashed package into a survivor
        (or forget the home — the next turn re-prefills fresh there)."""
        home = self._session_home.get(skey)
        stash = self._stash.get(skey) if self.config.stash else None
        self._migrate_session(
            skey, stash=stash,
            exclude=() if home is None else {home}, reason="death")

    def _migrate_session(self, skey: tuple, stash: Optional[dict],
                         exclude, reason: str) -> None:
        from tpudist import telemetry

        target, _ = self._pick(None, None, exclude=exclude)
        ok = False
        if target is not None and stash is not None:
            tenant, session = skey
            try:
                ok = target.server.adopt_session(tenant, session, stash)
            except Exception:
                ok = False
        if ok:
            self._session_home[skey] = target.index
            self.migrations += 1
            telemetry.event("session_migrated", to_replica=target.index,
                            migrate_reason=reason, ok=True)
        else:
            # no stash / no survivor / tier refused: the session's next
            # turn re-prefills fresh wherever routing lands it —
            # degraded, never wrong, never hung
            self._session_home.pop(skey, None)
            telemetry.event("session_migrated", ok=False,
                            migrate_reason=reason)

    # -- accounting ---------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "replicas": len(self._replicas),
                "replicas_up": len(self._ups()),
                "routed": self.routed,
                "routes_by_kind": dict(self.routes_by_kind),
                "per_replica": [r.routed for r in self._replicas],
                "spills": self.spills,
                "retries": self.retries,
                "migrations": self.migrations,
                "replica_deaths": self.replica_deaths,
                "lost": self.lost,
                "inflight": len(self._inflight),
                "sessions_homed": len(self._session_home),
                "stash_entries": len(self._stash),
                "ticks": self._ticks,
                "errors": self.errors,
            }
