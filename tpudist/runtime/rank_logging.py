"""Rank-aware console observability.

Parity with the reference's rank-prefixed prints of world size / hostname /
device count / seed / backend (``demo.py:51-63``) and rank-0-only tqdm
(``demo.py:91-92``).
"""

from __future__ import annotations

import functools
import socket
from typing import Callable

import jax


def rank_print(*args, **kwargs) -> None:
    """Print prefixed with ``[rank r/w]``."""
    prefix = f"[rank {jax.process_index()}/{jax.process_count()}]"
    print(prefix, *args, **kwargs, flush=True)


def rank_zero_only(fn: Callable) -> Callable:
    """Run ``fn`` only on process 0 (wandb.init / tqdm discipline,
    ``demo.py:76-78,91-92``)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if jax.process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapper


def describe_runtime(ctx=None, seed=None) -> None:
    """The ``demo.py:51-63`` startup banner, TPU edition."""
    rank_print(
        f"host={socket.gethostname()} "
        f"local_devices={jax.local_device_count()} "
        f"global_devices={jax.device_count()} "
        f"platform={jax.devices()[0].platform} "
        + (f"launch={ctx.launch_source} " if ctx is not None else "")
        + (f"seed={seed}" if seed is not None else "")
    )
