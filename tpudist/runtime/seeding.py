"""Seeding discipline.

The reference adds the rank to the user seed so each process gets distinct
randomness (``config.seed += dist.get_rank()``, ``demo.py:59-60``) and draws a
random seed when none is given (``argument_parser.py:18``).  In JAX the
idiomatic form is a single base PRNG key folded with the process index; model
init uses the *base* key on every process (so replicated params are bit-
identical without a broadcast — DDP gets this by broadcasting from rank 0
instead), while data/dropout keys use the folded key.
"""

from __future__ import annotations

import secrets
from typing import Optional

import jax


def draw_seed() -> int:
    """Random 32-bit seed, mirroring ``random.randint(0, 2**32-1)`` in
    ``argument_parser.py:18``."""
    return secrets.randbits(32)


def per_process_seed(base_seed: Optional[int], process_id: Optional[int] = None) -> int:
    """``base_seed + rank`` (``demo.py:59-60``)."""
    if base_seed is None:
        base_seed = draw_seed()
    if process_id is None:
        process_id = jax.process_index()
    return base_seed + process_id


def fold_in_process(key: jax.Array, process_id: Optional[int] = None) -> jax.Array:
    """Fold the process index into a PRNG key — the JAX-native analog of
    per-rank seeding."""
    if process_id is None:
        process_id = jax.process_index()
    return jax.random.fold_in(key, process_id)
