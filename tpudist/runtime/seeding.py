"""Seeding discipline.

The reference adds the rank to the user seed so each process gets distinct
randomness (``config.seed += dist.get_rank()``, ``demo.py:59-60``) and draws a
random seed when none is given (``argument_parser.py:18``).  In JAX the
idiomatic form is a single base PRNG key folded with the process index; model
init uses the *base* key on every process (so replicated params are bit-
identical without a broadcast — DDP gets this by broadcasting from rank 0
instead), while data/dropout keys use the folded key.
"""

from __future__ import annotations

import secrets
from typing import Optional

import jax
import numpy as np


def draw_seed() -> int:
    """Random 32-bit seed, mirroring ``random.randint(0, 2**32-1)`` in
    ``argument_parser.py:18``."""
    return secrets.randbits(32)


def resolve_shared_seed(seed: Optional[int]) -> int:
    """One seed the whole job agrees on.

    When the user passes no seed, the reference draws one per process and
    relies on DDP's rank-0 parameter broadcast to re-converge the models
    (``argument_parser.py:18`` + DDP wrap).  There is no such compensating
    broadcast in the replicated-init design, so the random draw itself must
    be agreed on: rank 0 draws, everyone else receives it over the
    coordination service.  Must be called *after* ``runtime.initialize``.
    """
    if seed is not None:
        return seed
    if jax.process_count() == 1:
        return draw_seed()
    from jax.experimental import multihost_utils

    local = np.asarray(draw_seed(), dtype=np.int64)
    return int(multihost_utils.broadcast_one_to_all(local))


def per_process_seed(base_seed: Optional[int], process_id: Optional[int] = None) -> int:
    """``base_seed + rank`` (``demo.py:59-60``)."""
    if base_seed is None:
        base_seed = draw_seed()
    if process_id is None:
        process_id = jax.process_index()
    return base_seed + process_id


def fold_in_process(key: jax.Array, process_id: Optional[int] = None) -> jax.Array:
    """Fold the process index into a PRNG key — the JAX-native analog of
    per-rank seeding."""
    if process_id is None:
        process_id = jax.process_index()
    return jax.random.fold_in(key, process_id)
