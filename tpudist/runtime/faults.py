"""Deterministic fault injection for chaos testing the tpudist runtime.

The failure paths (preemption saves, ``tpurun`` restarts, degraded-mode
checkpoint restore, init retry) are only as trustworthy as their tests,
and none of them can be exercised without a way to *cause* the failure on
demand.  This registry injects faults at four seams — the train loop, the
host fabric, checkpoint saves, and distributed init — driven by one env
var so chaos tests (and operators reproducing an incident) need no code
changes::

    TPUDIST_FAULT=kill@step:7,rank:1        # SIGKILL rank 1 at step 7
    TPUDIST_FAULT=sigterm@step:5            # preemption drill at step 5
    TPUDIST_FAULT=ckpt_corrupt@step:10      # garble the save at/after step 10
    TPUDIST_FAULT=host_delay@ms:500         # stall every host collective 500ms
    TPUDIST_FAULT=init_fail@attempts:2      # fail the first 2 init attempts
    TPUDIST_FAULT=ckpt_corrupt@step:16;kill@step:19   # compose with ';'
    TPUDIST_FAULT=serve_worker_kill@call:8,pool:1,worker:0
                                            # kill decode worker 0 at its
                                            # 8th engine call (disagg loop)
    TPUDIST_FAULT=handoff_corrupt@nth:2     # garble the 2nd serialized
                                            # KV-handoff package in flight
    TPUDIST_FAULT=host_tier_corrupt@nth:1   # garble the 1st package PARKED
                                            # in the host-RAM KV tier
    TPUDIST_FAULT=replica_kill@nth:1        # kill fleet replica 1's engine
                                            # loop at the router's next
                                            # probe tick (tick:K delays it)
    TPUDIST_FAULT=draft_swap_corrupt@nth:1  # garble the 1st distillation
                                            # candidate's params pre-gate
                                            # (held-out eval must reject)

Grammar: ``kind@key:int[,key:int][;kind@...]``.  Common keys: ``rank``
restricts the fault to one process (default: all); ``attempt`` fires only
on that ``TPUDIST_RESTART_COUNT`` (default 0 for the one-shot kinds, so a
``tpurun``-restarted group is NOT re-killed — the whole point of the
kill→restart→resume chaos test).

Cost when disarmed (production): every injection point is one module
attribute load and a ``None`` check — no parsing, no env reads.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import sys
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional

ENV_VAR = "TPUDIST_FAULT"

# kind -> (required params, allowed params)
_SCHEMA: Dict[str, tuple] = {
    "kill": ({"step"}, {"step", "rank", "attempt"}),
    "sigterm": ({"step"}, {"step", "rank", "attempt"}),
    "ckpt_corrupt": ({"step"}, {"step", "rank", "attempt"}),
    "host_delay": ({"ms"}, {"ms", "rank"}),
    "init_fail": ({"attempts"}, {"attempts", "rank"}),
    # serve-side chaos (tpudist.serve.disagg): kill a pool worker at its
    # Nth engine call (pool: 0=prefill, 1=decode [default]; worker
    # default 0), or garble the Nth serialized KV-handoff package —
    # recovery drives through the SAME grammar as the training faults.
    "serve_worker_kill": ({"call"}, {"call", "pool", "worker", "rank"}),
    "handoff_corrupt": ({"nth"}, {"nth", "rank"}),
    # host-RAM KV tier (tpudist.serve.host_tier): garble the Nth PARKED
    # package after its digest is stamped — a corrupt parked blob must
    # degrade to a full re-prefill (host_tier_corrupt event), never
    # crash and never import wrong bytes.
    "host_tier_corrupt": ({"nth"}, {"nth", "rank"}),
    # fleet router (tpudist.serve.router): kill the Nth replica's engine
    # loop at router scope — the router's probe tick consults this and
    # hard-stops that replica (its loop raises, in-flight work aborts,
    # /healthz goes 503), driving the SAME failover path a real replica
    # crash would: re-home in-flight lanes onto survivors, resume parked
    # sessions from the router-side stash.  `tick` delays the kill to
    # the router's Nth probe tick (default 1 = the first tick after
    # arming).
    "replica_kill": ({"nth"}, {"nth", "tick", "rank"}),
    # online draft distillation (tpudist.distill): garble the Nth
    # distillation round's CANDIDATE params pre-gate — the held-out
    # eval must reject it and the serving draft stays untouched (a
    # wrong draft can only cost speed, never bytes, but the gate
    # letting one through would quietly regress acceptance).
    "draft_swap_corrupt": ({"nth"}, {"nth", "rank"}),
}


class FaultSpecError(ValueError):
    """Malformed ``TPUDIST_FAULT`` value."""


class TransientInitError(RuntimeError):
    """Injected coordinator-init failure (``init_fail``) — shaped like the
    transient connect errors the bootstrap retry loop exists to absorb."""


@dataclasses.dataclass
class FaultSpec:
    kind: str
    params: Dict[str, int]
    fired: int = 0
    #: events observed by a counting injection point (e.g. serialized
    #: handoff packages seen by ``handoff_corrupt``) — distinct from
    #: ``fired`` so "the Nth occurrence" gating composes with fire-once.
    seen: int = 0

    def param(self, key: str, default: Optional[int] = None) -> Optional[int]:
        return self.params.get(key, default)


def parse(spec: str) -> List[FaultSpec]:
    """Parse the ``TPUDIST_FAULT`` grammar; raises :class:`FaultSpecError`
    on unknown kinds/keys or non-integer values (fail loud: a typo'd chaos
    spec silently doing nothing would defeat the test that armed it)."""
    out: List[FaultSpec] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, sep, rest = part.partition("@")
        kind = kind.strip()
        if kind not in _SCHEMA:
            raise FaultSpecError(
                f"unknown fault kind {kind!r} in {part!r} "
                f"(known: {sorted(_SCHEMA)})")
        required, allowed = _SCHEMA[kind]
        params: Dict[str, int] = {}
        if sep:
            for kv in rest.split(","):
                key, sep2, val = kv.partition(":")
                key = key.strip()
                if not sep2 or key not in allowed:
                    raise FaultSpecError(
                        f"bad param {kv!r} for fault {kind!r} "
                        f"(allowed: {sorted(allowed)})")
                try:
                    params[key] = int(val)
                except ValueError as e:
                    raise FaultSpecError(
                        f"param {key!r} of fault {kind!r} must be an "
                        f"integer, got {val!r}") from e
        missing = required - params.keys()
        if missing:
            raise FaultSpecError(
                f"fault {kind!r} missing required param(s) {sorted(missing)}")
        out.append(FaultSpec(kind=kind, params=params))
    if not out:
        raise FaultSpecError(f"empty fault spec {spec!r}")
    return out


# -- arming -----------------------------------------------------------------

_PLAN: Optional[List[FaultSpec]] = None
_SOURCE: Optional[str] = None  # "env" | "explicit"
_ENV_SPEC: Optional[str] = None  # the env string _PLAN was parsed from


def arm(spec: str) -> List[FaultSpec]:
    """Arm the registry from an explicit spec string (tests)."""
    global _PLAN, _SOURCE, _ENV_SPEC
    _PLAN = parse(spec)
    _SOURCE = "explicit"
    _ENV_SPEC = None
    return _PLAN


def arm_from_env() -> bool:
    """Arm from ``TPUDIST_FAULT`` if set (idempotent; re-parses only when
    the env value changed).  Called by ``run_training`` and
    ``runtime.bootstrap.initialize`` so the grammar works with zero code
    changes in the job.  An explicit :func:`arm` is never clobbered, and an
    unset env var disarms only an env-armed plan."""
    global _PLAN, _SOURCE, _ENV_SPEC
    spec = os.environ.get(ENV_VAR)
    if not spec:
        if _SOURCE == "env":
            disarm()
        return False
    if _SOURCE == "explicit":
        return False
    if _SOURCE == "env" and spec == _ENV_SPEC:
        return True
    _PLAN = parse(spec)
    _SOURCE = "env"
    _ENV_SPEC = spec
    return True


def disarm() -> None:
    global _PLAN, _SOURCE, _ENV_SPEC
    _PLAN = None
    _SOURCE = None
    _ENV_SPEC = None


def armed() -> bool:
    return _PLAN is not None


# -- gating helpers ---------------------------------------------------------

def _restart_count() -> int:
    from tpudist.utils.envutil import env_int

    return env_int("TPUDIST_RESTART_COUNT", 0)


def _current_rank() -> int:
    from tpudist.utils.envutil import env_rank

    rank = env_rank()
    if rank is not None:
        return rank
    if "jax" in sys.modules:  # never import jax just to gate a fault
        try:
            return sys.modules["jax"].process_index()
        except Exception:
            pass
    return 0


def _rank_matches(spec: FaultSpec) -> bool:
    rank = spec.param("rank")
    return rank is None or rank == _current_rank()


def _one_shot_due(spec: FaultSpec, step: int) -> bool:
    """kill/sigterm/ckpt_corrupt: fire once, at the first injection point
    whose step is >= the spec's, on the matching restart attempt/rank."""
    return (
        spec.fired == 0
        and step >= spec.params["step"]
        and spec.param("attempt", 0) == _restart_count()
        and _rank_matches(spec)
    )


def _log(msg: str) -> None:
    print(f"[tpudist.faults] {msg}", file=sys.stderr, flush=True)


# -- injection points -------------------------------------------------------

def inject_step(step: int) -> None:
    """Train-loop injection point (called once per iteration/window)."""
    if _PLAN is None:
        return
    for spec in _PLAN:
        if spec.kind in ("kill", "sigterm") and _one_shot_due(spec, step):
            spec.fired += 1
            signum = signal.SIGKILL if spec.kind == "kill" else signal.SIGTERM
            _log(f"injecting {spec.kind} at step {step} "
                 f"(rank {_current_rank()}, attempt {_restart_count()})")
            # Stamp + flush the telemetry stream first: a SIGKILL gives no
            # second chance, and the merged report joins this marker with
            # the restart gap it causes (lost_restart attribution).
            from tpudist import telemetry

            telemetry.event("fault_injected", fault=spec.kind, step=step)
            telemetry.flush()
            os.kill(os.getpid(), signum)


def inject_host() -> None:
    """Host-fabric injection point (``host_allreduce_sum`` / ``barrier``)."""
    if _PLAN is None:
        return
    for spec in _PLAN:
        if spec.kind == "host_delay" and _rank_matches(spec):
            spec.fired += 1
            time.sleep(spec.params["ms"] / 1000.0)


def inject_init(attempt: int) -> None:
    """Distributed-init injection point: raises :class:`TransientInitError`
    for the first ``attempts`` calls (exercises the bootstrap retry loop).
    ``attempt`` is informational (logged)."""
    if _PLAN is None:
        return
    for spec in _PLAN:
        if (spec.kind == "init_fail" and _rank_matches(spec)
                and spec.fired < spec.params["attempts"]):
            spec.fired += 1
            _log(f"injecting init failure "
                 f"({spec.fired}/{spec.params['attempts']}, "
                 f"attempt {attempt})")
            raise TransientInitError(
                f"injected transient init failure "
                f"{spec.fired}/{spec.params['attempts']}")


def inject_ckpt_save(step: int, step_dir: os.PathLike,
                     wait: Optional[Callable[[], None]] = None) -> bool:
    """Checkpoint-save injection point: after a (possibly async) save of
    ``step``, a due ``ckpt_corrupt`` fault waits for the write to finish
    and garbles the step's payload in place.  Returns whether it fired."""
    if _PLAN is None:
        return False
    for spec in _PLAN:
        if spec.kind == "ckpt_corrupt" and _one_shot_due(spec, step):
            spec.fired += 1
            if wait is not None:
                wait()
            n = corrupt_checkpoint(step_dir)
            _log(f"corrupted checkpoint step {step} "
                 f"({n} files garbled under {os.fspath(step_dir)})")
            from tpudist import telemetry

            telemetry.event("fault_injected", fault="ckpt_corrupt",
                            step=step, files=n)
            return True
    return False


def inject_serve_worker(pool: int, worker: int, ncalls: int) -> bool:
    """Disagg-loop injection point, consulted before every engine
    interaction of pool worker ``(pool, worker)`` (``pool``: 0=prefill,
    1=decode; ``ncalls`` = that worker's cumulative engine-call count).
    Returns True when a due ``serve_worker_kill`` says THIS call must
    die — the serving loop raises in response, driving the SAME
    worker-lost recovery path a real engine failure would."""
    if _PLAN is None:
        return False
    for spec in _PLAN:
        if (spec.kind == "serve_worker_kill" and spec.fired == 0
                and spec.param("pool", 1) == pool
                and spec.param("worker", 0) == worker
                and ncalls >= spec.params["call"]
                and _rank_matches(spec)):
            spec.fired += 1
            _log(f"injecting serve worker kill: pool "
                 f"{'decode' if pool else 'prefill'} worker {worker} at "
                 f"engine call {ncalls}")
            return True
    return False


def inject_handoff(ser: dict) -> bool:
    """Handoff-transport injection point: a due ``handoff_corrupt``
    garbles the ``nth`` serialized KV package in place (first blob
    leaf's leading bytes flipped — the integrity digest then rejects it
    at deserialize, the detectable-wire-corruption scenario).  Returns
    whether it fired."""
    if _PLAN is None:
        return False
    for spec in _PLAN:
        if (spec.kind == "handoff_corrupt" and spec.fired == 0
                and _rank_matches(spec)):
            spec.seen += 1
            if spec.seen < spec.params["nth"]:
                continue
            blob = ser.get("blob")
            if not blob:
                continue
            b, dt, shape = blob[0]
            blob[0] = (bytes(x ^ 0xFF for x in b[:8]) + b[8:], dt, shape)
            spec.fired += 1
            _log(f"corrupted handoff package #{spec.seen} "
                 f"({len(b)} B leaf garbled)")
            from tpudist import telemetry

            telemetry.event("fault_injected", fault="handoff_corrupt",
                            nth=spec.seen)
            return True
    return False


def inject_host_tier(ser: dict) -> bool:
    """Host-tier injection point (:meth:`tpudist.serve.host_tier.
    HostKVTier.put`): a due ``host_tier_corrupt`` garbles the ``nth``
    PARKED serialized package in place, after its digest stamp — the
    resume path's deserialize then detects the mismatch and degrades to
    a full re-prefill instead of importing garbage KV.  Returns whether
    it fired."""
    if _PLAN is None:
        return False
    for spec in _PLAN:
        if (spec.kind == "host_tier_corrupt" and spec.fired == 0
                and _rank_matches(spec)):
            spec.seen += 1
            if spec.seen < spec.params["nth"]:
                continue
            blob = ser.get("blob")
            if not blob:
                continue
            b, dt, shape = blob[0]
            blob[0] = (bytes(x ^ 0xFF for x in b[:8]) + b[8:], dt, shape)
            spec.fired += 1
            _log(f"corrupted parked host-tier package #{spec.seen} "
                 f"({len(b)} B leaf garbled)")
            from tpudist import telemetry

            telemetry.event("fault_injected", fault="host_tier_corrupt",
                            nth=spec.seen)
            return True
    return False


def inject_replica_kill(tick: int) -> Optional[int]:
    """Fleet-router injection point, consulted once per router probe
    tick (``tick`` = the router's cumulative tick count).  A due
    ``replica_kill`` fires once and returns the replica index to
    hard-stop (``nth``); ``None`` otherwise.  The router responds by
    killing that replica's engine loop — the in-process twin of a
    replica host dying — and its probe/failover machinery takes it from
    there with zero test-only seams."""
    if _PLAN is None:
        return None
    for spec in _PLAN:
        if (spec.kind == "replica_kill" and spec.fired == 0
                and tick >= spec.param("tick", 1)
                and _rank_matches(spec)):
            spec.fired += 1
            idx = spec.params["nth"]
            _log(f"injecting replica_kill: replica {idx} at router "
                 f"tick {tick}")
            from tpudist import telemetry

            telemetry.event("fault_injected", fault="replica_kill",
                            replica=idx, tick=tick)
            return idx
    return None


def inject_draft_swap(round_idx: int) -> bool:
    """Distillation-round injection point (:func:`tpudist.distill.swap.
    maybe_corrupt_candidate`), consulted once per round with the
    candidate in hand: a due ``draft_swap_corrupt`` fires on its
    ``nth`` offered candidate and returns True — the CALLER garbles
    the candidate's params (this module stays jax-free), and the
    held-out gate must then reject it (the chaos test's assertion).
    ``round_idx`` is informational (logged)."""
    if _PLAN is None:
        return False
    for spec in _PLAN:
        if (spec.kind == "draft_swap_corrupt" and spec.fired == 0
                and _rank_matches(spec)):
            spec.seen += 1
            if spec.seen < spec.params["nth"]:
                continue
            spec.fired += 1
            _log(f"corrupting draft-swap candidate #{spec.seen} "
                 f"(distill round {round_idx})")
            from tpudist import telemetry

            telemetry.event("fault_injected", fault="draft_swap_corrupt",
                            nth=spec.seen, round=round_idx)
            return True
    return False


def corrupt_checkpoint(step_dir: os.PathLike) -> int:
    """Garble every payload file under an Orbax step directory, keeping the
    step *listed* (its commit metadata survives) so restore has to detect
    the corruption the hard way — the scenario degraded-mode restore
    exists for.  Returns the number of files garbled."""
    root = Path(step_dir)
    n = 0
    for f in sorted(root.rglob("*")):
        if not f.is_file() or "_CHECKPOINT_METADATA" in f.name:
            continue
        try:
            f.write_bytes(b"tpudist-fault-injected-corruption")
            n += 1
        except OSError:
            pass
    return n
