"""Hang watchdog: convert a wedged step into a fast, diagnosable restart.

A hung collective (one rank dead in a way the coordination service hasn't
noticed, a deadlocked host callback, a wedged device tunnel) leaves every
process alive but advancing nothing — the worst failure mode on a managed
allocation, because ``tpurun``'s restart loop only reacts to *exits* and
the scheduler only reclaims the job at its own (hour-scale) timeout.

The watchdog is a daemon thread the train loop pets once per iteration
(or scan window).  When no pet arrives within the stall deadline it:

1. dumps every thread's stack into the structured crash-record file
   (``tpudist.utils.record`` — the same file ``tpurun`` surfaces as the
   first failure, so the hang is *diagnosable* post-mortem), and
2. hard-aborts the process with :data:`WATCHDOG_EXIT_CODE` via
   ``os._exit`` — deliberately not ``sys.exit``, which a wedged main
   thread would never run — so the agent's whole-group restart re-admits
   the job instead of burning the allocation.

Arm it via ``TrainLoopConfig.watchdog_timeout_s`` or the
``TPUDIST_WATCHDOG_S`` env var (unset/<=0 = disabled).  Size the deadline
above the slowest legitimate gap between pets — on the first iteration
that gap includes XLA compilation, which ``first_deadline_s`` can extend
separately.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
import weakref
from typing import Callable, Dict, Optional

#: Process exit code on a stall abort (the ``timeout(1)`` convention, so
#: operators' existing "what does 124 mean" reflex applies).
WATCHDOG_EXIT_CODE = 124

TIMEOUT_ENV = "TPUDIST_WATCHDOG_S"


def timeout_from_env(default: Optional[float] = None) -> Optional[float]:
    """Resolve the stall deadline from ``TPUDIST_WATCHDOG_S``; unset,
    unparseable, or <= 0 means disabled (returns ``default``)."""
    from tpudist.utils.envutil import env_positive_float

    return env_positive_float(TIMEOUT_ENV, default)


#: Running watchdogs, for the ``/healthz`` freshness check
#: (:mod:`tpudist.telemetry.statusz`): weak so a dropped watchdog never
#: pins itself in the health report.
_LIVE: "weakref.WeakSet[Watchdog]" = weakref.WeakSet()


def freshness() -> Dict[str, dict]:
    """Heartbeat freshness of every RUNNING watchdog: seconds since the
    last pet vs the current stall deadline.  Empty when none is armed —
    the health check treats that as vacuously healthy."""
    out: Dict[str, dict] = {}
    for dog in list(_LIVE):
        if dog._thread is None:
            continue  # built but not started / already stopped
        age = time.monotonic() - dog._last
        deadline = dog._deadline()
        out[dog.name] = {
            "age_s": round(age, 3),
            "deadline_s": round(deadline, 3),
            "fresh": age <= deadline,
        }
    return out


def dump_all_stacks() -> Dict[str, str]:
    """Formatted stacks of every live thread, keyed by thread name."""
    frames = sys._current_frames()
    out: Dict[str, str] = {}
    for t in threading.enumerate():
        frame = frames.get(t.ident)
        if frame is None:
            continue
        label = f"{t.name} (ident {t.ident}{', daemon' if t.daemon else ''})"
        out[label] = "".join(traceback.format_stack(frame))
    return out


class Watchdog:
    """Heartbeat-or-abort supervisor for a loop that must keep advancing.

    ``abort`` is injectable for tests; production uses ``os._exit`` (see
    module docstring for why graceful shutdown is the wrong move here).
    """

    def __init__(
        self,
        stall_timeout_s: float,
        *,
        name: str = "train_loop",
        poll_interval_s: Optional[float] = None,
        first_deadline_s: Optional[float] = None,
        abort: Optional[Callable[[int], None]] = None,
    ):
        if stall_timeout_s <= 0:
            raise ValueError(f"stall_timeout_s must be > 0, got {stall_timeout_s}")
        self.stall_timeout_s = float(stall_timeout_s)
        self.name = name
        self._poll = poll_interval_s or min(1.0, self.stall_timeout_s / 4)
        # extra slack before the FIRST pet only (covers XLA compile)
        self._first_extra = max(0.0, (first_deadline_s or 0.0) - self.stall_timeout_s)
        self._abort = abort if abort is not None else os._exit
        self._stop = threading.Event()
        self._petted = False
        self._last = time.monotonic()
        self._thread: Optional[threading.Thread] = None
        self.stalled = False  # post-mortem flag for injectable-abort tests

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self._stop.clear()  # restartable: stop() leaves the event set
        self._last = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name=f"tpudist-watchdog[{self.name}]", daemon=True
        )
        self._thread.start()
        _LIVE.add(self)  # visible to the /healthz freshness check
        return self

    def pet(self) -> None:
        """Heartbeat: the supervised loop made progress.

        Order matters: ``_last`` is refreshed BEFORE ``_petted`` collapses
        the first-deadline slack, so a supervisor that observes the tight
        deadline necessarily also observes the fresh timestamp (the
        reverse order could pair a collapsed deadline with a stale
        ``_last`` and spuriously abort a healthy process)."""
        self._last = time.monotonic()
        self._petted = True

    def stop(self) -> None:
        self._stop.set()
        _LIVE.discard(self)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- supervisor thread --------------------------------------------------

    def _deadline(self) -> float:
        extra = 0.0 if self._petted else self._first_extra
        return self.stall_timeout_s + extra

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            # Deadline snapshot FIRST, timestamp second (mirror of pet()'s
            # write order): a pet racing this read can only make the
            # deadline larger than needed or the stall smaller — never a
            # collapsed deadline judged against a stale timestamp.
            deadline = self._deadline()
            stalled_for = time.monotonic() - self._last
            if stalled_for > deadline:
                self._on_stall(stalled_for, deadline)
                return

    def _on_stall(self, stalled_for: float, deadline: float) -> None:
        self.stalled = True
        # Tag the stall in the telemetry stream and flush BEFORE the
        # abort: os._exit skips every atexit/buffer path, and the merged
        # report needs this event to attribute the restart's lost time.
        # Best-effort with a hard deadline — the wedged thread this abort
        # exists to kill may itself hold the telemetry write lock (hung
        # filesystem), and blocking here would defeat the whole watchdog.
        from tpudist import telemetry

        def _stamp():
            telemetry.event("watchdog_stall", watchdog=self.name,
                            stalled_for_s=round(stalled_for, 3),
                            deadline_s=round(deadline, 3))
            telemetry.flush()

        stamp = threading.Thread(target=_stamp, daemon=True,
                                 name="tpudist-watchdog-telemetry")
        stamp.start()
        stamp.join(2.0)
        message = (
            f"watchdog: no heartbeat from '{self.name}' for "
            f"{stalled_for:.1f}s (deadline {deadline:.1f}s) — "
            f"dumping stacks and aborting with exit {WATCHDOG_EXIT_CODE} "
            f"so the launcher can restart the group"
        )
        stacks = dump_all_stacks()
        # Same structured record the launcher surfaces for crashes, written
        # atomically (a torn record would be silently skipped).
        from tpudist.utils.record import write_error_record

        write_error_record({
            "exc_type": "WatchdogStall",
            "message": message,
            "traceback": "\n".join(
                f"--- {label} ---\n{stack}" for label, stack in stacks.items()
            ),
            "stacks": stacks,
            "stall_timeout_s": self.stall_timeout_s,
            "stalled_for_s": stalled_for,
        })
        print(f"[tpudist.watchdog] {message}", file=sys.stderr, flush=True)
        for label, stack in stacks.items():
            print(f"[tpudist.watchdog] --- {label} ---\n{stack}",
                  file=sys.stderr, flush=True)
        self._abort(WATCHDOG_EXIT_CODE)


def from_config(timeout_s: Optional[float] = None, **kwargs) -> Optional[Watchdog]:
    """Build (not start) a watchdog from an explicit timeout or the env;
    ``None`` when disabled — callers guard each ``pet()`` on that."""
    t = timeout_s if timeout_s is not None else timeout_from_env()
    if t is None or t <= 0:
        return None
    return Watchdog(t, **kwargs)
