from tpudist.runtime.bootstrap import (  # noqa: F401
    ProcessContext,
    resolve_process_context,
    initialize,
    shutdown,
)
from tpudist.runtime.compilation_cache import enable_compilation_cache  # noqa: F401
from tpudist.runtime.mesh import (  # noqa: F401
    MeshConfig,
    make_hybrid_mesh,
    make_mesh,
)
from tpudist.runtime.seeding import (  # noqa: F401
    per_process_seed,
    fold_in_process,
    resolve_shared_seed,
)
from tpudist.runtime.rank_logging import rank_print, rank_zero_only, describe_runtime  # noqa: F401
from tpudist.runtime.watchdog import (  # noqa: F401
    WATCHDOG_EXIT_CODE,
    Watchdog,
)
from tpudist.runtime import faults  # noqa: F401
