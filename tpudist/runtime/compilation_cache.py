"""Persistent XLA compilation cache wiring.

Compilation is the expensive, failure-prone step in this environment:
over the remote-device tunnel a single Pallas kernel compile has been
observed to hang for 37+ minutes (BASELINE.md round-4 log), and every
process — bench, demo, sweep agent — otherwise re-pays every compile
from scratch.  JAX ships a persistent on-disk cache keyed by HLO hash
(``jax_compilation_cache_dir``); enabling it means a compile that
succeeded ONCE this machine-lifetime is never re-run, so a retry after
a tunnel wedge skips straight to execution of everything previously
compiled.

``enable_compilation_cache()`` is called from ``initialize()`` (the
runtime bootstrap every entry point goes through) and from the bench
harnesses.  Controls:

- ``TPUDIST_COMPILATION_CACHE=off`` disables it;
- ``TPUDIST_COMPILATION_CACHE=<dir>`` relocates it (e.g. a fast scratch
  filesystem on a pod, or a per-job dir a SLURM epilogue clears);
- default location: ``~/.cache/tpudist/xla-cache``.

The min-compile-time floor is lowered to 0.5 s so the flash-attention
kernels (fast to compile on CPU, slow over the tunnel) are cached on
every backend.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

_OFF_VALUES = ("0", "off", "false", "disabled", "no")


def _cpu_platform_selected() -> bool:
    """True when this process is pinned to the CPU backend (env var or
    jax.config) — WITHOUT initializing any backend."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return True
    try:
        import jax

        return (jax.config.jax_platforms or "").strip().lower() == "cpu"
    except Exception:
        return False


def enable_compilation_cache(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at a writable directory.

    Returns the directory in use, or None when disabled (by env or
    because jax.config rejects the options — old jax).  Safe to call
    repeatedly and before/after backend init; compiled-executable reuse
    starts with the next compile either way.
    """
    env = os.environ.get("TPUDIST_COMPILATION_CACHE", "")
    if env.lower() in _OFF_VALUES:
        return None
    if not env and path is None and _cpu_platform_selected():
        # Default-on only for accelerator platforms: the cache exists to
        # avoid re-paying TUNNEL compiles.  XLA:CPU AOT entries are
        # feature-set-sensitive (observed: entries compiled with
        # +prefer-no-scatter warn of possible SIGILL when loaded under a
        # different cpu client config), and CPU compiles are cheap —
        # opt in explicitly via TPUDIST_COMPILATION_CACHE=<dir> if wanted.
        return None
    target = path or env or str(
        Path(os.path.expanduser("~")) / ".cache" / "tpudist" / "xla-cache")
    try:
        Path(target).mkdir(parents=True, exist_ok=True)
    except OSError:
        return None  # unwritable home (containers) — run uncached
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", target)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        return None
    return target
