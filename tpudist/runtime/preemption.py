"""Preemption-safe shutdown: SIGTERM → checkpoint at a step boundary.

SLURM (and every cloud TPU scheduler) delivers SIGTERM ahead of a
preemption/requeue; the reference's jobs simply died and its launcher
provisioned checkpoint directories it never wrote (SURVEY.md §5.4).
tpudist closes the loop: install the handler once per process, and
``run_training`` (``tpudist/train/loop.py``) checks the flag at its sync
boundaries — when ANY process was signaled, all processes save a final
checkpoint at the same boundary (meta carries ``preempted: true``), tear
down in the reference's ordering, and return.  A later run with
``--resume`` picks up at the exact iteration (the loop's deterministic
fast-forward).

Any-semantics is deliberate, and skew-tolerant: SLURM delivers SIGTERM to
ranks at slightly different times, and an Orbax save is collective —
everyone must save at the SAME step.  ``check_all()`` OR-reduces the
local flags over the host fabric (Gloo-group analog), so the first
boundary after the first signal lands the whole job on one common save.

Usage (the demos and Trainer do this automatically)::

    from tpudist.runtime import preemption
    preemption.install()
    run_training(..., ckpt=manager)   # loop handles the rest
"""

from __future__ import annotations

import signal
import threading
from typing import Iterable

import numpy as np

_flag = threading.Event()
_installed: list = []  # (signum, previous handler) for uninstall/tests
_last_run_preempted = False  # sticky: survives reset() (callers consult it)


def install(signals: Iterable[int] = (signal.SIGTERM,)) -> bool:
    """Install the preemption handler (idempotent).  Returns whether
    anything NEW was installed — the caller that got True owns the matching
    :func:`reset` (``run_training`` restores handlers on exit so SIGTERM
    terminates the process again once training is done).

    CPython restricts ``signal.signal`` to the main thread; called off it
    (Trainer under a threaded test runner), this degrades to a no-op
    returning ``False`` with a one-line warning — the caller still trains,
    just without preemption saves — instead of crashing with ValueError."""
    new = []
    for signum in signals:
        if any(s == signum for s, _ in _installed):
            continue
        try:
            prev = signal.signal(signum, _handle)
        except ValueError:
            # Roll back what THIS call installed: a False return means the
            # caller will never own reset(), so nothing may stay behind.
            for s, p in reversed(new):
                try:
                    signal.signal(s, p)
                except (ValueError, OSError):
                    pass
                _installed.remove((s, p))
            import warnings

            warnings.warn(
                "tpudist.runtime.preemption.install() could not install a "
                "signal handler (not on the main thread, or an invalid "
                "signal); preemption-save handling disabled for this run",
                RuntimeWarning, stacklevel=2,
            )
            return False
        _installed.append((signum, prev))
        new.append((signum, prev))
    return bool(new)


def _handle(signum, frame):  # noqa: ARG001
    _flag.set()


def requested() -> bool:
    """This process received a preemption signal."""
    return _flag.is_set()


def check_all() -> bool:
    """True when ANY process was signaled — reduced over the host fabric
    so every rank takes the same save-and-exit decision at the same
    boundary (single-process: just the local flag)."""
    import jax

    if jax.process_count() == 1:
        return _flag.is_set()
    from tpudist.comm.collectives import host_allreduce_sum

    total = host_allreduce_sum(np.float64(1.0 if _flag.is_set() else 0.0))
    return float(total) > 0.0


def note_run_preempted() -> None:
    """Called by the train loop when it exits early on preemption — the
    sticky record callers consult AFTER the loop returns (reset() clears
    the live flag but not this)."""
    global _last_run_preempted
    _last_run_preempted = True


def last_run_preempted() -> bool:
    """Did the most recent training loop exit early on preemption?  A
    partially-trained run must be distinguishable from a completed one
    (the loop's return signature carries no status)."""
    return _last_run_preempted


def clear_last_run_preempted() -> None:
    global _last_run_preempted
    _last_run_preempted = False


def reset() -> None:
    """Clear the live flag and restore previous handlers (loop exit,
    tests).  The sticky :func:`last_run_preempted` record is NOT cleared."""
    _flag.clear()
    while _installed:
        signum, prev = _installed.pop()
        try:
            signal.signal(signum, prev)
        except (ValueError, OSError):  # non-main thread / closed interp
            pass
