"""Device-mesh construction.

The reference binds one CUDA device per rank (``torch.cuda.set_device``,
``demo.py:66``) and leaves topology to NCCL.  The TPU-native design is the
inverse: one global :class:`jax.sharding.Mesh` over *all* devices in the job,
with named axes carrying the parallelism meaning:

- ``data``  — data parallelism (replaces DDP's gradient all-reduce group)
- ``stage`` — pipeline parallelism (generalizes the 2-stage vertical split of
  ``demo_one_model_multi_gpu.py:17-42``)
- ``seq``   — sequence/context parallelism (ring attention)
- ``model`` — tensor parallelism (the TPU-idiomatic way to put one model on
  several chips)

Expert parallelism routes over ``model`` (one expert group per tensor-axis
slice, Switch-Transformer style); see ``tpudist.parallel.moe``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_STAGE = "stage"
AXIS_SEQ = "seq"
AXIS_MODEL = "model"
ALL_AXES = (AXIS_DATA, AXIS_STAGE, AXIS_SEQ, AXIS_MODEL)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Axis sizes; ``-1`` means "absorb all remaining devices"."""

    data: int = -1
    stage: int = 1
    seq: int = 1
    model: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        sizes = {"data": self.data, "stage": self.stage, "seq": self.seq, "model": self.model}
        unknown = [k for k, v in sizes.items() if v == -1]
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if len(unknown) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if unknown:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[unknown[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} does not cover {n_devices} devices"
            )
        return MeshConfig(**sizes)

    def axis_sizes(self) -> dict:
        return {"data": self.data, "stage": self.stage, "seq": self.seq, "model": self.model}


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Sequence[str] = ALL_AXES,
) -> Mesh:
    """Build the global mesh.

    Axis order is ``(data, stage, seq, model)`` — outermost axis maps to the
    slowest-varying device dimension so that ``model`` (the most bandwidth-
    hungry axis) lands on adjacent chips and rides ICI, while ``data`` may
    span hosts over DCN.
    """
    if devices is None:
        devices = jax.devices()
    config = (config or MeshConfig()).resolve(len(devices))
    sizes = [config.axis_sizes()[a] for a in axis_names]
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(axis_names))


def make_hybrid_mesh(
    config: Optional[MeshConfig] = None,
    *,
    axis_names: Sequence[str] = ALL_AXES,
    force_granules: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Multi-host mesh with DCN/ICI-aware device placement.

    On a multi-host pod the two fabrics differ by ~an order of magnitude:
    ICI links chips within a slice, DCN links hosts.  This helper assigns
    the ``data`` axis (bandwidth-light: one gradient all-reduce per step)
    across hosts over DCN and keeps ``stage``/``seq``/``model`` (bandwidth-
    hungry: activations every layer) inside a host on ICI, via
    ``mesh_utils.create_hybrid_device_mesh`` — the scaling-book layout.

    Requires the ``data`` axis size to be divisible by the process count;
    single-process jobs fall back to :func:`make_mesh` (nothing to place).

    ``force_granules=k`` overrides granule detection with k contiguous
    pseudo-hosts — the single-process validation path (the driver's
    ``dryrun_multichip`` runs one process, where every device reports
    ``process_index == 0`` and nothing would otherwise exercise the
    hybrid layout).  The placement contract is the same: the data axis
    iterates granules in its OUTER positions (granule-major), so every
    non-data axis stays inside one granule.
    """
    if devices is None:
        devices = jax.devices()
    # distinct indices, not max+1: a caller-passed subset may exclude
    # lower-indexed processes (matches the n_slices counting below)
    n_procs = len({d.process_index for d in devices})
    config = (config or MeshConfig()).resolve(len(devices))
    if force_granules is not None and n_procs > 1:
        raise ValueError(
            "force_granules is the single-process validation path; "
            f"this job has {n_procs} processes — real granules are "
            "detected from process/slice indices")
    if n_procs == 1 and force_granules is None:
        return make_mesh(config, axis_names=axis_names, devices=devices)

    # Granule = what DCN separates: distinct TPU slices when present
    # (multi-slice pods), else processes (multi-host single slice, or the
    # CPU test rig).
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    process_is_granule = n_slices <= 1
    n_granules = (force_granules if force_granules is not None
                  else n_procs if process_is_granule else n_slices)

    sizes = config.axis_sizes()
    if sizes["data"] % n_granules != 0:
        raise ValueError(
            f"hybrid mesh: data axis {sizes['data']} not divisible by "
            f"{n_granules} DCN granules (the data axis is the DCN axis)"
        )
    dcn_shape = [1] * len(axis_names)
    ici_shape = [sizes[a] for a in axis_names]
    data_pos = list(axis_names).index(AXIS_DATA)
    dcn_shape[data_pos] = n_granules
    ici_shape[data_pos] = sizes["data"] // n_granules
    if force_granules is not None and n_procs == 1:
        # Pseudo-host grouping: contiguous device blocks stand in for
        # hosts; per-granule ICI blocks concatenate along the data axis
        # (granule-major — exactly create_hybrid_device_mesh's layout).
        if len(devices) % n_granules != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible into "
                f"{n_granules} granules")
        per = len(devices) // n_granules
        blocks = [
            np.asarray(devices[i * per:(i + 1) * per]).reshape(ici_shape)
            for i in range(n_granules)
        ]
        dev_array = np.concatenate(blocks, axis=data_pos)
    else:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices,
            process_is_granule=process_is_granule,
        )
    return Mesh(dev_array, axis_names=tuple(axis_names))


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D all-data mesh — the DDP-equivalent default (SURVEY.md §2.4)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), axis_names=(AXIS_DATA,))


def data_model_mesh(
    model_size: int = 2, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """2-D ``('data','model')`` mesh for the one-model-multi-chip demo
    (parity with ``demo_one_model_multi_gpu.py``'s 2-GPU-per-process shape)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % model_size != 0:
        raise ValueError(f"{n} devices not divisible by model axis {model_size}")
    dev_array = np.asarray(devices).reshape(n // model_size, model_size)
    return Mesh(dev_array, axis_names=(AXIS_DATA, AXIS_MODEL))
