"""Process bootstrap: the rank/world-size contract and distributed init.

This is the TPU-native replacement for the reference's dual bootstrap paths
(``demo.py:19-73``): torchrun env vars (``WORLD_SIZE``/``LOCAL_WORLD_SIZE``/
``LOCAL_RANK``/``RANK``), raw-scheduler env vars (``SLURM_PROCID`` or
``NODE_RANK * TASKS_PER_NODE + SLURM_LOCALID``, ``demo.py:36-41``), and the
MPI bootstrap (``demo_assume_started_with_mpiexec.py:29-50``).  All three
rendezvous modes of the reference (c10d store / explicit tcp:// / env seeded
by MPI broadcast, SURVEY.md §5.8) collapse onto one primitive here:
``jax.distributed.initialize(coordinator_address, num_processes, process_id)``.

Resolution priority (first match wins):

1. explicit arguments to :func:`resolve_process_context`
2. tpudist launcher contract: ``TPUDIST_COORDINATOR`` / ``TPUDIST_NUM_PROCESSES``
   / ``TPUDIST_PROCESS_ID`` (set by ``launch/tpurun``)
3. torchrun-style contract: ``MASTER_ADDR``/``MASTER_PORT`` + ``RANK`` +
   ``WORLD_SIZE`` (and ``LOCAL_RANK``/``LOCAL_WORLD_SIZE``)
4. SLURM contract: ``MASTER_ADDR``/``MASTER_PORT`` + ``WORLD_SIZE`` +
   (``NODE_RANK``×``TASKS_PER_NODE``+``SLURM_LOCALID`` when ``use_node_rank``,
   else ``SLURM_PROCID``) — the ``demo.py:35-49`` contract verbatim
5. OpenMPI/PMI contract: ``OMPI_COMM_WORLD_RANK``/``OMPI_COMM_WORLD_SIZE``
   (+ optional mpi4py hostname/port broadcast, see
   ``tpudist.runtime.mpi_bootstrap``)
6. single-process default (no distributed init)
"""

from __future__ import annotations

import dataclasses
import os
import random
import socket
import sys
import time
from typing import Callable, Optional


class BootstrapError(RuntimeError):
    """A launch contract was detected but is incomplete/inconsistent."""


@dataclasses.dataclass(frozen=True)
class ProcessContext:
    """Everything a rank needs to know about its place in the job."""

    process_id: int
    num_processes: int
    coordinator_address: Optional[str]  # "host:port" or None for single-process
    local_rank: int
    local_world_size: int
    launch_source: str  # explicit | tpudist | torchrun | slurm | mpi | single

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def _env_int(name: str, default: Optional[int] = None) -> Optional[int]:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError as e:
        raise BootstrapError(f"env var {name}={v!r} is not an integer") from e


def _require(name: str) -> str:
    v = os.environ.get(name)
    if v is None or v == "":
        # Mirrors the reference's fail-fast env checks (demo.py:31-33,47-48).
        raise BootstrapError(
            f"required env var {name} is not set for this launch contract"
        )
    return v


def _coordinator_from_master_env(default_port: int = 2345) -> str:
    addr = _require("MASTER_ADDR")
    port = _env_int("MASTER_PORT", default_port)
    return f"{addr}:{port}"


def resolve_process_context(
    *,
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
    coordinator_address: Optional[str] = None,
    use_node_rank: bool = False,
) -> ProcessContext:
    """Resolve (process_id, num_processes, coordinator) from args or env.

    ``use_node_rank`` mirrors the reference's ``--use_node_rank`` flag
    (``argument_parser.py:16-19``, consumed at ``demo.py:38-41``).
    """
    if num_processes is not None:
        if process_id is None:
            raise BootstrapError("explicit num_processes requires explicit process_id")
        if num_processes > 1 and coordinator_address is None:
            raise BootstrapError(
                "explicit multi-process launch requires coordinator_address"
            )
        return ProcessContext(
            process_id=process_id,
            num_processes=num_processes,
            coordinator_address=coordinator_address,
            local_rank=_env_int("LOCAL_RANK", 0) or 0,
            local_world_size=_env_int("LOCAL_WORLD_SIZE", 1) or 1,
            launch_source="explicit",
        )

    env = os.environ
    # 2. tpudist launcher contract.
    if "TPUDIST_NUM_PROCESSES" in env:
        n = _env_int("TPUDIST_NUM_PROCESSES")
        pid = _env_int("TPUDIST_PROCESS_ID")
        if n is None:
            raise BootstrapError("TPUDIST_NUM_PROCESSES is set but empty")
        if pid is None:
            raise BootstrapError("TPUDIST_NUM_PROCESSES set but TPUDIST_PROCESS_ID missing")
        coord = env.get("TPUDIST_COORDINATOR")
        if n > 1 and not coord:
            raise BootstrapError("TPUDIST_COORDINATOR required for multi-process launch")
        return ProcessContext(
            process_id=pid,
            num_processes=n,
            coordinator_address=coord,
            local_rank=_env_int("TPUDIST_LOCAL_RANK", 0) or 0,
            local_world_size=_env_int("TPUDIST_LOCAL_WORLD_SIZE", 1) or 1,
            launch_source="tpudist",
        )

    # 3. torchrun-style contract (reference demo.py:25-34 reads WORLD_SIZE/
    #    LOCAL_WORLD_SIZE/LOCAL_RANK under --torchrun).
    if "RANK" in env and "WORLD_SIZE" in env:
        n = _env_int("WORLD_SIZE")
        pid = _env_int("RANK")
        if n is None or pid is None:
            raise BootstrapError("RANK/WORLD_SIZE are set but empty")
        coord = _coordinator_from_master_env() if n > 1 else None
        return ProcessContext(
            process_id=pid,
            num_processes=n,
            coordinator_address=coord,
            local_rank=_env_int("LOCAL_RANK", 0) or 0,
            local_world_size=_env_int("LOCAL_WORLD_SIZE", 1) or 1,
            launch_source="torchrun",
        )

    # 4. SLURM contract (reference demo.py:35-49).
    if "SLURM_PROCID" in env or ("WORLD_SIZE" in env and "SLURM_LOCALID" in env):
        n = _env_int("WORLD_SIZE", _env_int("SLURM_NTASKS"))
        if n is None:
            raise BootstrapError("SLURM launch detected but WORLD_SIZE/SLURM_NTASKS unset")
        local_rank = _env_int("SLURM_LOCALID", 0) or 0
        local_world = _env_int("TASKS_PER_NODE", _env_int("SLURM_NTASKS_PER_NODE", 1)) or 1
        if use_node_rank:
            # demo.py:38-39 — global = NODE_RANK * local_world + local_rank
            node_rank = _env_int("NODE_RANK")
            if node_rank is None:
                raise BootstrapError("--use_node_rank requires NODE_RANK")
            pid = node_rank * local_world + local_rank
        else:
            pid = _env_int("SLURM_PROCID")  # demo.py:41
            if pid is None:
                raise BootstrapError("SLURM launch without SLURM_PROCID")
        coord = _coordinator_from_master_env() if n > 1 else None
        return ProcessContext(
            process_id=pid,
            num_processes=n,
            coordinator_address=coord,
            local_rank=local_rank,
            local_world_size=local_world,
            launch_source="slurm",
        )

    # 5. OpenMPI contract (mpiexec-started; demo_assume_started_with_mpiexec.py).
    if "OMPI_COMM_WORLD_RANK" in env:
        n = _env_int("OMPI_COMM_WORLD_SIZE")
        pid = _env_int("OMPI_COMM_WORLD_RANK")
        if n is None or pid is None:
            raise BootstrapError("OMPI_COMM_WORLD_RANK/SIZE are set but empty")
        coord = None
        if n > 1:
            # The coordinator address must have been agreed on out-of-band —
            # either by the mpi4py broadcast helper
            # (tpudist.runtime.mpi_bootstrap.exchange_coordinator) or by env.
            if "MASTER_ADDR" in env:
                coord = _coordinator_from_master_env()
            else:
                raise BootstrapError(
                    "MPI launch detected; call "
                    "tpudist.runtime.mpi_bootstrap.exchange_coordinator() first "
                    "or set MASTER_ADDR/MASTER_PORT"
                )
        return ProcessContext(
            process_id=pid,
            num_processes=n,
            coordinator_address=coord,
            local_rank=_env_int("OMPI_COMM_WORLD_LOCAL_RANK", 0) or 0,
            local_world_size=_env_int("OMPI_COMM_WORLD_LOCAL_SIZE", 1) or 1,
            launch_source="mpi",
        )

    # 6. single-process default.
    return ProcessContext(
        process_id=0,
        num_processes=1,
        coordinator_address=None,
        local_rank=0,
        local_world_size=1,
        launch_source="single",
    )


def find_free_port() -> int:
    """Pick a free TCP port (reference ``_find_free_port``,
    ``demo_assume_started_with_mpiexec.py:20-27``)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        return s.getsockname()[1]


_INITIALIZED_CTX: Optional[ProcessContext] = None


def _retry_with_backoff(
    fn: Callable[[int], "object"],
    *,
    retries: int,
    backoff_s: float,
    what: str,
    retry_on: tuple = (Exception,),
    sleep: Callable[[float], None] = time.sleep,
    rng: Callable[[], float] = random.random,
):
    """Run ``fn(attempt)`` with up to ``retries`` retries on ``retry_on``
    failures, sleeping a jittered exponential backoff between attempts:
    ``backoff_s * 2**attempt * (0.5 + rng())`` — the jitter (0.5x–1.5x)
    decorrelates a whole worker group hammering a recovering coordinator
    at the same instant.  KeyboardInterrupt/SystemExit (and anything not
    in ``retry_on``) pass through.  Shared by distributed init and the
    checkpoint manager's save path."""
    attempt = 0
    while True:
        try:
            return fn(attempt)
        except (KeyboardInterrupt, SystemExit):
            raise
        except retry_on as e:  # noqa: BLE001 — bounded by `retries`
            if attempt >= retries:
                raise
            delay = backoff_s * (2 ** attempt) * (0.5 + rng())
            from tpudist import telemetry

            telemetry.event("retry", what=what, attempt=attempt,
                            error=type(e).__name__, backoff_s=round(delay, 3))
            print(
                f"[tpudist.retry] {what} failed "
                f"(attempt {attempt + 1}/{retries + 1}): "
                f"{type(e).__name__}: {e}; retrying in {delay:.1f}s",
                file=sys.stderr, flush=True,
            )
            sleep(delay)
            attempt += 1


def initialize(
    ctx: Optional[ProcessContext] = None,
    *,
    use_node_rank: bool = False,
    initialization_timeout_s: int = 3600,
    init_retries: Optional[int] = None,
    init_backoff_s: Optional[float] = None,
) -> ProcessContext:
    """Bring up the JAX coordination service for this process.

    Replaces ``dist.init_process_group`` (``demo.py:27,49``).  The reference's
    1-hour init timeout (``demo.py:27``) is preserved as
    ``initialization_timeout_s``.  Idempotent: a second call returns the
    context from the first.

    ``jax.distributed.initialize`` is retried with jittered exponential
    backoff on transient coordinator failures (a worker restarted by
    ``tpurun`` often races the coordinator's own restart): ``init_retries``
    retries (default ``TPUDIST_INIT_RETRIES`` or 3) starting at
    ``init_backoff_s`` (default ``TPUDIST_INIT_BACKOFF_S`` or 1.0s).
    """
    global _INITIALIZED_CTX
    if _INITIALIZED_CTX is not None:
        return _INITIALIZED_CTX
    # An explicitly-set JAX_PLATFORMS env var must win even on hosts whose
    # sitecustomize force-selects a platform via jax.config at interpreter
    # start (which silently defeats the env var).  Re-assert it before the
    # backend comes up; no-op once backends are initialized.
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms:
        import jax

        try:
            from jax._src import xla_bridge as _xb

            backend_up = _xb.backends_are_initialized()
        except Exception:  # internal API moved — don't second-guess
            backend_up = True
        if not backend_up:
            jax.config.update("jax_platforms", env_platforms)
    # Persistent XLA compilation cache: a compile that succeeded once on
    # this machine is never re-paid (tunnel compiles are the slow,
    # wedge-prone step — see tpudist/runtime/compilation_cache.py).
    from tpudist.runtime.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    if ctx is None:
        ctx = resolve_process_context(use_node_rank=use_node_rank)
    # Chaos harness: honor TPUDIST_FAULT from the earliest runtime seam;
    # telemetry starts here too so the init span lands in the same session
    # the training loop records into.
    from tpudist import telemetry
    from tpudist.runtime import faults

    faults.arm_from_env()
    telemetry.ensure_started()
    if ctx.is_distributed:
        import jax

        from tpudist.utils.envutil import env_float

        if init_retries is None:
            init_retries = max(0, int(env_float("TPUDIST_INIT_RETRIES", 3)))
        if init_backoff_s is None:
            init_backoff_s = env_float("TPUDIST_INIT_BACKOFF_S", 1.0)

        def _attempt(attempt: int) -> None:
            faults.inject_init(attempt)
            if attempt > 0:
                # A failed connect leaves jax's global distributed state
                # half-initialized (State.initialize sets .client BEFORE
                # connect()), so a bare retry would raise 'should only be
                # called once' forever.  shutdown() clears it and is a
                # documented no-op when nothing is running.
                jax.distributed.shutdown()
            jax.distributed.initialize(
                coordinator_address=ctx.coordinator_address,
                num_processes=ctx.num_processes,
                process_id=ctx.process_id,
                initialization_timeout=initialization_timeout_s,
            )

        with telemetry.span("init", world=ctx.num_processes,
                            source=ctx.launch_source):
            _retry_with_backoff(
                _attempt, retries=init_retries, backoff_s=init_backoff_s,
                what=f"jax.distributed.initialize({ctx.coordinator_address})",
            )
    _INITIALIZED_CTX = ctx
    return ctx


def shutdown() -> None:
    """Tear down the coordination service.

    Replaces ``dist.barrier(); dist.destroy_process_group()``
    (``demo.py:177-178``).  The barrier is implicit: ``jax.distributed
    .shutdown`` synchronizes with the coordination service.
    """
    global _INITIALIZED_CTX
    if _INITIALIZED_CTX is not None and _INITIALIZED_CTX.is_distributed:
        import jax

        jax.distributed.shutdown()
    _INITIALIZED_CTX = None
