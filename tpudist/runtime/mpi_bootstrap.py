"""Bootstrap the JAX coordination service from an MPI launch.

The reference pattern (``demo_assume_started_with_mpiexec.py:29-50``): use one
communication fabric (MPI) to bootstrap another — rank 0 picks a free port
(``:20-27``), broadcasts its hostname and the port over ``MPI.COMM_WORLD``
(``:43-45``), every rank exports ``MASTER_ADDR``/``MASTER_PORT``/``RANK``/
``WORLD_SIZE`` and then initializes the real backend (``:46-50``).

Here the "real backend" is the JAX coordination service.  mpi4py is optional:
when absent (it is not baked into the TPU image) we fall back to the pure
``OMPI_*`` env contract, which additionally requires ``MASTER_ADDR`` (and
optionally ``MASTER_PORT``) to be exported by the launcher — there is no way
to agree on rank 0's hostname without either a collective or the env.
"""

from __future__ import annotations

import os
import socket
from typing import Optional, Tuple

from tpudist.runtime.bootstrap import (
    ProcessContext,
    find_free_port,
    initialize,
    resolve_process_context,
)


def have_mpi4py() -> bool:
    try:
        import mpi4py  # noqa: F401

        return True
    except ImportError:
        return False


def exchange_coordinator(port: Optional[int] = None) -> Tuple[str, int, int]:
    """Agree on ``(coordinator_address, num_processes, process_id)`` via MPI.

    Mirrors ``demo_assume_started_with_mpiexec.py:35-47``: rank, size from
    ``COMM_WORLD``; rank 0 picks the port; hostname+port broadcast to all.
    Exports MASTER_ADDR/MASTER_PORT so later env-contract resolution agrees.
    """
    from mpi4py import MPI  # deferred: optional dependency

    comm = MPI.COMM_WORLD
    rank = comm.Get_rank()
    size = comm.Get_size()
    if rank == 0:
        hostname = socket.gethostname()
        port = port or find_free_port()
    else:
        hostname, port = None, None
    hostname = comm.bcast(hostname, root=0)
    port = comm.bcast(port, root=0)
    os.environ["MASTER_ADDR"] = hostname
    os.environ["MASTER_PORT"] = str(port)
    os.environ.setdefault("WORLD_SIZE", str(size))
    return f"{hostname}:{port}", size, rank


def initialize_from_mpi(port: Optional[int] = None) -> ProcessContext:
    """One-call MPI-launched bootstrap → initialized JAX distributed runtime."""
    if have_mpi4py():
        coord, size, rank = exchange_coordinator(port)
        ctx = ProcessContext(
            process_id=rank,
            num_processes=size,
            coordinator_address=coord if size > 1 else None,
            local_rank=int(os.environ.get("OMPI_COMM_WORLD_LOCAL_RANK", 0)),
            local_world_size=int(os.environ.get("OMPI_COMM_WORLD_LOCAL_SIZE", 1)),
            launch_source="mpi",
        )
        return initialize(ctx)
    # mpi4py-less fallback: pure env contract (OMPI_* + MASTER_ADDR).
    return initialize(resolve_process_context())
