"""High-level Trainer facade — parity with PyTorch Lightning as used by the
reference (``demo_pytorch_lightning.py``, SURVEY.md §3.4).

The reference's ``LitToyModel`` holds two models (``:16-25``), sums their MSE
losses in ``training_step`` (``:27-33``) and returns one Adam per model from
``configure_optimizers`` (``:35-40``); ``pl.Trainer(gpus, num_nodes,
strategy='ddp', precision=32)`` owns the loop, device placement, and
distributed wiring (``:57-60``).

The TPU-native facade keeps that division of labor: the user supplies a
:class:`TrainerModule` (models + optimizers + loss); the :class:`Trainer`
owns the mesh, the compiled step, logging, and teardown.  ``strategy`` maps
onto mesh layout + state sharding (the Lightning ``strategy=`` flag analog,
``demo_pytorch_lightning.py:57-60``, opened to the full library — VERDICT
r4 weak #5):

- ``'dp'``       1-D data mesh, replicated state (≅ ``strategy='ddp'``)
- ``'dp_model'`` 2-D ``('data','model')`` mesh, user-supplied sharding
- ``'zero1'``    data mesh, optimizer state sharded over it
  (:func:`tpudist.parallel.zero1_sharding` — weight-update sharding)
- ``'fsdp'``     data mesh, params + optimizer state fully sharded
  (:func:`tpudist.parallel.fsdp_sharding` — ZeRO-3 layout)
- ``'pp'``       ``('data','stage')`` mesh, pipeline schedule
  (:class:`LMTrainerModule` only — blocks shard over stages)

``devices``/``num_nodes`` are *not* parameters — the mesh covers whatever the
launch contract provided, which is the multi-controller JAX model.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import jax
import optax

from tpudist.comm.collectives import MetricBackend
from tpudist.runtime.bootstrap import initialize, shutdown
from tpudist.runtime.mesh import data_model_mesh, data_parallel_mesh
from tpudist.runtime.seeding import resolve_shared_seed
from tpudist.train.loop import TrainLoopConfig, run_training
from tpudist.train.step import (
    init_model_states,
    make_multi_model_train_step,
    make_scanned_train_step,
    mse_loss,
)
from tpudist.utils.metrics import MetricsLogger, init_metrics


def _cast_tree(tree, dtype):
    """Cast float leaves only — integer inputs (token ids) and non-float
    leaves pass through untouched."""
    import jax.numpy as jnp

    return jax.tree.map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
        else a, tree)


def _bf16_apply(f):
    """Mixed precision: fp32 master weights, bf16 compute — params cast at
    apply time so grads come back fp32 for the optimizer."""
    import jax.numpy as jnp

    def wrapped(p, x):
        return _cast_tree(
            f(_cast_tree(p, jnp.bfloat16), _cast_tree(x, jnp.bfloat16)),
            jnp.float32)
    return wrapped


class TrainerModule:
    """Subclass and override; the Lightning-``LightningModule`` analog."""

    def configure_models(self, rng: jax.Array) -> Dict[str, Tuple[Callable, object]]:
        """Return name → ``(apply_fn, params)``.  Called once on every
        process with the same ``rng`` (replicated init without broadcast)."""
        raise NotImplementedError

    def configure_optimizers(self):
        """Return one optax transformation, or a per-model dict — the
        ``configure_optimizers`` returning a list of Adams analog
        (``demo_pytorch_lightning.py:35-40``).  For LR schedules use
        :func:`tpudist.train.build_optimizer` (owning the optimizer is the
        module's job, the Lightning contract, so the Trainer does not read
        ``--lr_schedule`` itself)."""
        return optax.adam(1e-3)

    def loss(self, pred: jax.Array, target: jax.Array) -> jax.Array:
        """Per-model loss; the total logged loss is the sum over models
        (``training_step`` summing loss_X + loss_Y, ``:27-33``)."""
        return mse_loss(pred, target)

    def state_sharding(self, mesh, states):
        """Optional non-replicated state layout for ``strategy='dp_model'``
        (strategy-derived layouts — fsdp/zero1 — apply when this returns
        None)."""
        return None


class LMTrainerModule(TrainerModule):
    """Trainer module for the LM family — the contract that opens the
    Trainer to the transformer strategies (fsdp / zero1 / pp).

    The user supplies ONE flax language model via :meth:`configure_lm`;
    the loader passed to ``fit`` yields ``[batch, seq]`` int32 token
    arrays (re-iterated per epoch; an optional ``set_epoch(e)`` hook gets
    the DistributedSampler set_epoch call, ``demo.py:96-98``).
    """

    def configure_lm(self, rng: jax.Array):
        """Return ``(flax_module, params)`` — e.g. from
        :func:`tpudist.models.create_transformer`.  Called once on every
        process with the same ``rng`` (replicated init)."""
        raise NotImplementedError

    def configure_optimizers(self):
        """One optax transformation (the LM path has a single model, so a
        per-model dict is rejected)."""
        return optax.adam(1e-3)

    def loss(self, logits: jax.Array, tokens: jax.Array) -> jax.Array:
        """Next-token loss given ``apply(params, tokens) -> logits``.
        Ignored by ``strategy='pp'`` (the pipeline schedules own their
        fused vocab head — see ``tpudist.parallel.pipeline_lm``)."""
        from tpudist.train.lm import lm_loss

        return lm_loss(logits, tokens)


@dataclasses.dataclass
class Trainer:
    max_steps: int = 1000  # demo_pytorch_lightning.py:48 (1000 steps)
    # 'dp' | 'dp_model' | 'fsdp' | 'zero1' | 'pp' | 'auto' ('auto' =
    # measurement-driven pick, tpudist.plan — resolved at fit(); the
    # ranked report lands on self.plan and stamps into telemetry)
    strategy: str = "dp"
    model_parallel: int = 2
    # fsdp/zero1: leaves under this many elements stay replicated (the
    # gather overhead beats the memory win for small tensors).
    shard_min_size: int = 1024
    # pp (LMTrainerModule only): stage-axis width, schedule, microbatches
    # (default: one per stage; interleaved wants 2x).
    pipeline_stages: int = 2
    pp_schedule: str = "1f1b"  # 'gpipe' | '1f1b' | 'interleaved'
    pp_chunks: int = 2         # virtual chunks/device (interleaved only)
    microbatches: Optional[int] = None
    precision: str = "fp32"  # 'fp32' (reference precision=32) | 'bf16'
    log_every: int = 1
    metric_backend: MetricBackend = MetricBackend.ICI
    project: str = "tpudist"
    group: Optional[str] = None
    dry_run: bool = False
    seed: Optional[int] = 0  # None → rank-0 draw broadcast job-wide
    use_node_rank: bool = False
    progress_bar: bool = True
    # Checkpointing (the demos' --checkpoint_dir/--checkpoint_every/--resume
    # contract, reference dir layout job_submitter.sh:157-159): a directory
    # enables periodic saves; resume=True restores the latest step and
    # continues the loop from its saved iteration.
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0
    resume: bool = False

    def fit(self, module: TrainerModule, loader) -> Dict[str, float]:
        """Own the whole run: init runtime, build mesh + compiled step,
        train, tear down.  Returns the final per-model losses."""
        from tpudist.checkpoint import (
            resolve_checkpoint_location,
            setup_checkpointing,
        )

        # Resolve (and validate resume config) before any runtime side
        # effects — same env-contract resolution as the plain demos.
        ckpt_dir = resolve_checkpoint_location(
            self.checkpoint_dir, save_every=self.checkpoint_every,
            resume=self.resume,
        )
        initialize(use_node_rank=self.use_node_rank)
        seed = resolve_shared_seed(self.seed)
        if self.strategy == "auto":
            # measurement-driven resolution (tpudist.plan): score the
            # strategies this facade can enact against the frozen
            # artifacts, assign the winner onto self.strategy (+ pp
            # fields when pp wins).  self.plan keeps the full ranked
            # report; the loop stamps plan.stamp() into telemetry so
            # prediction-vs-actual is auditable from the run report.
            from tpudist.plan import resolve_trainer_auto

            self.plan = resolve_trainer_auto(self, module, seed)
        if isinstance(module, LMTrainerModule):
            return self._fit_lm(module, loader, ckpt_dir, seed)

        if self.strategy in ("dp", "fsdp", "zero1"):
            mesh = data_parallel_mesh()
        elif self.strategy == "dp_model":
            mesh = data_model_mesh(model_size=self.model_parallel)
        elif self.strategy == "pp":
            raise ValueError(
                "strategy='pp' needs an LMTrainerModule (transformer "
                "blocks shard over pipeline stages; the multi-model toy "
                "contract has no block stack)")
        else:
            raise ValueError(f"unknown strategy {self.strategy!r}")

        models = module.configure_models(jax.random.PRNGKey(seed))
        tx = module.configure_optimizers()
        states = init_model_states(models, tx)
        state_sharding = module.state_sharding(mesh, states)
        if state_sharding is None and self.strategy in ("fsdp", "zero1"):
            from tpudist.parallel import fsdp_sharding, zero1_sharding

            if self.strategy == "fsdp":
                state_sharding = fsdp_sharding(
                    mesh, states, min_size=self.shard_min_size)
            else:
                state_sharding = {
                    k: zero1_sharding(mesh, st, min_size=self.shard_min_size)
                    for k, st in states.items()}
        if state_sharding is not None:
            states = jax.device_put(states, state_sharding)

        apply_fns = {k: f for k, (f, _) in models.items()}
        if self.precision == "bf16":
            apply_fns = {k: _bf16_apply(f) for k, f in apply_fns.items()}
        step = make_multi_model_train_step(
            apply_fns, tx, mesh, loss_fn=module.loss, state_sharding=state_sharding
        )
        chunk_step = make_scanned_train_step(
            apply_fns, tx, mesh, loss_fn=module.loss, state_sharding=state_sharding
        )

        ckpt = None
        start_iteration = 0
        if ckpt_dir is not None:
            # mesh= routes resume through the reshard path: a checkpoint
            # saved at a different world size (elastic tpurun relaunch)
            # re-binds its logical shardings onto THIS mesh.
            ckpt, states, start_iteration = setup_checkpointing(
                states, ckpt_dir, save_every=self.checkpoint_every,
                resume=self.resume, mesh=mesh,
            )

        logger: MetricsLogger = init_metrics(
            project=self.project, group=self.group or "trainer", dry_run=self.dry_run
        )
        cfg = TrainLoopConfig(
            total_iterations=self.max_steps,
            log_every=self.log_every,
            metric_backend=self.metric_backend,
            progress_bar=self.progress_bar,
            plan_stamp=(self.plan.stamp()
                        if getattr(self, "plan", None) is not None
                        else None),
        )
        try:
            states, losses = run_training(
                states, step, loader, mesh, logger, cfg,
                ckpt=ckpt, start_iteration=start_iteration,
                chunk_step_fn=chunk_step,
            )
        finally:
            if ckpt is not None:
                ckpt.close()
        self.final_states = states
        # A SIGTERM-preempted run checkpointed and exited EARLY — the
        # caller must not mistake it for a completed fit (resume with
        # the same checkpoint_dir + resume=True to continue).
        from tpudist.runtime import preemption
        from tpudist.runtime.rank_logging import rank_print

        self.preempted = preemption.last_run_preempted()
        if self.preempted:
            rank_print("[trainer] preempted: checkpoint saved, fit "
                       "incomplete — rerun with resume=True to continue")
        return losses

    def _fit_lm(self, module: "LMTrainerModule", loader, ckpt_dir, seed):
        """LM-family fit: one transformer, strategy-derived state layout
        (dp / fsdp / zero1 / pp), token-batch loader."""
        from tpudist.checkpoint import setup_checkpointing
        from tpudist.train import init_lm_state, make_lm_train_step

        if self.strategy in ("dp", "fsdp", "zero1"):
            mesh = data_parallel_mesh()
        elif self.strategy == "pp":
            from tpudist.runtime.mesh import MeshConfig, make_mesh

            mesh = make_mesh(
                MeshConfig(data=-1, stage=self.pipeline_stages),
                axis_names=("data", "stage"))
        else:
            raise ValueError(
                f"strategy {self.strategy!r} not supported for "
                "LMTrainerModule (use dp/fsdp/zero1/pp; dp_model is the "
                "toy split-MLP layout)")

        flax_mod, params = module.configure_lm(jax.random.PRNGKey(seed))
        tx = module.configure_optimizers()
        if isinstance(tx, dict):
            raise ValueError(
                "LMTrainerModule.configure_optimizers must return one "
                "optax transformation (single model)")

        if self.strategy == "pp":
            if self.precision == "bf16":
                raise ValueError(
                    "strategy='pp' does not support precision='bf16' yet: "
                    "the pipeline schedules own their step construction "
                    "(tpudist.parallel.pipeline_lm) and the facade's "
                    "apply-time cast does not reach it — requesting it "
                    "must not silently train fp32")
            from tpudist.parallel import (
                make_pp_lm_train_step,
                pp_state_sharding,
                stack_block_params,
                stack_block_params_interleaved,
            )

            chunks = self.pp_chunks if self.pp_schedule == "interleaved" else 1
            micro = self.microbatches or self.pipeline_stages * (
                2 if self.pp_schedule == "interleaved" else 1)
            if chunks > 1:
                pp_params = stack_block_params_interleaved(
                    params, self.pipeline_stages, chunks)
            else:
                pp_params = stack_block_params(params, self.pipeline_stages)
            state = init_lm_state(pp_params, tx)
            sharding = pp_state_sharding(mesh, state)
            state = jax.device_put(state, sharding)
            step = make_pp_lm_train_step(
                mesh, flax_mod, tx, n_stages=self.pipeline_stages,
                num_microbatches=micro, schedule=self.pp_schedule,
                n_chunks=chunks, state_sharding=sharding)
        else:
            state = init_lm_state(params, tx)
            sharding = module.state_sharding(mesh, state)
            if sharding is None and self.strategy in ("fsdp", "zero1"):
                from tpudist.parallel import fsdp_sharding, zero1_sharding

                sharding = (
                    fsdp_sharding(mesh, state, min_size=self.shard_min_size)
                    if self.strategy == "fsdp"
                    else zero1_sharding(mesh, state,
                                        min_size=self.shard_min_size))
            if sharding is not None:
                state = jax.device_put(state, sharding)
            apply_fn = flax_mod.apply
            if self.precision == "bf16":
                apply_fn = _bf16_apply(apply_fn)
            step = make_lm_train_step(
                apply_fn, tx, mesh, state_sharding=sharding,
                loss_fn=module.loss)

        ckpt = None
        start_iteration = 0
        if ckpt_dir is not None:
            ckpt, state, start_iteration = setup_checkpointing(
                state, ckpt_dir, save_every=self.checkpoint_every,
                resume=self.resume, mesh=mesh,
            )
        logger: MetricsLogger = init_metrics(
            project=self.project, group=self.group or "trainer",
            dry_run=self.dry_run)
        try:
            state, losses = self._run_lm_loop(
                state, step, loader, mesh, logger, ckpt, start_iteration)
        finally:
            if ckpt is not None:
                ckpt.close()
        self.final_states = state
        from tpudist.runtime import preemption
        from tpudist.runtime.rank_logging import rank_print

        self.preempted = preemption.last_run_preempted()
        if self.preempted:
            rank_print("[trainer] preempted: checkpoint saved, fit "
                       "incomplete — rerun with resume=True to continue")
        return losses

    def _run_lm_loop(self, state, step, loader, mesh, logger, ckpt,
                     start_iteration):
        """Token-batch loop.  The preemption bracket and run-teardown
        ordering are the SHARED helpers in :mod:`tpudist.train.loop`
        (``preemption_scope`` / ``finalize_run``) — one copy of that
        contract for every loop in the framework."""
        import time

        import numpy as np

        from tpudist import telemetry
        from tpudist.train import token_sharding
        from tpudist.train.loop import (
            TrainLoopConfig,
            _data_wait_iter,
            _make_pbar,
            _preemption_check,
            finalize_run,
            preemption_scope,
        )

        # session ownership: only finish a session this loop started —
        # a pre-existing one belongs to the embedding process (e.g. a
        # serving process whose distill flywheel trains through here)
        owns_telemetry = telemetry.active() is None
        telemetry.ensure_started()
        if getattr(self, "plan", None) is not None:
            # auto-mode audit trail: the chosen plan + predictions land
            # in the same stream as the measured step spans
            telemetry.event("plan_selected", **self.plan.stamp())
        # live observability: scrape endpoint + step-time gauges flow
        # from the step spans via the metrics feed (TPUDIST_METRICS_PORT
        # gates the endpoint; no-op when unset)
        from tpudist.telemetry import statusz

        statusz.ensure_started()
        tele = telemetry.active()
        first_step = True  # first dispatch pays XLA compile → its own span

        ts = token_sharding(mesh)
        batches = len(loader) if hasattr(loader, "__len__") else None
        if batches is None and start_iteration:
            # Fast-forwarding start_iteration batches through a loader with
            # no __len__ cannot recover the epoch boundary: the skip loop
            # would silently exhaust a shorter iterator (dying later with a
            # misleading "yielded no batches") and epoch-seeded shuffling
            # would replay epoch-0 data.  Fail at the resume site instead.
            raise ValueError(
                f"resume at iteration {start_iteration} requires a sized "
                "loader: the LM loop derives the epoch boundary from "
                "len(loader), which this loader does not provide — wrap it "
                "with a __len__ (e.g. a list or tpudist.data loader) or "
                "restart without resume")
        epoch = start_iteration // batches if batches else 0
        skip = start_iteration - epoch * (batches or 0)
        iteration = start_iteration
        loss = None
        preempted = False
        pbar = _make_pbar(
            TrainLoopConfig(total_iterations=self.max_steps,
                            progress_bar=self.progress_bar),
            initial=start_iteration)
        # finalize_run stays INSIDE the scope: the forced preemption save
        # must run with the SIGTERM handler still installed, or a second
        # signal during the grace window kills the process mid-save.
        with preemption_scope(ckpt is not None):
            while iteration < self.max_steps and not preempted:
                if hasattr(loader, "set_epoch"):
                    loader.set_epoch(epoch)
                it = iter(loader)
                for _ in range(skip):
                    next(it, None)
                skip = 0
                advanced = False
                for tokens in _data_wait_iter(it, tele):
                    advanced = True
                    if iteration >= self.max_steps:
                        break
                    if tele is not None:
                        _t0 = time.monotonic()
                    state, loss = step(
                        state, jax.device_put(
                            np.asarray(tokens, dtype=np.int32), ts))
                    if tele is not None:
                        if first_step:
                            # Block on the first result so the span
                            # measures the compile, not the dispatch.
                            jax.block_until_ready(loss)
                        tele.record_span("compile" if first_step else "step",
                                         _t0, time.monotonic() - _t0)
                    first_step = False
                    iteration += 1
                    # The compiled LM step already reduces the loss over
                    # the GLOBAL batch, so there is no per-rank value for
                    # a host-fabric (metric_backend) reduction to merge —
                    # rank-0 logging of the step loss is the whole story.
                    if logger is not None and \
                            iteration % max(1, self.log_every) == 0:
                        logger.log({"loss/lm": float(loss)}, commit=True)
                    if pbar is not None:
                        pbar.update(1)
                    if ckpt is not None:
                        ckpt.maybe_save(iteration, state,
                                        {"iteration": iteration,
                                         "epoch": epoch})
                        if (iteration < self.max_steps
                                and _preemption_check()):
                            preempted = True
                            break
                if not advanced:
                    raise ValueError("LM loader yielded no batches")
                if not preempted:
                    epoch += 1
            if pbar is not None:
                pbar.close()
            finalize_run(state, iteration=iteration, epoch=epoch,
                         preempted=preempted, ckpt=ckpt, logger=logger,
                         own_telemetry=owns_telemetry)
        return state, {"lm": float(loss) if loss is not None else None}

    @staticmethod
    def teardown():
        shutdown()
