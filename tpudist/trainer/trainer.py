"""High-level Trainer facade — parity with PyTorch Lightning as used by the
reference (``demo_pytorch_lightning.py``, SURVEY.md §3.4).

The reference's ``LitToyModel`` holds two models (``:16-25``), sums their MSE
losses in ``training_step`` (``:27-33``) and returns one Adam per model from
``configure_optimizers`` (``:35-40``); ``pl.Trainer(gpus, num_nodes,
strategy='ddp', precision=32)`` owns the loop, device placement, and
distributed wiring (``:57-60``).

The TPU-native facade keeps that division of labor: the user supplies a
:class:`TrainerModule` (models + optimizers + loss); the :class:`Trainer`
owns the mesh, the compiled step, logging, and teardown.  ``strategy`` maps
onto mesh layout: ``'dp'`` (1-D data mesh, the ``strategy='ddp'`` analog) or
``'dp_model'`` (2-D ``('data','model')`` mesh with user-supplied sharding).
``devices``/``num_nodes`` are *not* parameters — the mesh covers whatever the
launch contract provided, which is the multi-controller JAX model.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import jax
import optax

from tpudist.comm.collectives import MetricBackend
from tpudist.runtime.bootstrap import initialize, shutdown
from tpudist.runtime.mesh import data_model_mesh, data_parallel_mesh
from tpudist.runtime.seeding import resolve_shared_seed
from tpudist.train.loop import TrainLoopConfig, run_training
from tpudist.train.step import (
    init_model_states,
    make_multi_model_train_step,
    make_scanned_train_step,
    mse_loss,
)
from tpudist.utils.metrics import MetricsLogger, init_metrics


class TrainerModule:
    """Subclass and override; the Lightning-``LightningModule`` analog."""

    def configure_models(self, rng: jax.Array) -> Dict[str, Tuple[Callable, object]]:
        """Return name → ``(apply_fn, params)``.  Called once on every
        process with the same ``rng`` (replicated init without broadcast)."""
        raise NotImplementedError

    def configure_optimizers(self):
        """Return one optax transformation, or a per-model dict — the
        ``configure_optimizers`` returning a list of Adams analog
        (``demo_pytorch_lightning.py:35-40``).  For LR schedules use
        :func:`tpudist.train.build_optimizer` (owning the optimizer is the
        module's job, the Lightning contract, so the Trainer does not read
        ``--lr_schedule`` itself)."""
        return optax.adam(1e-3)

    def loss(self, pred: jax.Array, target: jax.Array) -> jax.Array:
        """Per-model loss; the total logged loss is the sum over models
        (``training_step`` summing loss_X + loss_Y, ``:27-33``)."""
        return mse_loss(pred, target)

    def state_sharding(self, mesh, states):
        """Optional non-replicated state layout for ``strategy='dp_model'``."""
        return None


@dataclasses.dataclass
class Trainer:
    max_steps: int = 1000  # demo_pytorch_lightning.py:48 (1000 steps)
    strategy: str = "dp"   # 'dp' (≅ ddp) | 'dp_model'
    model_parallel: int = 2
    precision: str = "fp32"  # 'fp32' (reference precision=32) | 'bf16'
    log_every: int = 1
    metric_backend: MetricBackend = MetricBackend.ICI
    project: str = "tpudist"
    group: Optional[str] = None
    dry_run: bool = False
    seed: Optional[int] = 0  # None → rank-0 draw broadcast job-wide
    use_node_rank: bool = False
    progress_bar: bool = True
    # Checkpointing (the demos' --checkpoint_dir/--checkpoint_every/--resume
    # contract, reference dir layout job_submitter.sh:157-159): a directory
    # enables periodic saves; resume=True restores the latest step and
    # continues the loop from its saved iteration.
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0
    resume: bool = False

    def fit(self, module: TrainerModule, loader) -> Dict[str, float]:
        """Own the whole run: init runtime, build mesh + compiled step,
        train, tear down.  Returns the final per-model losses."""
        from tpudist.checkpoint import (
            resolve_checkpoint_location,
            setup_checkpointing,
        )

        # Resolve (and validate resume config) before any runtime side
        # effects — same env-contract resolution as the plain demos.
        ckpt_dir = resolve_checkpoint_location(
            self.checkpoint_dir, save_every=self.checkpoint_every,
            resume=self.resume,
        )
        initialize(use_node_rank=self.use_node_rank)
        seed = resolve_shared_seed(self.seed)
        if self.strategy == "dp":
            mesh = data_parallel_mesh()
        elif self.strategy == "dp_model":
            mesh = data_model_mesh(model_size=self.model_parallel)
        else:
            raise ValueError(f"unknown strategy {self.strategy!r}")

        models = module.configure_models(jax.random.PRNGKey(seed))
        tx = module.configure_optimizers()
        states = init_model_states(models, tx)
        state_sharding = module.state_sharding(mesh, states)
        if state_sharding is not None:
            states = jax.device_put(states, state_sharding)

        apply_fns = {k: f for k, (f, _) in models.items()}
        if self.precision == "bf16":
            # mixed precision: fp32 master weights, bf16 compute — params are
            # cast at apply time so grads come back fp32 for the optimizer
            import jax.numpy as jnp

            def _cast(tree, dtype):
                # floats only — integer inputs (token ids) and non-float
                # leaves pass through untouched
                return jax.tree.map(
                    lambda a: a.astype(dtype)
                    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                    else a, tree)

            def _bf16(f):
                def wrapped(p, x):
                    return _cast(
                        f(_cast(p, jnp.bfloat16), _cast(x, jnp.bfloat16)),
                        jnp.float32)
                return wrapped

            apply_fns = {k: _bf16(f) for k, f in apply_fns.items()}
        step = make_multi_model_train_step(
            apply_fns, tx, mesh, loss_fn=module.loss, state_sharding=state_sharding
        )
        chunk_step = make_scanned_train_step(
            apply_fns, tx, mesh, loss_fn=module.loss, state_sharding=state_sharding
        )

        ckpt = None
        start_iteration = 0
        if ckpt_dir is not None:
            ckpt, states, start_iteration = setup_checkpointing(
                states, ckpt_dir, save_every=self.checkpoint_every,
                resume=self.resume,
            )

        logger: MetricsLogger = init_metrics(
            project=self.project, group=self.group or "trainer", dry_run=self.dry_run
        )
        cfg = TrainLoopConfig(
            total_iterations=self.max_steps,
            log_every=self.log_every,
            metric_backend=self.metric_backend,
            progress_bar=self.progress_bar,
        )
        try:
            states, losses = run_training(
                states, step, loader, mesh, logger, cfg,
                ckpt=ckpt, start_iteration=start_iteration,
                chunk_step_fn=chunk_step,
            )
        finally:
            if ckpt is not None:
                ckpt.close()
        self.final_states = states
        # A SIGTERM-preempted run checkpointed and exited EARLY — the
        # caller must not mistake it for a completed fit (resume with
        # the same checkpoint_dir + resume=True to continue).
        from tpudist.runtime import preemption
        from tpudist.runtime.rank_logging import rank_print

        self.preempted = preemption.last_run_preempted()
        if self.preempted:
            rank_print("[trainer] preempted: checkpoint saved, fit "
                       "incomplete — rerun with resume=True to continue")
        return losses

    @staticmethod
    def teardown():
        shutdown()
