"""High-level Trainer facade (Lightning-equivalent, parity with
``demo_pytorch_lightning.py``)."""

from tpudist.trainer.trainer import (  # noqa: F401
    LMTrainerModule,
    Trainer,
    TrainerModule,
)
