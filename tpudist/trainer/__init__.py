"""High-level Trainer facade (Lightning-equivalent, parity with
``demo_pytorch_lightning.py``)."""
