"""Measurement-driven configuration planner (AMP-style, but measured).

Every other pillar of the repo produces *frozen measurements* — comm
audits, rooflines, scaling-model fits, serve sweeps.  This package turns
them into decisions: enumerate the legal configuration space, predict a
step time (training) or TTFT/TPOT (serving) for each candidate by
composing the measured artifacts, rank, and pick.

The division of labor (one module each):

- :mod:`tpudist.plan.artifacts` — typed loader for the frozen
  ``<FAMILY>_rNN.json`` artifacts (newest round wins; stale or
  foreign-geometry artifacts rejected loudly; missing families degrade
  to the analytic model with an explicit "unmeasured" flag).
- :mod:`tpudist.plan.cost` — the predicted-step-time and
  predicted-TTFT/TPOT models.  Measured ratios beat analytic guesses
  (arXiv:2505.12832): wherever an artifact carries a measured twin for
  a knob, the model quotes THAT ratio; analytic formulas fill the gaps
  and are tagged ``extrapolated``.
- :mod:`tpudist.plan.enumerate` — the legal candidate space, mirroring
  the refusal rules the Trainer and SlotEngine enforce (pp needs an LM
  module, pp×bf16 refused, kernel arms need the paged cache, ...).
- :mod:`tpudist.plan.planner` — score, rank, report; the
  ``Trainer(strategy="auto")`` / ``SlotEngine(auto=True)`` resolution
  entry points; the plan stamps into telemetry as a ``plan_selected``
  event so prediction-vs-actual is auditable from any run.

Offline: ``python -m tpudist.plan`` prints the ranked table.

Knobs (all parsed once, ENV_VARS-registered): ``TPUDIST_PLAN_DIR``,
``TPUDIST_PLAN_TOPN``, ``TPUDIST_PLAN_STALE_ROUNDS``,
``TPUDIST_PLAN_STRICT``.
"""

from tpudist.plan.artifacts import (  # noqa: F401
    Artifact,
    ArtifactSet,
    PlanArtifactError,
    default_root,
    load_artifacts,
)
from tpudist.plan.cost import (  # noqa: F401
    Calibration,
    Estimate,
    ServeCandidate,
    ServeWorkload,
    TrainCandidate,
    TrainWorkload,
    predict_serving,
    predict_training,
)
from tpudist.plan.enumerate import (  # noqa: F401
    serving_candidates,
    training_candidates,
)
from tpudist.plan.planner import (  # noqa: F401
    PlannedConfig,
    PlanReport,
    plan_serving,
    plan_training,
    resolve_engine_auto,
    resolve_trainer_auto,
)
