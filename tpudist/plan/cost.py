"""Predicted step-time (training) and TTFT/TPOT (serving) cost models.

The modeling stance, in one sentence: **measured ratios beat analytic
guesses** (the DDP/FSDP characterization result, arXiv:2505.12832), so
every knob whose effect the repo has FROZEN a measured twin for is
scored with that ratio, and only the gaps are filled with the analytic
formulas — each gap tagged ``extrapolated`` in the estimate so a plan
report can say exactly which parts of a prediction rest on evidence.

Composition (what plugs into what):

- training: ``t = t_compute · (1 + pp_bubble) + exposed_comm`` where
  the wire bytes per strategy come from the COMM_AUDIT byte ledgers
  (measured for fsdp/tp regimes, the classic ``2(n−1)/n`` ring formulas
  otherwise) and the exposed fraction is the audit's measured
  ``exposed_fraction`` per overlap mode.  ``t_compute`` and the
  collective bandwidth come from a :class:`Calibration` when the caller
  measured them (the plan_bench path), else from the device tables in
  :mod:`tpudist.utils.flops` (the offline path — explicitly
  extrapolated).
- serving: ``tpot = base · Π multiplier(knob)`` where the multipliers
  are measured twins out of BENCH_SERVE (decode-block sweep, spec
  acceptance sweep, kernel-family twins) and ROOFLINE (paged bytes
  curves).  A knob with byte-level evidence but NO measured wall twin
  (e.g. int8 KV) contributes a **neutral 1.0 multiplier plus a note**:
  the planner never claims a win it has not measured.

An unmeasured input never fails the estimate — it degrades to the
analytic value and lands in ``Estimate.extrapolated``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

from tpudist.plan.artifacts import Artifact, ArtifactSet

# -- device tables (the offline, uncalibrated path) ---------------------

#: Fall-back device kind when none is visible (the artifact history was
#: frozen against v5e-class assumptions).
DEFAULT_DEVICE_KIND = "TPU v5 lite"


def _device_tables() -> Tuple[dict, dict, dict]:
    from tpudist.utils.flops import (
        HBM_BYTES_PER_S,
        ICI_LINK_BYTES_PER_S,
        PEAK_BF16_FLOPS,
    )

    return PEAK_BF16_FLOPS, ICI_LINK_BYTES_PER_S, HBM_BYTES_PER_S


# -- workloads and candidates ------------------------------------------


@dataclasses.dataclass
class TrainWorkload:
    """What the training step IS, independent of how it is laid out."""

    param_bytes: float
    flops_per_step: float
    n_devices: int
    global_batch: int = 8
    lm: bool = True
    precision: str = "fp32"
    device_kind: str = DEFAULT_DEVICE_KIND


@dataclasses.dataclass(frozen=True)
class TrainCandidate:
    """One point of the training config space (enumerate.py emits these)."""

    strategy: str            # dp | dp_model | fsdp | zero1 | pp
    overlap: str = "none"    # none | ring | bidir (fsdp/tp regimes only)
    microbatches: Optional[int] = None   # pp only
    stages: int = 1                      # pp only
    model_parallel: int = 1              # dp_model only

    @property
    def name(self) -> str:
        bits = [self.strategy]
        if self.overlap != "none":
            bits.append(f"overlap={self.overlap}")
        if self.strategy == "pp":
            bits.append(f"stages={self.stages}")
            if self.microbatches:
                bits.append(f"micro={self.microbatches}")
        if self.strategy == "dp_model":
            bits.append(f"mp={self.model_parallel}")
        return ",".join(bits)


@dataclasses.dataclass
class ServeWorkload:
    """What serving a model IS: the byte geometry decode must stream."""

    weight_bytes: float
    kv_bytes_per_pos: float
    n_layers: int
    max_len: int
    n_devices: int = 1
    slots: int = 4
    prompt_len: int = 32
    device_kind: str = DEFAULT_DEVICE_KIND


@dataclasses.dataclass(frozen=True)
class ServeCandidate:
    """One point of the serving config space."""

    decode_block: int = 8
    paged: bool = False
    kv_block: int = 16
    kv_int8: bool = False
    attn_kernel: str = "gather"      # gather | paged
    prefill_kernel: bool = False
    sample_kernel: bool = False
    fused_rope: bool = False
    spec_layers: Optional[int] = None  # tied-draft depth; None = no spec
    spec_k: int = 4
    slots: int = 4
    mesh: Optional[str] = None
    disagg: bool = False
    host_tier_bytes: int = 0

    @property
    def name(self) -> str:
        bits = [f"K={self.decode_block}",
                "paged" if self.paged else "dense"]
        if self.kv_int8:
            bits.append("int8")
        if self.attn_kernel != "gather":
            bits.append(f"attn={self.attn_kernel}")
        if self.prefill_kernel:
            bits.append("prefill_kernel")
        if self.sample_kernel:
            bits.append("sample_kernel")
        if self.fused_rope:
            bits.append("fused_rope")
        if self.spec_layers is not None:
            bits.append(f"spec={self.spec_layers}x{self.spec_k}")
        if self.slots != 4:
            bits.append(f"slots={self.slots}")
        if self.mesh:
            bits.append(f"mesh={self.mesh}")
        if self.disagg:
            bits.append("disagg")
        return ",".join(bits)


@dataclasses.dataclass
class Calibration:
    """Measured unit costs for THIS machine (plan_bench measures them;
    offline callers omit the whole object and get device-table numbers
    tagged extrapolated).

    ``base_s`` anchors the compute term: the measured seconds of the
    BASE candidate (dp for training, the dense-``K=8`` engine for
    serving) on the target workload.  ``collective_bytes_per_s`` is a
    micro-measured all-reduce bandwidth on the target mesh;
    ``dispatch_overhead_s`` a measured per-dispatch host cost.

    ``state_shard_ratio`` is the measured zero1/dp step-time ratio on a
    small PROXY workload on this host.  On real accelerators replicated
    optimizer math is free (it runs in parallel on distinct chips) and
    the ratio measures > 1 (gather overhead); on shared-core virtual
    meshes every replica competes for the same silicon and the ratio
    measures < 1.  Scoring fsdp/zero1's compute term by this ratio is
    what lets the planner rank state sharding correctly on BOTH kinds
    of host — an analytic model can't know which one it is on."""

    base_s: Optional[float] = None
    collective_bytes_per_s: Optional[float] = None
    dispatch_overhead_s: Optional[float] = None
    state_shard_ratio: Optional[float] = None


@dataclasses.dataclass
class Estimate:
    """A prediction plus its evidence trail."""

    seconds: float
    #: named components/multipliers (seconds for additive parts,
    #: dimensionless for multipliers) — the "show your work" dict
    parts: Dict[str, float]
    #: component names backed by a frozen measurement
    measured: List[str]
    #: component names filled by the analytic fallback
    extrapolated: List[str]
    notes: List[str]

    def tag(self, name: str, measured: bool) -> None:
        (self.measured if measured else self.extrapolated).append(name)


# -- training ----------------------------------------------------------

#: Analytic wire bytes per parameter byte for an ``n``-way ring; the
#: audit's measured ledgers override these where they exist.
def _ring_factor(n: int) -> float:
    return 2.0 * (n - 1) / n if n > 1 else 0.0


def _audit_regime(arts: Optional[ArtifactSet], name: str) -> Optional[dict]:
    if arts is None:
        return None
    a = arts.get("COMM_AUDIT")
    if a is None:
        return None
    reg = a.data.get("regimes", {})
    r = reg.get(name)
    return r if isinstance(r, dict) else None


def _wire_bytes(cand: TrainCandidate, wl: TrainWorkload,
                arts: Optional[ArtifactSet], est: Estimate) -> float:
    """Per-step collective bytes for the candidate's strategy."""
    n = max(2, wl.n_devices)
    P = wl.param_bytes
    if cand.strategy == "dp":
        # grad all-reduce: ring all-reduce moves 2(n-1)/n of the tree
        est.tag("wire:dp", measured=False)
        return _ring_factor(n) * P
    if cand.strategy == "zero1":
        # grad all-reduce + updated-shard all-gather
        est.tag("wire:zero1", measured=False)
        return (_ring_factor(n) + (n - 1) / n) * P
    if cand.strategy == "fsdp":
        reg = _audit_regime(arts, "fsdp")
        if reg is not None:
            info = reg.get("info", {})
            split = reg.get("overlap_split", {})
            pb = float(info.get("param_bytes", 0) or 0)
            total = float(split.get("exposed_bytes", 0)
                          + split.get("overlapped_bytes", 0))
            if pb > 0 and total > 0:
                est.tag("wire:fsdp", measured=True)
                return total / pb * P
        est.tag("wire:fsdp", measured=False)
        # analytic: all-gather params (fwd) + all-gather (bwd) +
        # reduce-scatter grads — 3 ring passes over the sharded tree
        return 3.0 * (n - 1) / n * P
    if cand.strategy == "dp_model":
        # activations cross the model axis, not the param tree — small
        # next to grad sync; the audit's tp_mlp regime measures the
        # per-layer all-reduce bytes for the toy split-MLP.
        reg = _audit_regime(arts, "tp_mlp")
        if reg is not None:
            split = reg.get("overlap_split", {})
            total = float(split.get("exposed_bytes", 0)
                          + split.get("overlapped_bytes", 0))
            if total > 0:
                est.tag("wire:dp_model", measured=True)
                # audit bytes are per toy step; scale by batch share
                return total + _ring_factor(n) * P
        est.tag("wire:dp_model", measured=False)
        return 0.1 * P + _ring_factor(n) * P
    if cand.strategy == "pp":
        # stage boundaries move activations only
        est.tag("wire:pp", measured=False)
        return 0.05 * P
    raise ValueError(f"unknown strategy {cand.strategy!r}")


#: Analytic exposed fractions when the audit has no regime for the
#: overlap mode.  Ordered so more overlap NEVER predicts slower (the
#: monotonicity contract tests pin).
_ANALYTIC_EXPOSED = {"none": 1.0, "ring": 0.45, "bidir": 0.30}


def _exposed_fraction(cand: TrainCandidate,
                      arts: Optional[ArtifactSet], est: Estimate) -> float:
    base = _audit_regime(arts, cand.strategy)  # e.g. "fsdp"
    reg = None
    if cand.overlap != "none":
        reg = _audit_regime(arts, f"{cand.strategy}_overlap_{cand.overlap}")
    elif base is not None:
        reg = base
    if reg is not None and isinstance(reg.get("exposed_fraction"),
                                      (int, float)):
        frac = float(reg["exposed_fraction"])
        # clamp against the no-overlap regime so a noisy audit can
        # never invert the more-overlap-never-slower ordering
        if cand.overlap != "none" and base is not None and isinstance(
                base.get("exposed_fraction"), (int, float)):
            frac = min(frac, float(base["exposed_fraction"]))
        est.tag(f"exposed:{cand.overlap}", measured=True)
        return frac
    est.tag(f"exposed:{cand.overlap}", measured=False)
    return _ANALYTIC_EXPOSED.get(cand.overlap, 1.0)


def predict_training(
    cand: TrainCandidate,
    wl: TrainWorkload,
    arts: Optional[ArtifactSet] = None,
    calibration: Optional[Calibration] = None,
) -> Estimate:
    """Predicted seconds per optimizer step for one candidate."""
    est = Estimate(seconds=0.0, parts={}, measured=[], extrapolated=[],
                   notes=[])
    peak_tbl, link_tbl, _ = _device_tables()

    # compute term: data-parallel width divides the batch; model/stage
    # axes divide the per-example flops, so per-device flops only
    # depend on total device count for the dense strategies.
    n = max(1, wl.n_devices)
    if calibration is not None and calibration.base_s is not None:
        t_comp = calibration.base_s
        est.tag("compute", measured=True)
        est.notes.append("compute anchored to measured base candidate")
    else:
        peak = peak_tbl.get(wl.device_kind, next(iter(peak_tbl.values())))
        if wl.precision == "fp32":
            peak = peak / 2.0  # fp32 runs at half the bf16 MXU rate
        t_comp = wl.flops_per_step / (n * peak)
        est.tag("compute", measured=False)

    wire = _wire_bytes(cand, wl, arts, est)
    if calibration is not None and calibration.collective_bytes_per_s:
        bw = calibration.collective_bytes_per_s
        est.tag("link_bw", measured=True)
    else:
        bw = link_tbl.get(wl.device_kind, next(iter(link_tbl.values())))
        est.tag("link_bw", measured=False)

    # Exposure: dp/zero1's grad all-reduce streams DURING backward —
    # the scaling model's own law (benchmarks/scaling_model.py):
    # exposed = max(0, t_comm − t_bwd), t_bwd ≈ 2/3·t_step.  fsdp and
    # the tp regimes use the comm audit's MEASURED exposed fractions.
    t_bwd = (2.0 / 3.0) * t_comp
    if cand.strategy in ("dp", "zero1"):
        ar_wire = _ring_factor(n) * wl.param_bytes
        rest = max(0.0, wire - ar_wire)
        t_comm = max(0.0, ar_wire / bw - t_bwd) + rest / bw
        frac = t_comm * bw / wire if wire > 0 else 0.0
        est.tag("exposed:bwd-overlap", measured=False)
    else:
        frac = _exposed_fraction(cand, arts, est)
        t_comm = wire * frac / bw

    bubble = 0.0
    if cand.strategy == "pp":
        m = cand.microbatches or cand.stages
        bubble = (cand.stages - 1) / (m + cand.stages - 1)

    # state sharding reshapes the COMPUTE term, not just the wire: a
    # sharded optimizer update does 1/n of the replicated math.  Free
    # on real accelerators (parallel chips), real wall time on shared-
    # core hosts — only a measured ratio can tell the two apart.
    m_state = 1.0
    if cand.strategy in ("fsdp", "zero1"):
        if calibration is not None and calibration.state_shard_ratio:
            m_state = float(calibration.state_shard_ratio)
            est.tag("state_sharding", measured=True)
            est.notes.append(
                f"compute scaled by the calibrated zero1/dp step ratio "
                f"{m_state:.3f} (proxy-workload measurement on this "
                f"host)")
        else:
            est.tag("state_sharding", measured=False)

    # dp's anchored base already contains dp's own (small) exposed
    # comm; model every candidate the same way so DELTAS are honest.
    est.parts = {
        "compute_s": t_comp,
        "bubble_frac": bubble,
        "m_state": m_state,
        "wire_bytes": wire,
        "exposed_fraction": frac,
        "exposed_comm_s": t_comm,
    }
    est.seconds = t_comp * m_state * (1.0 + bubble) + t_comm
    if cand.strategy in ("fsdp", "zero1"):
        est.notes.append(
            f"{cand.strategy} is a MEMORY lever — pick it when the "
            "model does not fit replicated, even ranked behind dp")
    return est


# -- serving -----------------------------------------------------------


def _serve_section(arts: Optional[ArtifactSet], key: str,
                   est: Optional[Estimate] = None):
    """Newest BENCH_SERVE round that measured section ``key`` — bench
    rounds are not supersets (r18 froze the kernel twins, r09 the spec
    sweep), so each section resolves independently."""
    if arts is None:
        return None
    val, rnd = arts.section("BENCH_SERVE", key)
    newest = arts.get("BENCH_SERVE")
    if (est is not None and val is not None and newest is not None
            and rnd != newest.round):
        est.notes.append(
            f"{key} quoted from BENCH_SERVE r{rnd:02d} (newest round "
            f"r{newest.round:02d} did not re-measure it)")
    return val


def _block_sweep_rows(sweep) -> List[dict]:
    return [r for r in sweep if isinstance(r, dict)] \
        if isinstance(sweep, list) else []


def _block_multiplier(k: int, arts: Optional[ArtifactSet],
                      calib: Optional[Calibration],
                      est: Estimate) -> float:
    """TPOT multiplier of decode block ``k`` relative to the largest
    measured block (the base config)."""
    rows = _block_sweep_rows(_serve_section(arts, "block_sweep", est))
    by_k = {int(r["decode_block"]): r for r in rows
            if isinstance(r.get("tpot_s_p50"), (int, float))}
    if by_k:
        ref_k = max(by_k)
        ref = float(by_k[ref_k]["tpot_s_p50"])
        if k in by_k and ref > 0:
            est.tag(f"block:K={k}", measured=True)
            return float(by_k[k]["tpot_s_p50"]) / ref
        if ref > 0:
            # interpolate on dispatches/token (∝ 1/K) between the
            # measured endpoints; outside the sweep range, clamp —
            # never extrapolate a trend past its evidence
            ks = sorted(by_k)
            lo, hi = ks[0], ks[-1]
            kk = min(max(k, lo), hi)
            m_lo = float(by_k[lo]["tpot_s_p50"]) / ref
            m_hi = float(by_k[hi]["tpot_s_p50"]) / ref
            if hi != lo:
                w = (1.0 / kk - 1.0 / hi) / (1.0 / lo - 1.0 / hi)
            else:
                w = 0.0
            est.tag(f"block:K={k}", measured=False)
            return m_hi + w * (m_lo - m_hi)
    # no sweep at all: analytic dispatch-amortization model
    est.tag(f"block:K={k}", measured=False)
    h = (calib.dispatch_overhead_s
         if calib is not None and calib.dispatch_overhead_s else 5e-4)
    base_t = 2e-3
    return (base_t + h / k) / (base_t + h / 8)


def _spec_multiplier(cand: ServeCandidate, wl: ServeWorkload,
                     arts: Optional[ArtifactSet], est: Estimate) -> float:
    if cand.spec_layers is None:
        return 1.0
    sweep = _serve_section(arts, "spec_sweep", est) or {}
    floor = sweep.get("floor") or {}
    floor_tpot = floor.get("tpot_s_p50")
    for row in sweep.get("rows") or []:
        if not isinstance(row, dict):
            continue
        if (int(row.get("draft_layers", -1)) == cand.spec_layers
                and int(row.get("k", -1)) == cand.spec_k
                and not row.get("distilled", False)
                and isinstance(row.get("tpot_s_p50"), (int, float))
                and isinstance(floor_tpot, (int, float))
                and floor_tpot > 0):
            est.tag(f"spec:{cand.spec_layers}x{cand.spec_k}",
                    measured=True)
            return float(row["tpot_s_p50"]) / float(floor_tpot)
    # analytic: a tied draft accepts ~1 + 0.25·K tokens per pass at
    # best; each pass costs one verify plus K draft passes
    est.tag(f"spec:{cand.spec_layers}x{cand.spec_k}", measured=False)
    draft_frac = cand.spec_layers / max(1, wl.n_layers)
    accepted = 1.0 + 0.25 * cand.spec_k
    return max(1e-9, (1.0 + cand.spec_k * draft_frac) / accepted)


def _kernel_multipliers(cand: ServeCandidate, arts: Optional[ArtifactSet],
                        est: Estimate) -> Tuple[float, float]:
    """(tpot multiplier, ttft multiplier) from the kernel-family twins."""
    m_tpot, m_ttft = 1.0, 1.0
    twin = _serve_section(arts, "kernel_family_twin", est) or {}
    attn = _serve_section(arts, "attn_kernel_twin", est) or {}

    def _ratio(section: dict, key: str) -> Optional[float]:
        base = section.get("base") or section.get("gather") or {}
        fused = section.get("fused") or section.get("kernel") or {}
        b, f = base.get(key), fused.get(key)
        if isinstance(b, (int, float)) and isinstance(f, (int, float)) \
                and b > 0:
            return float(f) / float(b)
        return None

    if cand.attn_kernel == "paged":
        r = _ratio(attn, "tpot_busy_s")
        if r is None and isinstance(attn, dict):
            # r18 twin stores tokens/s, invert it
            g, k = attn.get("tokens_per_s_gather"), attn.get(
                "tokens_per_s_kernel")
            if isinstance(g, (int, float)) and isinstance(
                    k, (int, float)) and k > 0:
                r = float(g) / float(k)
        if r is not None:
            est.tag("attn_kernel", measured=True)
            m_tpot *= r
        else:
            est.tag("attn_kernel", measured=False)
            est.notes.append(
                "attn_kernel='paged' wall twin unmeasured — neutral 1.0 "
                "(bytes/token curve says it wins at long live KV)")
    for name, flag, affects_ttft in (
            ("prefill", cand.prefill_kernel, True),
            ("sample", cand.sample_kernel, False),
            ("rope_qkv", cand.fused_rope, False)):
        if not flag:
            continue
        sec = twin.get(name) or {}
        r = _ratio(sec, "tpot_busy_s")
        rt = _ratio(sec, "ttft_s_p50")
        if r is not None:
            est.tag(f"kernel:{name}", measured=True)
            m_tpot *= r
            if affects_ttft:
                m_ttft *= rt if rt is not None else r
        else:
            est.tag(f"kernel:{name}", measured=False)
            est.notes.append(
                f"kernel arm {name!r}: no measured twin — neutral 1.0")
    return m_tpot, m_ttft


def predict_serving(
    cand: ServeCandidate,
    wl: ServeWorkload,
    arts: Optional[ArtifactSet] = None,
    calibration: Optional[Calibration] = None,
) -> Tuple[Estimate, Estimate]:
    """Predicted ``(tpot, ttft)`` for one serving candidate.

    The TPOT estimate is the ranking key; TTFT rides along with the
    prefill-side multipliers applied.
    """
    est = Estimate(seconds=0.0, parts={}, measured=[], extrapolated=[],
                   notes=[])
    _, _, hbm_tbl = _device_tables()

    # base TPOT: measured anchor > artifact floor > HBM roofline
    if calibration is not None and calibration.base_s is not None:
        base = calibration.base_s
        est.tag("base_tpot", measured=True)
    else:
        floor = (_serve_section(arts, "spec_sweep", est) or {}).get(
            "floor") or {}
        if isinstance(floor.get("tpot_s_p50"), (int, float)):
            base = float(floor["tpot_s_p50"])
            est.tag("base_tpot", measured=True)
            est.notes.append(
                "base TPOT quoted from the frozen BENCH_SERVE floor — "
                "its geometry, not necessarily yours")
        else:
            hbm = hbm_tbl.get(wl.device_kind,
                              next(iter(hbm_tbl.values())))
            per_tok = (wl.weight_bytes
                       + wl.slots * wl.max_len * wl.kv_bytes_per_pos) \
                / max(1, wl.n_devices)
            base = per_tok / hbm
            est.tag("base_tpot", measured=False)

    m_block = _block_multiplier(cand.decode_block, arts, calibration, est)
    m_spec = _spec_multiplier(cand, wl, arts, est)
    m_kern, m_ttft_kern = _kernel_multipliers(cand, arts, est)

    # paged-vs-dense and int8: byte-level evidence exists (ROOFLINE
    # paged rows, the kv_dtype sweep) but no wall twin — neutral, noted.
    m_paged = 1.0
    if cand.paged:
        est.tag("paged", measured=False)
        est.notes.append(
            "paged cache: wall twin unmeasured — neutral 1.0 (capacity "
            "and live-KV bytes are its wins, not raw TPOT)")
    if cand.kv_int8:
        sweep = _serve_section(arts, "kv_dtype_sweep", est) or {}
        rows = sweep if isinstance(sweep, list) else sweep.get("rows") or []
        ratio = None
        bpp = {}
        for r in rows:
            if isinstance(r, dict) and isinstance(
                    r.get("bytes_per_pos"), (int, float)):
                bpp[r.get("kv_dtype", r.get("dtype"))] = float(
                    r["bytes_per_pos"])
        if "native" in bpp and "int8" in bpp and bpp["int8"] > 0:
            ratio = bpp["native"] / bpp["int8"]
        est.tag("kv_int8", measured=False)
        est.notes.append(
            "int8 KV: wall twin unmeasured — neutral 1.0"
            + (f" (measured bytes/pos win: {ratio:.2f}x)" if ratio
               else ""))
    m_slots = 1.0
    if cand.slots != wl.slots:
        # more lanes amortize the weight stream over more tokens —
        # analytic, HBM-roofline shaped
        kv_tok = wl.max_len * wl.kv_bytes_per_pos
        w = wl.weight_bytes / max(1, wl.n_devices)
        m_slots = ((w / cand.slots + kv_tok)
                   / (w / wl.slots + kv_tok))
        m_slots = max(0.5, min(2.0, m_slots))
        est.tag("slots", measured=False)

    est.parts = {
        "base_tpot_s": base,
        "m_block": m_block,
        "m_spec": m_spec,
        "m_kernels": m_kern,
        "m_paged": m_paged,
        "m_slots": m_slots,
    }
    est.seconds = base * m_block * m_spec * m_kern * m_paged * m_slots

    ttft = Estimate(seconds=0.0, parts={}, measured=list(est.measured),
                    extrapolated=list(est.extrapolated), notes=[])
    # TTFT: one prefill pass over the prompt at the compute/byte floor,
    # scaled by the prefill-side kernel twin when that arm is on
    base_ttft = base * max(1, wl.prompt_len) / max(1, cand.decode_block)
    floor = (_serve_section(arts, "spec_sweep") or {}).get("floor") or {}
    if isinstance(floor.get("ttft_s_p50"), (int, float)) and (
            calibration is None or calibration.base_s is None):
        base_ttft = float(floor["ttft_s_p50"])
        ttft.tag("base_ttft", measured=True)
    else:
        ttft.tag("base_ttft", measured=False)
    ttft.parts = {"base_ttft_s": base_ttft, "m_kernels": m_ttft_kern}
    ttft.seconds = base_ttft * m_ttft_kern
    return est, ttft
