"""``python -m tpudist.plan`` — the offline ranked table.

Scores the legal config space for a described workload against the
frozen measurement artifacts and prints the ranked prediction table,
with provenance (artifact rounds, measured-vs-extrapolated components,
the frozen prediction-error band) inline.  No devices are touched —
this is pure JSON-in, table-out.

Examples::

    python -m tpudist.plan --workload training --devices 8 \
        --param-bytes 4e8
    python -m tpudist.plan --workload serving --d-model 256 \
        --n-layers 4 --max-len 512 --spec-layers 1
    python -m tpudist.plan --workload both --json
"""

from __future__ import annotations

import argparse
import json
import sys

from tpudist.plan import artifacts as _artifacts
from tpudist.plan import cost as _cost
from tpudist.plan import planner as _planner


def _train_report(args, arts):
    pb = float(args.param_bytes)
    wl = _cost.TrainWorkload(
        param_bytes=pb,
        flops_per_step=6.0 * (pb / 4.0) * args.batch * args.seq_len,
        n_devices=args.devices, global_batch=args.batch,
        lm=not args.toy, precision=args.precision)
    return _planner.plan_training(wl, arts, top_n=args.top_n)


def _serve_report(args, arts):
    d, L = args.d_model, args.n_layers
    heads = max(2, d // 64)
    wl = _cost.ServeWorkload(
        weight_bytes=4.0 * (args.vocab * d + L * 12 * d * d),
        kv_bytes_per_pos=2.0 * L * d * 4,
        n_layers=L, max_len=args.max_len, n_devices=args.devices,
        slots=args.slots, prompt_len=args.prompt_len)
    del heads
    return _planner.plan_serving(
        wl, arts, top_n=args.top_n,
        decode_blocks=tuple(int(k) for k in args.blocks.split(",")),
        spec_layers=(args.spec_layers,) if args.spec_layers else (),
        include_kernels=args.kernels, include_int8=args.int8)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpudist.plan",
        description=__doc__.split("\n")[0])
    p.add_argument("--workload", choices=("training", "serving", "both"),
                   default="both")
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--top-n", type=int, default=None,
                   help="rows to print (default TPUDIST_PLAN_TOPN or all)")
    p.add_argument("--json", action="store_true",
                   help="machine form: one JSON object instead of tables")
    # training workload shape
    p.add_argument("--param-bytes", default=4e8,
                   help="model parameter bytes (training)")
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--precision", choices=("fp32", "bf16"), default="fp32")
    p.add_argument("--toy", action="store_true",
                   help="multi-model toy module (opens dp_model, "
                        "closes pp)")
    # serving workload shape
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--prompt-len", type=int, default=32)
    p.add_argument("--blocks", default="1,4,8")
    p.add_argument("--spec-layers", type=int, default=0,
                   help="tied-draft depth to include spec candidates")
    p.add_argument("--kernels", action="store_true",
                   help="include the Pallas kernel arms")
    p.add_argument("--int8", action="store_true",
                   help="include int8 KV candidates")
    args = p.parse_args(argv)

    arts = _artifacts.load_artifacts()
    reports = {}
    if args.workload in ("training", "both"):
        reports["training"] = _train_report(args, arts)
    if args.workload in ("serving", "both"):
        reports["serving"] = _serve_report(args, arts)

    if args.json:
        out = {}
        for kind, rep in reports.items():
            out[kind] = {
                "best": rep.best.candidate.name,
                "stamp": rep.stamp(),
                "ranked": [
                    {"rank": r.rank, "config": r.candidate.name,
                     "predicted_s": r.estimate.seconds,
                     "measured": r.estimate.measured,
                     "extrapolated": r.estimate.extrapolated}
                    for r in rep.ranked],
            }
        print(json.dumps(out, indent=1))
    else:
        for i, rep in enumerate(reports.values()):
            if i:
                print()
            print(rep.table())
    return 0


if __name__ == "__main__":
    sys.exit(main())
