"""The legal configuration space the planner scores.

One rule here for every refusal the runtime enforces — the planner must
never rank a config the Trainer or SlotEngine would raise on:

training (mirrors ``tpudist/trainer/trainer.py``):

- ``dp_model`` is the toy split-MLP layout — refused for LM modules;
- ``pp`` needs an LMTrainerModule (blocks shard over stages) and
  refuses ``precision='bf16'``;
- ``pp`` stage width must divide the device count; microbatches must
  be a multiple the schedule can fill;
- overlap modes attach only to regimes that HAVE an overlapped twin in
  the comm audit (fsdp ring/bidir) — and are emitted only when
  ``actionable=False``, because the Trainer facade does not expose an
  overlap knob yet (the CLI table shows them; auto mode must only pick
  what it can enact).

serving (mirrors ``tpudist/serve/engine.py``):

- ``attn_kernel='paged'`` and ``prefill_kernel`` require the paged
  cache; ``fused_rope`` requires a kernel arm;
- ``kv_block`` must divide ``max_len``;
- kernel arms and spec drafts are emitted only when requested —
  ``SlotEngine(auto=True)`` cannot invent a draft module the caller
  did not provide.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from tpudist.plan.cost import (
    ServeCandidate,
    ServeWorkload,
    TrainCandidate,
    TrainWorkload,
)

TRAIN_STRATEGIES = ("dp", "dp_model", "fsdp", "zero1", "pp")


def _divisors(n: int, *, floor: int = 2) -> List[int]:
    return [d for d in range(floor, n + 1) if n % d == 0]


def training_candidates(
    wl: TrainWorkload,
    *,
    strategies: Optional[Sequence[str]] = None,
    actionable: bool = False,
    stages: Sequence[int] = (2,),
) -> List[TrainCandidate]:
    """Legal training candidates for ``wl``.

    ``actionable=True`` restricts to configs ``Trainer`` can enact
    today (the auto-mode contract); the full space (overlap modes,
    stage/microbatch sweeps) is for the offline table.
    """
    strategies = tuple(strategies or TRAIN_STRATEGIES)
    n = wl.n_devices
    out: List[TrainCandidate] = []
    for s in strategies:
        if s == "dp":
            out.append(TrainCandidate(strategy="dp"))
        elif s in ("fsdp", "zero1"):
            if n < 2:
                continue  # sharding one device is the dp config
            out.append(TrainCandidate(strategy=s))
            if s == "fsdp" and not actionable:
                # the audit measured ring/bidir overlapped fsdp twins;
                # the facade cannot switch them on yet — table-only
                out.append(TrainCandidate(strategy="fsdp", overlap="ring"))
                out.append(TrainCandidate(strategy="fsdp", overlap="bidir"))
        elif s == "dp_model":
            if wl.lm:
                continue  # refused: dp_model is the toy split-MLP layout
            for mp in _divisors(n):
                if mp < n:  # keep a data axis
                    out.append(TrainCandidate(strategy="dp_model",
                                              model_parallel=mp))
        elif s == "pp":
            if not wl.lm or wl.precision == "bf16":
                continue  # pp needs LM blocks; pp×bf16 is refused
            for st in stages:
                if st < 2 or n % st:
                    continue
                data = n // st
                # microbatches must divide the per-step batch the data
                # axis leaves to the schedule
                per_data = wl.global_batch // max(1, data)
                for micro in (st, 2 * st):
                    if per_data and micro > per_data:
                        continue
                    out.append(TrainCandidate(
                        strategy="pp", stages=st, microbatches=micro))
    return out


def serving_candidates(
    wl: ServeWorkload,
    *,
    decode_blocks: Sequence[int] = (1, 4, 8),
    paged: Sequence[bool] = (False, True),
    kv_blocks: Sequence[int] = (16,),
    spec_layers: Sequence[int] = (),
    spec_ks: Sequence[int] = (4, 8),
    include_kernels: bool = False,
    include_int8: bool = False,
    slots: Optional[Sequence[int]] = None,
) -> List[ServeCandidate]:
    """Legal serving candidates for ``wl``.

    ``spec_layers`` is empty by default: speculative decode needs a
    draft, and auto mode only enumerates spec points when the caller
    actually provided one (``spec_draft=``/``spec_draft_layers``).
    """
    out: List[ServeCandidate] = []
    slot_opts = tuple(slots or (wl.slots,))
    for p in paged:
        kb_opts = [kb for kb in kv_blocks if wl.max_len % kb == 0] \
            if p else [16]
        if p and not kb_opts:
            continue  # no legal block size divides max_len
        attn_opts = ["gather"]
        if p and include_kernels:
            attn_opts.append("paged")
        for kb in kb_opts:
            for attn in attn_opts:
                prefill_opts = [False]
                if p and include_kernels:
                    prefill_opts.append(True)
                for pk in prefill_opts:
                    rope_opts = [False]
                    if include_kernels and (attn == "paged" or pk):
                        rope_opts.append(True)
                    int8_opts = [False] + ([True] if include_int8 else [])
                    for rope in rope_opts:
                        for i8 in int8_opts:
                            for k in decode_blocks:
                                for ns in slot_opts:
                                    out.append(ServeCandidate(
                                        decode_block=k, paged=p,
                                        kv_block=kb, kv_int8=i8,
                                        attn_kernel=attn,
                                        prefill_kernel=pk,
                                        fused_rope=rope,
                                        slots=ns))
    base = list(out)
    for sl in spec_layers:
        if not 1 <= sl < wl.n_layers:
            continue  # a draft as deep as the target is not a draft
        for sk in spec_ks:
            for c in base:
                if c.paged or c.kv_int8 or c.attn_kernel != "gather" \
                        or c.prefill_kernel or c.fused_rope:
                    continue  # spec sweeps were measured on the dense arm
                out.append(ServeCandidate(
                    decode_block=c.decode_block, paged=False,
                    kv_block=c.kv_block, slots=c.slots,
                    spec_layers=sl, spec_k=sk))
    return out
