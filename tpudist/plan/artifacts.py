"""Typed loader for the repo's frozen measurement artifacts.

The benchmarks freeze one JSON per family per round at the repo root —
``COMM_AUDIT_r08.json``, ``SCALING_MODEL_r05.json``,
``ROOFLINE_r18.json``, ``BENCH_SERVE_r09.json``, ... — in two physical
forms: a single JSON dict (most families) or JSONL rows
(``BENCH_SESSION``, ``BENCH_ADAPTER``).  This module is the ONE place
that knows how to find, parse, and validate them; the cost model only
ever sees :class:`Artifact` objects.

Selection and validation contract (the loud parts are deliberate):

- **newest round wins** per family; older rounds are recorded as
  ``superseded`` (not errors — history is supposed to accumulate).
- **declared metadata beats filename parsing**: artifacts written since
  the header convention landed carry ``{"artifact": {"schema", "family",
  "round", "geometry"}}`` (dict form: a top-level key; JSONL form: the
  first line).  A header that CONTRADICTS the filename means the file
  was renamed or hand-edited — rejected loudly, never trusted.
- **stale artifacts rejected loudly**: a family whose newest round
  trails the overall newest round by more than ``stale_rounds``
  (``TPUDIST_PLAN_STALE_ROUNDS``, default 20) no longer describes this
  codebase; it is rejected with a warning, and the cost model degrades
  to its analytic formula for that input — with an ``unmeasured`` flag
  in the plan report, never silently.
- **foreign geometry rejected loudly**: pass ``expect_geometry`` (e.g.
  ``{"n_devices": 8}``) and any artifact whose declared geometry
  contradicts it on an overlapping key is rejected.
- **missing families degrade, never raise** — unless ``strict``
  (``TPUDIST_PLAN_STRICT=1``), where :meth:`ArtifactSet.require`
  raises :class:`PlanArtifactError` naming what was rejected and why.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import warnings
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from tpudist.utils.envutil import env_flag, env_int

#: Header schema version this loader understands (satellite of ISSUE 20:
#: round_snapshot stamps this into every future artifact write).
ARTIFACT_SCHEMA = 1

#: Families the planner consumes.  Other frozen files (PARITY, BANDS,
#: MULTICHIP, ...) are evidence for humans, not cost-model inputs.
FAMILIES = (
    "SCALING_MODEL",
    "COMM_AUDIT",
    "ROOFLINE",
    "DECODE_PROFILE",
    "BENCH_SERVE",
    "BENCH_SESSION",
    "BENCH_ADAPTER",
    "PLAN",
)

#: Geometry keys compared for the foreign-geometry check.  Only keys
#: PRESENT ON BOTH SIDES are compared — an artifact that never declared
#: ``device_kind`` is not foreign to a query that does.
GEOMETRY_KEYS = ("platform", "n_devices", "device_kind")

_NAME_RE = re.compile(r"^([A-Z][A-Z0-9_]*?)_r(\d+)\.json$")


class PlanArtifactError(RuntimeError):
    """A required measurement artifact is missing or was rejected."""


@dataclasses.dataclass
class Rejection:
    path: Path
    reason: str


@dataclasses.dataclass
class Artifact:
    """One frozen measurement file, parsed and validated."""

    family: str
    round: int
    path: Path
    #: dict form: the parsed JSON object.  JSONL form: ``{"rows": [...]}``
    #: (header line, if any, lifted out into :attr:`header`).
    data: dict
    header: Optional[dict] = None

    @property
    def geometry(self) -> dict:
        """Declared geometry: header first, then the conventional
        top-level keys the older (pre-header) artifacts carry."""
        if self.header and isinstance(self.header.get("geometry"), dict):
            return dict(self.header["geometry"])
        out = {}
        for k in GEOMETRY_KEYS:
            if k in self.data:
                out[k] = self.data[k]
        g = self.data.get("geometry")
        if isinstance(g, dict):
            for k in GEOMETRY_KEYS:
                if k in g:
                    out.setdefault(k, g[k])
        return {k: v for k, v in out.items() if v is not None}

    @property
    def rows(self) -> List[dict]:
        r = self.data.get("rows")
        return r if isinstance(r, list) else []


@dataclasses.dataclass
class ArtifactSet:
    """Everything :func:`load_artifacts` found, kept, and refused."""

    root: Path
    by_family: Dict[str, Artifact]
    rejected: List[Rejection]
    superseded: List[Path]
    #: family → every VALID round, newest first (``by_family`` holds the
    #: head).  Sections that only older rounds measured are reachable
    #: through :meth:`section` without weakening newest-round-wins for
    #: anything the newest round does carry.
    history: Dict[str, List[Artifact]] = dataclasses.field(
        default_factory=dict)

    def get(self, family: str) -> Optional[Artifact]:
        return self.by_family.get(family)

    def section(self, family: str, key: str
                ) -> Tuple[Optional[object], Optional[int]]:
        """Newest round of ``family`` that MEASURED section ``key``.

        Benchmark rounds are not supersets of each other (r18 froze the
        kernel twins, r09 the spec sweep) — "newest round wins" means
        the newest round that actually measured the thing.  Returns
        ``(value, round)`` or ``(None, None)``."""
        for a in self.history.get(family, []):
            v = a.data.get(key)
            if v not in (None, {}, []):
                return v, a.round
        return None, None

    def require(self, family: str) -> Artifact:
        a = self.by_family.get(family)
        if a is None:
            why = "; ".join(
                f"{r.path.name}: {r.reason}" for r in self.rejected
                if r.path.name.startswith(family + "_r")) or "no file found"
            raise PlanArtifactError(
                f"required artifact family {family!r} unavailable under "
                f"{self.root} ({why}) — run the benchmarks "
                f"(benchmarks/round_snapshot.py) or unset "
                f"TPUDIST_PLAN_STRICT to degrade to the analytic model")
        return a

    def rounds(self) -> Dict[str, int]:
        """family → round actually loaded (the provenance line every
        plan report quotes)."""
        return {f: a.round for f, a in sorted(self.by_family.items())}

    def missing(self, families: Sequence[str]) -> List[str]:
        return [f for f in families if f not in self.by_family]


def default_root() -> Path:
    """Artifact directory: ``TPUDIST_PLAN_DIR`` else the repo root (the
    directory the benchmarks freeze into)."""
    env = os.environ.get("TPUDIST_PLAN_DIR")
    if env:
        return Path(env)
    return Path(__file__).resolve().parents[2]


def _parse(path: Path) -> Tuple[dict, Optional[dict]]:
    """Parse either physical form; return ``(data, header)``."""
    text = path.read_text()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        header = obj.get("artifact")
        return obj, header if isinstance(header, dict) else None
    if isinstance(obj, list):
        return {"rows": obj}, None
    # JSONL: one object per line; an optional leading header line
    rows = []
    header = None
    for i, line in enumerate(text.splitlines()):
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        if i == 0 and isinstance(row, dict) and isinstance(
                row.get("artifact"), dict) and len(row) == 1:
            header = row["artifact"]
            continue
        rows.append(row)
    return {"rows": rows}, header


def geometry_conflicts(declared: dict, expected: dict) -> List[str]:
    """Keys present on BOTH sides with contradicting values."""
    out = []
    for k in GEOMETRY_KEYS:
        if k in declared and k in expected and declared[k] != expected[k]:
            out.append(f"{k}={declared[k]!r} (expected {expected[k]!r})")
    return out


def load_artifacts(
    root: "str | Path | None" = None,
    *,
    families: Sequence[str] = FAMILIES,
    expect_geometry: Optional[dict] = None,
    stale_rounds: Optional[int] = None,
    strict: Optional[bool] = None,
) -> ArtifactSet:
    """Scan ``root`` for ``<FAMILY>_rNN.json`` and build the set.

    Every refusal lands in ``rejected`` AND raises a ``UserWarning`` —
    a planner silently ignoring evidence would be worse than no planner.
    ``strict`` (default ``TPUDIST_PLAN_STRICT``) additionally makes
    :meth:`ArtifactSet.require` the access path callers should use.
    """
    root = Path(root) if root is not None else default_root()
    if stale_rounds is None:
        stale_rounds = env_int("TPUDIST_PLAN_STALE_ROUNDS", 20)
    if strict is None:
        strict = env_flag("TPUDIST_PLAN_STRICT", False)

    found: Dict[str, List[Tuple[int, Path]]] = {}
    for p in sorted(root.glob("*_r*.json")):
        m = _NAME_RE.match(p.name)
        if not m or m.group(1) not in families:
            continue
        found.setdefault(m.group(1), []).append((int(m.group(2)), p))

    newest_overall = max(
        (r for cands in found.values() for r, _ in cands), default=0)

    rejected: List[Rejection] = []
    superseded: List[Path] = []
    by_family: Dict[str, Artifact] = {}
    history: Dict[str, List[Artifact]] = {}

    def _reject(path: Path, reason: str) -> None:
        rejected.append(Rejection(path=path, reason=reason))
        warnings.warn(
            f"tpudist.plan: rejected artifact {path.name}: {reason}",
            stacklevel=3)

    for family, cands in found.items():
        # newest round wins; walk downward so a rejected newest round
        # falls back to the next one (still loudly).  Valid older
        # rounds stay reachable through ArtifactSet.section.
        for rnd, path in sorted(cands, reverse=True):
            if newest_overall - rnd > stale_rounds:
                _reject(path, f"stale: round r{rnd:02d} trails newest "
                              f"r{newest_overall:02d} by more than "
                              f"{stale_rounds} rounds "
                              f"(TPUDIST_PLAN_STALE_ROUNDS)")
                continue
            try:
                data, header = _parse(path)
            except (json.JSONDecodeError, OSError) as e:
                _reject(path, f"unparseable: {e}")
                continue
            if header is not None:
                hfam, hrnd = header.get("family"), header.get("round")
                if hfam is not None and hfam != family:
                    _reject(path, f"declared family {hfam!r} contradicts "
                                  f"filename family {family!r}")
                    continue
                if hrnd is not None and int(hrnd) != rnd:
                    _reject(path, f"declared round r{int(hrnd):02d} "
                                  f"contradicts filename round r{rnd:02d}")
                    continue
                hschema = header.get("schema")
                if hschema is not None and int(hschema) > ARTIFACT_SCHEMA:
                    _reject(path, f"schema {hschema} is newer than this "
                                  f"loader understands "
                                  f"({ARTIFACT_SCHEMA})")
                    continue
            art = Artifact(family=family, round=rnd, path=path,
                           data=data, header=header)
            if expect_geometry:
                conflicts = geometry_conflicts(art.geometry, expect_geometry)
                if conflicts:
                    _reject(path,
                            "foreign geometry: " + ", ".join(conflicts))
                    continue
            if family in by_family:
                superseded.append(path)
            else:
                by_family[family] = art
            history.setdefault(family, []).append(art)

    out = ArtifactSet(root=root, by_family=by_family,
                      rejected=rejected, superseded=superseded,
                      history=history)
    if strict:
        missing = out.missing(families)
        if missing:
            # strict callers want the failure at load time, not at the
            # first degraded estimate
            raise PlanArtifactError(
                f"TPUDIST_PLAN_STRICT: missing artifact families "
                f"{missing} under {root}")
    return out
