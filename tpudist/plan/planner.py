"""Score, rank, report — and the ``auto`` entry points.

A plan is a ranked table of :class:`PlannedConfig` rows plus the
provenance a reader needs to trust (or distrust) it: which artifact
rounds fed the prediction, which components were measured vs
extrapolated, and the prediction-error band the LAST frozen plan_bench
rung (``PLAN_rNN.json``) measured for this model family.

Auto-mode contract (the part wired into the runtime):

- ``Trainer(strategy="auto")`` → :func:`resolve_trainer_auto` picks
  among the strategies the facade can enact for the module kind and
  assigns ``trainer.strategy`` (+ pp fields when pp wins).  The chosen
  plan stamps into telemetry as a ``plan_selected`` event the moment
  the training loop's session is live, so every report can show
  prediction next to the measured step time.
- ``SlotEngine(auto=True)`` → :func:`resolve_engine_auto` fills the
  engine's performance knobs (decode block, paged/kv geometry, kernel
  arms, spec K) for whatever the caller did not explicitly pin;
  ``InferenceServer.start()`` stamps the plan.
- Ties break toward the SIMPLER config (fewer moving parts), and a
  knob with no measured wall evidence predicts neutral — the planner
  never claims a win it has not measured (cost.py's contract).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from tpudist.plan import artifacts as _artifacts
from tpudist.plan import cost as _cost
from tpudist.plan import enumerate as _enum
from tpudist.utils.envutil import env_int


@dataclasses.dataclass
class PlannedConfig:
    candidate: object            # TrainCandidate | ServeCandidate
    estimate: _cost.Estimate
    rank: int = 0
    ttft: Optional[_cost.Estimate] = None


def _complexity(c) -> int:
    """Non-default field count — the moving-parts tiebreak metric."""
    return sum(1 for f in dataclasses.fields(c)
               if getattr(c, f.name) != f.default)


@dataclasses.dataclass
class PlanReport:
    kind: str                    # "training" | "serving"
    ranked: List[PlannedConfig]
    artifact_rounds: Dict[str, int]
    unmeasured: List[str]
    rejected: List[str]
    error_band: Optional[dict] = None
    #: set by :meth:`pick` — the config auto mode enacts (rank 1 unless
    #: the tie rule promoted a simpler near-equal)
    chosen: Optional[PlannedConfig] = None

    @property
    def best(self) -> PlannedConfig:
        return self.chosen if self.chosen is not None else self.ranked[0]

    def pick(self, tie_s: float = 1e-4) -> PlannedConfig:
        """The auto-mode choice: rank 1, UNLESS other candidates predict
        within ``tie_s`` seconds of it — deltas below the per-dispatch
        host-overhead floor are extrapolation noise, not findings — in
        which case the simplest tied config wins.  (A planner should
        only buy complexity with a measurable prediction.)"""
        top = self.ranked[0]
        tied = [p for p in self.ranked
                if p.estimate.seconds - top.estimate.seconds <= tie_s]
        tied.sort(key=lambda p: (_complexity(p.candidate),
                                 p.estimate.seconds))
        self.chosen = tied[0]
        if self.chosen is not top:
            self.chosen.estimate.notes.append(
                f"picked over rank-1 {top.candidate.name!r}: predicted "
                f"delta "
                f"{self.chosen.estimate.seconds - top.estimate.seconds:.2e}"
                f"s is under the {tie_s:.0e}s tie threshold — simplest "
                f"tied config wins")
        return self.chosen

    def stamp(self) -> dict:
        """Flat tags for the ``plan_selected`` telemetry event — the
        prediction a report can later sit next to the measurement."""
        best = self.best
        out = {
            # "kind" is a RESERVED telemetry record key — the workload
            # kind travels as "workload" (the adapter-stamp precedent)
            "workload": self.kind,
            "chosen": best.candidate.name,
            "predicted_s": round(best.estimate.seconds, 6),
            "n_candidates": len(self.ranked),
            "measured_components": len(best.estimate.measured),
            "extrapolated_components": len(best.estimate.extrapolated),
            "artifact_rounds": ",".join(
                f"{f}:r{r:02d}"
                for f, r in sorted(self.artifact_rounds.items())),
        }
        if self.kind == "serving" and best.ttft is not None:
            out["predicted_ttft_s"] = round(best.ttft.seconds, 6)
        if self.error_band and isinstance(
                self.error_band.get("max_frac"), (int, float)):
            out["error_band_frac"] = round(
                float(self.error_band["max_frac"]), 4)
        return out

    def table(self) -> str:
        """The ranked table ``python -m tpudist.plan`` prints."""
        unit = "step" if self.kind == "training" else "TPOT"
        lines = [f"# {self.kind} plan — predicted {unit} seconds",
                 f"# artifacts: " + (", ".join(
                     f"{f}:r{r:02d}" for f, r in sorted(
                         self.artifact_rounds.items())) or "NONE"), ]
        if self.unmeasured:
            lines.append("# unmeasured (analytic fallback): "
                         + ", ".join(sorted(set(self.unmeasured))))
        if self.rejected:
            lines.append("# rejected artifacts: " + "; ".join(self.rejected))
        if self.error_band:
            mx = self.error_band.get("max_frac")
            src = self.error_band.get("source", "PLAN rung")
            if isinstance(mx, (int, float)):
                lines.append(f"# prediction error band: ±{mx:.1%} "
                             f"(measured by {src})")
        else:
            lines.append("# prediction error band: unknown — no frozen "
                         "PLAN rung (run benchmarks/plan_bench.py)")
        w = max((len(p.candidate.name) for p in self.ranked), default=8)
        lines.append(f"{'rank':>4}  {'config':<{w}}  {'pred_s':>12}  "
                     f"evidence")
        for p in self.ranked:
            ev = f"{len(p.estimate.measured)} measured"
            if p.estimate.extrapolated:
                ev += f", {len(p.estimate.extrapolated)} extrapolated"
            lines.append(f"{p.rank:>4}  {p.candidate.name:<{w}}  "
                         f"{p.estimate.seconds:>12.6f}  {ev}")
        for p in self.ranked:
            for note in p.estimate.notes:
                lines.append(f"# note[{p.candidate.name}]: {note}")
        return "\n".join(lines)


def _error_band(arts: Optional[_artifacts.ArtifactSet],
                kind: str) -> Optional[dict]:
    """Quote the prediction-vs-measured band the frozen plan_bench rung
    carries (the planner's own honesty loop)."""
    if arts is None:
        return None
    a = arts.get("PLAN")
    if a is None:
        return None
    sec = a.data.get(kind) or {}
    band = sec.get("error_band") or a.data.get(
        "summary", {}).get("error_band", {}).get(kind)
    if isinstance(band, dict) and isinstance(
            band.get("max_frac"), (int, float)):
        return {**band, "source": a.path.name}
    return None


def _finish(kind: str, rows: List[Tuple[object, _cost.Estimate,
                                        Optional[_cost.Estimate]]],
            arts: Optional[_artifacts.ArtifactSet],
            top_n: Optional[int]) -> PlanReport:
    rows = sorted(rows, key=lambda r: (r[1].seconds, _complexity(r[0])))
    if top_n is None:
        top_n = env_int("TPUDIST_PLAN_TOPN", 0) or len(rows)
    ranked = [PlannedConfig(candidate=c, estimate=e, ttft=t, rank=i + 1)
              for i, (c, e, t) in enumerate(rows[:max(1, top_n)])]
    unmeasured = sorted({x for _, e, _ in rows for x in e.extrapolated})
    return PlanReport(
        kind=kind, ranked=ranked,
        artifact_rounds=arts.rounds() if arts is not None else {},
        unmeasured=unmeasured,
        rejected=[f"{r.path.name}: {r.reason}"
                  for r in (arts.rejected if arts is not None else [])],
        error_band=_error_band(arts, kind))


def plan_training(
    wl: _cost.TrainWorkload,
    arts: Optional[_artifacts.ArtifactSet] = None,
    *,
    candidates: Optional[Sequence[_cost.TrainCandidate]] = None,
    calibration: Optional[_cost.Calibration] = None,
    actionable: bool = False,
    top_n: Optional[int] = None,
) -> PlanReport:
    if arts is None:
        arts = _artifacts.load_artifacts()
    if candidates is None:
        candidates = _enum.training_candidates(wl, actionable=actionable)
    rows = [(c, _cost.predict_training(c, wl, arts, calibration), None)
            for c in candidates]
    return _finish("training", rows, arts, top_n)


def plan_serving(
    wl: _cost.ServeWorkload,
    arts: Optional[_artifacts.ArtifactSet] = None,
    *,
    candidates: Optional[Sequence[_cost.ServeCandidate]] = None,
    calibration: Optional[_cost.Calibration] = None,
    top_n: Optional[int] = None,
    **enum_kw,
) -> PlanReport:
    if arts is None:
        arts = _artifacts.load_artifacts()
    if candidates is None:
        candidates = _enum.serving_candidates(wl, **enum_kw)
    rows = []
    for c in candidates:
        tpot, ttft = _cost.predict_serving(c, wl, arts, calibration)
        rows.append((c, tpot, ttft))
    return _finish("serving", rows, arts, top_n)


# -- runtime wiring -----------------------------------------------------


def _param_bytes(shapes) -> float:
    import numpy as np

    total = 0.0
    for leaf in _tree_leaves(shapes):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        dt = getattr(leaf, "dtype", None)
        size = np.dtype(dt).itemsize if dt is not None else 4
        n = 1
        for d in shape:
            n *= int(d)
        total += n * size
    return total


def _tree_leaves(tree):
    import jax

    return jax.tree.leaves(tree)


def trainer_workload(module, seed: int, n_devices: int,
                     precision: str = "fp32",
                     global_batch: int = 8) -> _cost.TrainWorkload:
    """Build a :class:`TrainWorkload` from a TrainerModule WITHOUT
    materializing parameters (``eval_shape``); falls back to a real
    ``configure_*`` call for modules whose init resists tracing."""
    import jax

    from tpudist.trainer.trainer import LMTrainerModule

    lm = isinstance(module, LMTrainerModule)
    rng = jax.random.PRNGKey(seed)
    if lm:
        def shapes_of(r):
            return module.configure_lm(r)[1]
    else:
        def shapes_of(r):
            return {k: p for k, (_, p)
                    in module.configure_models(r).items()}
    try:
        shapes = jax.eval_shape(shapes_of, rng)
    except Exception:
        shapes = shapes_of(rng)
    pb = _param_bytes(shapes)
    # fwd+bwd ≈ 6 flops per param per token; the batch token count is a
    # coarse default — strategy RANKING only needs the comm-vs-compute
    # scale, which the calibration path replaces with a measurement
    flops = 6.0 * (pb / 4.0) * max(1, global_batch) * 32
    kind = _cost.DEFAULT_DEVICE_KIND
    try:
        kind = jax.devices()[0].device_kind or kind
    except Exception:
        pass
    return _cost.TrainWorkload(
        param_bytes=pb, flops_per_step=flops, n_devices=n_devices,
        global_batch=global_batch, lm=lm, precision=precision,
        device_kind=kind)


def resolve_trainer_auto(trainer, module, seed: int) -> PlanReport:
    """``Trainer(strategy='auto')`` resolution: plan over the
    actionable strategies, assign the winner onto ``trainer``, return
    the report (the loop stamps ``report.stamp()`` into telemetry)."""
    import jax

    wl = trainer_workload(module, seed, jax.device_count(),
                          precision=trainer.precision)
    report = plan_training(wl, actionable=True)
    best = report.pick().candidate
    trainer.strategy = best.strategy
    if best.strategy == "pp":
        trainer.pipeline_stages = best.stages
        if best.microbatches:
            trainer.microbatches = best.microbatches
    return report


def engine_workload(module, params, n_devices: int = 1,
                    slots: int = 4) -> _cost.ServeWorkload:
    wb = 0.0
    for leaf in _tree_leaves(params):
        size, dt = getattr(leaf, "size", None), getattr(leaf, "dtype", None)
        if size is not None and dt is not None:
            wb += int(size) * dt.itemsize
    d = int(getattr(module, "d_model", 64))
    heads = int(getattr(module, "n_heads", max(1, d // 64)))
    n_kv = int(getattr(module, "n_kv_heads", None) or heads)
    dh = d // max(1, heads)
    kv_pos = 2 * getattr(module, "n_layers", 2) * n_kv * dh * 4
    return _cost.ServeWorkload(
        weight_bytes=wb, kv_bytes_per_pos=kv_pos,
        n_layers=int(getattr(module, "n_layers", 2)),
        max_len=int(getattr(module, "max_len", 512)),
        n_devices=n_devices, slots=slots)


#: Engine knobs auto mode owns, mapped to the values it treats as
#: "caller did not pin this" — each knob's SlotEngine signature default
#: AND its ServeConfig default (the two entry points spell some
#: defaults differently: decode_block None vs 8, attn_kernel None vs
#: "gather").  An explicitly-passed non-default value always wins over
#: the plan.
_ENGINE_AUTO_DEFAULTS = {
    "decode_block": (None, 8), "paged": (False,), "kv_block": (16,),
    "kv_int8": (False,), "attn_kernel": (None, "gather"),
    "prefill_kernel": (False,), "sample_kernel": (False,),
    "fused_rope": (False,), "spec_k": (4,),
}


def resolve_engine_auto(module, params, *, n_devices: int = 1,
                        num_slots: int = 4,
                        spec_draft_layers: Optional[int] = None,
                        user_kwargs: Optional[dict] = None,
                        ) -> Tuple[dict, PlanReport]:
    """``SlotEngine(auto=True)`` resolution.

    Returns ``(chosen_kwargs, report)``: engine kwargs for every auto-
    owned knob the caller left at its default.  Spec points enter the
    candidate space only when the caller supplied a draft depth — auto
    cannot invent a draft model.
    """
    user_kwargs = user_kwargs or {}
    wl = engine_workload(module, params, n_devices=n_devices,
                         slots=num_slots)
    spec_layers = (spec_draft_layers,) if spec_draft_layers else ()
    report = plan_serving(
        wl,
        decode_blocks=(1, 4, 8),
        spec_layers=spec_layers,
        include_kernels=False,  # wall twins say the interpreter arms
                                # lose on this host; neutral-1.0 arms
                                # must not win a ranking by tie
        include_int8=False,
    )
    best = report.pick().candidate
    chosen = {
        "decode_block": best.decode_block,
        "paged": best.paged,
        "kv_block": best.kv_block,
        "kv_int8": best.kv_int8,
        "attn_kernel": best.attn_kernel,
        "prefill_kernel": best.prefill_kernel,
        "sample_kernel": best.sample_kernel,
        "fused_rope": best.fused_rope,
        "spec_k": best.spec_k,
    }
    # the caller's explicit knobs win over the plan
    out = {}
    for k, v in chosen.items():
        if k in user_kwargs and \
                user_kwargs[k] not in _ENGINE_AUTO_DEFAULTS[k]:
            continue
        out[k] = v
    return out, report
