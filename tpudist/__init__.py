"""tpudist — a TPU-native distributed training framework.

A ground-up JAX/XLA re-design of the capabilities demonstrated by
``ammunk/distributed-training-pytorch`` (see SURVEY.md):

- ``tpudist.runtime``   — process bootstrap / rank contract / device mesh
  (replaces torch.distributed.init_process_group + the torchrun/srun/MPI
  env contracts of reference ``demo.py:19-73``).
- ``tpudist.comm``      — dual-fabric collectives: in-step device (ICI)
  gradient reduction + off-step host (DCN) metric reduction (replaces the
  NCCL default group + the Gloo logging group of ``demo.py:84,114-121``).
- ``tpudist.data``      — deterministic sharded data loading
  (DistributedSampler/set_epoch semantics of ``demo.py:139-154``).
- ``tpudist.models``    — Flax model zoo: the toy MLP (parity with
  ``toy_model_and_data.py``), the two-stage split model, and a flagship
  transformer exercising dp/tp/pp/sp/ep.
- ``tpudist.parallel``  — parallelism building blocks (DP, tensor,
  pipeline, ring-attention sequence parallel, MoE expert parallel).
- ``tpudist.train``     — jitted train steps and the training loop.
- ``tpudist.trainer``   — a Lightning-equivalent high-level Trainer facade
  (parity with ``demo_pytorch_lightning.py``).
- ``tpudist.ops``       — Pallas TPU kernels for hot ops.
- ``tpudist.telemetry`` — per-step span tracing, cross-rank/generation
  aggregation, and end-of-run goodput reports (step vs compile vs data
  vs checkpoint vs idle vs lost-to-restart, summing to wall-clock).
- ``tpudist.utils``     — metrics/W&B-compatible logging, profiling, misc.
"""

from tpudist.version import __version__  # noqa: F401
