"""Shared CLI — flag parity with the reference ``argument_parser.py:6-28``.

Mapping of reference flags onto the TPU runtime:

- ``--dataloader {distributed,standard}`` — identical semantics
  (``demo.py:139-154``).
- ``--backend {ici,host}`` — replaces ``{nccl,mpi,gloo}``: selects where the
  per-iteration metric reduction runs (SURVEY.md §5.8).  Gradient reduction
  always rides ICI inside the compiled step; ``host`` reduces logged scalars
  over DCN like the reference's Gloo logging group.  ``nccl``/``gloo``/``mpi``
  are accepted as aliases for migration (nccl→ici, gloo/mpi→host).
- ``--torchrun`` — accepted for launcher-script compatibility; rank
  derivation is contract-autodetected here, so it is a no-op.
- ``--use_node_rank`` — identical semantics (``demo.py:38-39``).
- ``--seed`` — random 32-bit default (``argument_parser.py:18``).
- ``--num_workers`` — same semantics: >0 enables background batch assembly
  via the native C++ gather pool (``tpudist.data.native_loader``); 0 keeps
  the synchronous numpy loader.  Threads instead of the reference's worker
  *processes*, so none of its forkserver/fd-sharing hazards apply.
- ``--dry_run`` — offline metrics mode (``demo.py:160-161``).

Plus training-shape flags (fixed constants in the reference):
``--total_iterations`` (``demo.py:88``), ``--batch_size`` (``demo.py:145``),
``--lr`` (``demo.py:80-81``), and TPU extras ``--profile_dir`` /
``--checkpoint_dir`` / ``--checkpoint_every``.
"""

from __future__ import annotations

import argparse

BACKEND_ALIASES = {"nccl": "ici", "gloo": "host", "mpi": "host", "ici": "ici", "host": "host"}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="tpudist training entry point")
    p.add_argument("--dataloader", choices=["distributed", "standard"],
                   type=str, default="distributed")
    p.add_argument("--backend", choices=sorted(BACKEND_ALIASES),
                   type=str, default="ici",
                   help="metric-reduction fabric: ici (on-device) or host (DCN); "
                        "nccl/gloo/mpi accepted as migration aliases")
    p.add_argument("--torchrun", action="store_true",
                   help="compat no-op: launch contract is autodetected")
    p.add_argument("--use_node_rank", action="store_true",
                   help="derive global rank as NODE_RANK*TASKS_PER_NODE+LOCAL_RANK")
    p.add_argument("--seed", default=None, type=int,
                   help="job-wide seed; when omitted, rank 0 draws one after "
                        "runtime init and broadcasts it (see "
                        "runtime.seeding.resolve_shared_seed)")
    p.add_argument("--num_workers", default=0, type=int,
                   help=">0: native background batch assembly (C++ gather "
                        "pool); 0: synchronous numpy loader")
    p.add_argument("--dry_run", action="store_true",
                   help="offline metrics (no wandb network/credentials)")
    p.add_argument("--total_iterations", default=1000, type=int)
    p.add_argument("--batch_size", default=256, type=int,
                   help="per-process batch size")
    p.add_argument("--lr", default=1e-3, type=float)
    p.add_argument("--lr_schedule",
                   choices=["constant", "cosine", "warmup_cosine"],
                   default="constant",
                   help="learning-rate schedule over --total_iterations")
    p.add_argument("--warmup_steps", default=0, type=int,
                   help="linear warmup steps (warmup_cosine)")
    p.add_argument("--optimizer",
                   choices=["adam", "adamw", "adafactor", "lion"],
                   default="adam",
                   help="optimizer family (adam = the reference's choice)")
    p.add_argument("--grad_clip", default=0.0, type=float,
                   help="global-norm gradient clipping (0 = off)")
    p.add_argument("--weight_decay", default=0.0, type=float,
                   help="decoupled weight decay, masked to weight matrices "
                        "(applies to adamw/adafactor/lion; with --optimizer "
                        "adam, >0 upgrades to adamw)")
    p.add_argument("--log_every", default=1, type=int)
    p.add_argument("--project", default="tpudist", type=str)
    p.add_argument("--group", default=None, type=str)
    p.add_argument("--profile_dir", default=None, type=str,
                   help="capture a jax.profiler trace into this directory")
    p.add_argument("--checkpoint_dir", default=None, type=str)
    p.add_argument("--checkpoint_every", default=0, type=int)
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in --checkpoint_dir")
    return p


def get_args(argv=None, parser: argparse.ArgumentParser | None = None) -> argparse.Namespace:
    """Parse + normalize.  ``parser`` lets entry points extend the shared
    parser (extra flags) while keeping normalization in one place.

    ``args.seed`` stays ``None`` when not given: it must be resolved
    job-wide *after* ``runtime.initialize`` via
    ``resolve_shared_seed(args.seed)`` — a per-process random draw here
    would silently desynchronize replicated init and shard plans.
    """
    args = (parser or build_parser()).parse_args(argv)
    args.backend = BACKEND_ALIASES[args.backend]
    return args
