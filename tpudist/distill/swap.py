"""The gated hot-swap: score a distilled candidate on held-out capture,
swap into the engine ONLY on a measured win.

The gate's two invariants:

- **Measured, with hysteresis** — the candidate must beat the BETTER of
  (a) the serving draft re-scored on the SAME held-out slice and (b)
  the serving draft's live acceptance from ``spec_stats()`` (the PR 13
  gauges an operator sees), by at least ``TPUDIST_DISTILL_SWAP_MARGIN``.
  Scoring serving params on the holdout kills the distribution-shift
  false negative (live acceptance measured on OLD traffic), and the
  live floor kills the overfit false positive (a candidate that only
  wins on the tiny holdout); the margin keeps a coin-flip candidate
  from flapping the engine.
- **Quality-only blast radius** — a WRONG candidate (the
  ``draft_swap_corrupt`` chaos fault garbles one pre-gate) can only
  cost speed, never bytes: the target verifies every drafted token, so
  the gate rejecting it is an efficiency story — but the gate MUST
  reject it, or swaps would quietly regress acceptance.  The chaos
  test drives exactly that.

Scoring is one padded batched teacher-forced forward per params tree
(one jit shape per round): next-token argmax agreement over the
EMITTED region, plus a windowed leading-prefix estimate of per-pass
acceptance for the engine's ``spec_k`` (the draft proposes K, the
target accepts the leading prefix that matches — greedy lanes make
teacher-forced agreement an exact oracle for that prefix).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from tpudist.distill.train import pack_streams


def score_holdout(draft_module, draft_params, streams, *,
                  spec_k: int = 4, pad_to: Optional[int] = None) -> dict:
    """Teacher-forced draft quality on held-out streams: ``match`` =
    next-token argmax agreement over emitted positions, ``acceptance``
    = the windowed leading-prefix estimate of the drafted-token accept
    rate at ``spec_k``, ``accepted_per_pass`` = its tokens-per-verify
    translation (leading prefix + the verify pass's bonus token)."""
    import jax
    import jax.numpy as jnp

    if not streams:
        return {"streams": 0, "positions": 0, "match": None,
                "acceptance": None, "accepted_per_pass": None}
    toks = pack_streams(streams, pad_to=pad_to)

    @jax.jit
    def preds(p, t):
        logits = draft_module.apply(p, jnp.maximum(t, 0))
        return jnp.argmax(logits, axis=-1)

    pred = np.asarray(preds(draft_params, toks))  # [N, T]
    k = max(1, int(spec_k))
    npos = 0
    nmatch = 0
    windows = 0
    accepted = 0
    per_pass: List[float] = []
    for i, s in enumerate(streams):
        T = len(s)
        start = max(0, int(getattr(s, "prompt_len", 1)) - 1)
        # position j's prediction targets token j+1 — compare over the
        # emitted region only (prompt modeling is not what verify pays)
        tgt = toks[i, start + 1:T]
        got = pred[i, start:T - 1]
        ok = got == tgt
        npos += ok.size
        nmatch += int(ok.sum())
        for w in range(0, ok.size, k):
            win = ok[w:w + k]
            if win.size < k:
                break  # partial trailing window would inflate the rate
            lead = int(np.argmin(win)) if not win.all() else k
            windows += 1
            accepted += lead
            per_pass.append(float(lead + 1))
    return {
        "streams": len(streams),
        "positions": npos,
        "match": round(nmatch / npos, 4) if npos else None,
        "acceptance": round(accepted / (windows * k), 4) if windows
        else (round(nmatch / npos, 4) if npos else None),
        "accepted_per_pass": (round(float(np.mean(per_pass)), 3)
                              if per_pass else None),
    }


def gate_swap(candidate: dict, serving: dict,
              live_acceptance: Optional[float],
              margin: float = 0.02) -> dict:
    """The swap decision: candidate's holdout acceptance vs the
    baseline = max(serving-on-holdout, live gauge), with hysteresis.
    Returns ``{"swap": bool, "reason": str, ...}`` — every input the
    decision read is stamped on it (the ``distill_round`` event makes
    the gate auditable from the stream alone)."""
    cand = candidate.get("acceptance")
    base_hold = serving.get("acceptance")
    floors = [v for v in (base_hold, live_acceptance)
              if isinstance(v, (int, float))]
    baseline = max(floors) if floors else None
    out = {
        "candidate_acceptance": cand,
        "serving_holdout_acceptance": base_hold,
        "live_acceptance": live_acceptance,
        "baseline": baseline,
        "margin": float(margin),
    }
    if cand is None:
        return {**out, "swap": False, "reason": "no_holdout"}
    if baseline is None:
        # no measurement to beat (cold engine, no spec traffic yet):
        # the candidate still had to clear the holdout forward — admit
        return {**out, "swap": True, "reason": "no_baseline"}
    if cand >= baseline + float(margin):
        return {**out, "swap": True, "reason": "measured_win"}
    return {**out, "swap": False, "reason": "below_margin"}


def maybe_corrupt_candidate(candidate_params, round_idx: int):
    """The ``draft_swap_corrupt`` chaos seam: a due fault garbles the
    candidate's params PRE-GATE (every float leaf saturated — garbage
    logits, unambiguous rejection), modeling a poisoned training round
    or a torn publish.  The held-out eval must then reject it and the
    serving draft stays untouched.  Returns
    ``(params, corrupted: bool)``."""
    from tpudist.runtime import faults

    if not faults.inject_draft_swap(round_idx):
        return candidate_params, False
    import jax
    import jax.numpy as jnp

    def garble(leaf):
        a = jnp.asarray(leaf)
        if jnp.issubdtype(a.dtype, jnp.floating):
            return jnp.full_like(a, 1000.0)
        return a

    return jax.tree.map(garble, candidate_params), True
