"""Online draft distillation (the serving↔training flywheel).

Three pieces wired end to end: :mod:`capture` taps finished-request
streams off the serving loop into a bounded ring, :mod:`loop` drives
the repo's own Trainer on that ring in a background thread, and
:mod:`swap` gates the resulting candidate on a held-out slice before
the server lands it between decode blocks as a pure same-shape param
update (compile pins flat, greedy bytes identical — speculation's
correctness never depended on the draft).

Import surface is deliberately lazy-light: :class:`CaptureBuffer` is
numpy+stdlib (the serving tap must not drag jax), the trainer/scorer
halves import jax only when a round runs.
"""

from tpudist.distill.capture import CaptureBuffer, CapturedStream
from tpudist.distill.loop import DistillLoop
from tpudist.distill.swap import gate_swap, score_holdout
from tpudist.distill.train import (
    DraftDistillModule,
    continuations_from_target,
    distill_draft,
    distill_streams,
    pack_streams,
)

__all__ = [
    "CaptureBuffer",
    "CapturedStream",
    "DistillLoop",
    "DraftDistillModule",
    "continuations_from_target",
    "distill_draft",
    "distill_streams",
    "gate_swap",
    "pack_streams",
    "score_holdout",
]
