"""The one draft-distillation code path.

Two entries share the same objective (next-token cross entropy on the
target's own continuations — sequence-level distillation):

- :func:`distill_draft` — the offline/bench form previously inlined in
  ``benchmarks/serve_bench.py --spec-distill``: GENERATE the target's
  greedy continuations of a prompt pool, then fit a fresh tied draft to
  them.  ``serve_bench`` now imports it from here (dedup satellite —
  one distillation implementation, no drift).
- :func:`DraftDistillModule` + :func:`pack_streams` — the online form:
  the capture ring already holds the continuations the target emitted
  in production, so the flywheel skips generation and drives the
  repo's own :class:`~tpudist.trainer.trainer.Trainer` (the training
  stack finally running TOGETHER with serving) on the packed streams,
  warm-started from the serving draft's current params.

Padding contract: packed batches pad with ``-1``.  The apply shim
clamps tokens to ``>= 0`` before the embed (a ``-1`` through
``jnp.take`` would read garbage rows) and the loss masks every
position whose TARGET is ``-1`` (``lm_loss_with_targets``), so pad
positions contribute exactly zero gradient.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def pack_streams(streams, pad_to: Optional[int] = None,
                 pad_rows_to: Optional[int] = None) -> np.ndarray:
    """Pack captured streams into one ``[N, T]`` int32 matrix padded
    with ``-1`` — ONE shape per round, so the train step and the
    holdout scorer each compile once.  ``pad_to`` forces the time dim
    (rounds with growing rings can pin a shape across rounds);
    ``pad_rows_to`` pads N with all-``-1`` rows (fully masked → zero
    loss) so the batch divides a data-parallel mesh."""
    if not streams:
        raise ValueError("pack_streams: no streams")
    T = max(len(s) for s in streams)
    if pad_to is not None:
        if pad_to < T:
            raise ValueError(f"pad_to={pad_to} < longest stream {T}")
        T = int(pad_to)
    N = len(streams)
    if pad_rows_to is not None and pad_rows_to > N:
        N = int(pad_rows_to)
    toks = np.full((N, T), -1, np.int32)
    for i, s in enumerate(streams):
        t = s.tokens if hasattr(s, "tokens") else np.asarray(s, np.int32)
        toks[i, :len(t)] = t
    return toks


class DraftDistillModule:
    """The :class:`~tpudist.trainer.trainer.LMTrainerModule` the
    flywheel feeds to ``Trainer.fit``: one tied/loaded draft, warm-
    started from the SERVING params (same geometry by construction —
    the swap-gate invariant), pad-aware apply + loss."""

    def __init__(self, draft_module, draft_params, lr: float = 3e-3):
        from tpudist.trainer.trainer import LMTrainerModule

        # subclass-at-init keeps this module importable without jax
        # until a round actually runs
        self._base = LMTrainerModule
        self._module = draft_module
        self._params = draft_params
        self._lr = float(lr)

    def build(self):
        import jax.numpy as jnp
        import optax

        from tpudist.models.transformer import lm_loss_with_targets
        from tpudist.trainer.trainer import LMTrainerModule

        draft_module, draft_params, lr = (
            self._module, self._params, self._lr)

        class _Shim:
            """``flax_mod.apply``-shaped wrapper clamping pad tokens
            before the embed (the LM trainer path only calls
            ``.apply``)."""

            def apply(self, p, toks):
                return draft_module.apply(p, jnp.maximum(toks, 0))

        class _Module(LMTrainerModule):
            def configure_lm(self, rng):
                # deep-copy the warm start: the LM train step DONATES
                # its state buffers, and these are the ENGINE's live
                # serving params — donating them would delete the
                # serving draft out from under the dispatcher
                import jax

                return _Shim(), jax.tree.map(jnp.array, draft_params)

            def configure_optimizers(self):
                return optax.adam(lr)

            def loss(self, logits, tokens):
                # next-token targets; pad (and the position BEFORE a
                # pad run's start) masked via the -1 convention
                return lm_loss_with_targets(logits[:, :-1], tokens[:, 1:])

        return _Module()


def distill_streams(draft_module, draft_params, streams, *,
                    steps: int = 40, lr: float = 3e-3,
                    max_steps_cap: int = 1000) -> Tuple[object, float]:
    """One distillation round through the repo Trainer: fit the draft
    (warm-started from ``draft_params``) to the captured streams and
    return ``(candidate_params, final_loss)``.  Runs on whatever mesh
    the process holds (``strategy='dp'`` — replicated draft state, the
    serving-compatible layout)."""
    import jax

    from tpudist.trainer.trainer import Trainer

    steps = max(1, min(int(steps), max_steps_cap))
    toks = pack_streams(
        streams, pad_rows_to=-(-len(streams) // jax.device_count())
        * jax.device_count())
    trainer = Trainer(max_steps=steps, strategy="dp", dry_run=True,
                      progress_bar=False, log_every=steps)
    losses = trainer.fit(
        DraftDistillModule(draft_module, draft_params, lr).build(),
        [toks])
    state = trainer.final_states
    cand = state.params if hasattr(state, "params") else state
    return cand, (losses or {}).get("lm")


def distill_draft(module, params, layers: int, prompt_pool,
                  steps: int, max_new: int, *, lr: float = 3e-3,
                  seed: int = 11):
    """Build a TRAINED draft the way production does: distill the
    target's own greedy continuations of the serving prompt pool into a
    shallow student (cross-entropy on next-token, the sequence-level
    distillation objective).  Random-weight targets ship no pre-trained
    draft pair, so benches (and cold-start deployments) train one from
    the serving distribution — acceptance is a property of
    (draft, workload), and this trains for the workload.  Returns
    ``(draft_module, draft_params, final_loss)``."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpudist.models import make_generator, tied_draft
    from tpudist.models.transformer import lm_loss_with_targets

    draft_mod, _ = tied_draft(module, params, layers)
    dp = draft_mod.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))
    gen = make_generator(module, params, max_new)
    T = max(len(p) for p in prompt_pool) + max_new
    toks = np.zeros((len(prompt_pool), T), np.int32)
    tgts = np.full((len(prompt_pool), T - 1), -1, np.int32)
    for i, p in enumerate(prompt_pool):
        out = np.asarray(gen(jnp.asarray(p)[None]))[0]
        toks[i, :len(out)] = out
        tgts[i, :len(out) - 1] = out[1:]
    opt = optax.adam(lr)
    ost = opt.init(dp)

    @jax.jit
    def train_step(dp, ost, toks, tgts):
        def loss_fn(dp):
            return lm_loss_with_targets(draft_mod.apply(dp, toks[:, :-1]),
                                        tgts)

        loss, g = jax.value_and_grad(loss_fn)(dp)
        up, ost = opt.update(g, ost)
        return optax.apply_updates(dp, up), ost, loss

    tj, gj = jnp.asarray(toks), jnp.asarray(tgts)
    loss = None
    for _ in range(max(1, steps)):
        dp, ost, loss = train_step(dp, ost, tj, gj)
    return draft_mod, dp, float(loss)


def continuations_from_target(module, params, prompt_pool, max_new: int,
                              ) -> List[np.ndarray]:
    """The target's greedy continuations of a prompt pool as plain
    ``[T_i]`` arrays (prompt + emitted) — the offline twin of what the
    capture ring collects from live traffic (benches use it to seed a
    flywheel without a serving warmup phase)."""
    import jax.numpy as jnp

    from tpudist.models import make_generator

    gen = make_generator(module, params, max_new)
    return [np.asarray(gen(jnp.asarray(p)[None]))[0]
            for p in prompt_pool]
