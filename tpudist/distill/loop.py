"""The background distillation lane: capture ring → repo Trainer →
gated hot-swap, as a thread beside the serving loop.

One :meth:`DistillLoop.run_once` is the whole flywheel turn:

1. snapshot the capture ring (skip below ``TPUDIST_DISTILL_MIN_TOKENS``
   — a round on three streams would swap on noise);
2. split a held-out slice off the capture (interleaved — both slices
   see the CURRENT mix under distribution shift);
3. drive the repo's own :class:`~tpudist.trainer.trainer.Trainer` on
   the training slice, warm-started from the SERVING draft's current
   params (same geometry asserted, not assumed);
4. run the candidate through the ``draft_swap_corrupt`` chaos seam,
   then the measured gate (:func:`tpudist.distill.swap.gate_swap`)
   against the serving draft's holdout re-score AND its live
   ``spec_stats()`` acceptance, with hysteresis;
5. on a win, hand the candidate to ``server.swap_draft`` — the server
   loop lands it BETWEEN decode blocks as a pure same-shape param
   update (compile pins flat, lanes re-armed, greedy bytes identical).

Per-adapter binding (PR 15): with ``per_adapter`` on, a round whose
heaviest captured adapter is RESIDENT in the engine's name→block
registry trains an adapter-biased candidate on that adapter's slice
and gates it against the adapter's OWN labeled acceptance
(``spec_stats()['by_adapter']``).  The swap stays whole-draft (the
slot programs carry one dparams tree), so the adapter round only
lands when it also clears the global holdout — biased toward the
heavy tenant, never regressing the rest.

Every round emits one ``distill_round`` event carrying the gate's full
input (and ``draft_swap`` fires from the server on an applied swap) —
the flywheel is auditable from the telemetry stream alone.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from tpudist.distill.capture import CaptureBuffer
from tpudist.distill.swap import (
    gate_swap,
    maybe_corrupt_candidate,
    score_holdout,
)
from tpudist.distill.train import distill_streams


def _env_cfg() -> dict:
    from tpudist.utils.envutil import (
        env_flag,
        env_float,
        env_int,
        env_positive_float,
    )

    return {
        "interval_s": env_positive_float("TPUDIST_DISTILL_INTERVAL_S", 30.0),
        "steps": env_int("TPUDIST_DISTILL_STEPS", 40),
        "min_tokens": env_int("TPUDIST_DISTILL_MIN_TOKENS", 256),
        "holdout": env_float("TPUDIST_DISTILL_HOLDOUT", 0.25),
        "margin": env_float("TPUDIST_DISTILL_SWAP_MARGIN", 0.02),
        "lr": env_float("TPUDIST_DISTILL_LR", 3e-3),
        "per_adapter": env_flag("TPUDIST_DISTILL_PER_ADAPTER", False),
    }


class DistillLoop:
    """Owns the flywheel thread.  ``server`` is either server flavor —
    the loop reads ``server.draft_ref()`` (serving draft module +
    current params), ``server.stats()['spec']`` (live gauges), and
    calls ``server.swap_draft(params)`` (the between-blocks landing).
    """

    def __init__(self, server, capture: CaptureBuffer, *,
                 interval_s: Optional[float] = None,
                 steps: Optional[int] = None,
                 min_tokens: Optional[int] = None,
                 holdout: Optional[float] = None,
                 margin: Optional[float] = None,
                 lr: Optional[float] = None,
                 per_adapter: Optional[bool] = None):
        cfg = _env_cfg()
        self.server = server
        self.capture = capture
        self.interval_s = float(interval_s if interval_s is not None
                                else cfg["interval_s"])
        self.steps = int(steps if steps is not None else cfg["steps"])
        self.min_tokens = int(min_tokens if min_tokens is not None
                              else cfg["min_tokens"])
        self.holdout = float(holdout if holdout is not None
                             else cfg["holdout"])
        self.margin = float(margin if margin is not None
                            else cfg["margin"])
        self.lr = float(lr if lr is not None else cfg["lr"])
        self.per_adapter = bool(per_adapter if per_adapter is not None
                                else cfg["per_adapter"])
        self.rounds = 0
        self.swaps = 0
        self.rejected = 0
        self.corrupt_rejected = 0
        self.last_round: Optional[dict] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- one flywheel turn ---------------------------------------------------

    def run_once(self) -> dict:
        """One distillation round (synchronous — tests and benches call
        this directly; the background thread calls it on a cadence).
        Returns the round record it also emits as ``distill_round``."""
        self.rounds += 1
        t0 = time.monotonic()
        info = {"round": self.rounds}
        cap = self.capture.stats()
        info["capture_tokens"] = cap["tokens"]
        info["capture_streams"] = cap["streams"]
        info["capture_evicted"] = cap["evicted"]
        ref = self.server.draft_ref()
        if ref is None:
            return self._done(info, swapped=False, reason="no_draft", t0=t0)
        if cap["tokens"] < self.min_tokens:
            return self._done(info, swapped=False, reason="min_tokens",
                              t0=t0)
        adapter = None
        if self.per_adapter:
            adapter = self.capture.heaviest_adapter()
            if adapter is not None and not self._adapter_bound(adapter):
                adapter = None  # not resident in the name→block registry
        streams = self.capture.snapshot()
        train, hold = CaptureBuffer.split_holdout(streams, self.holdout)
        if adapter is not None:
            biased = [s for s in train if s.adapter == adapter]
            if biased:
                # adapter-biased round: the heavy tenant's slice leads,
                # the rest stays in (a pure-slice round would forget
                # the base traffic the same draft still serves)
                train = biased + [s for s in train if s.adapter != adapter]
                info["adapter"] = adapter
        # greedy lanes are the exact oracle for leading-prefix accept;
        # score on them when available, whole holdout otherwise
        ghold = [s for s in hold if s.greedy] or hold
        draft_module, serving_params = ref
        candidate, loss = distill_streams(
            draft_module, serving_params, train,
            steps=self.steps, lr=self.lr)
        info["train_streams"] = len(train)
        info["holdout_streams"] = len(ghold)
        info["loss"] = None if loss is None else round(float(loss), 5)
        candidate, corrupted = maybe_corrupt_candidate(
            candidate, self.rounds)
        if corrupted:
            info["fault"] = "draft_swap_corrupt"
        spec_k = int((self._live_spec() or {}).get("spec_k") or 4)
        cscore = score_holdout(draft_module, candidate, ghold,
                               spec_k=spec_k)
        sscore = score_holdout(draft_module, serving_params, ghold,
                               spec_k=spec_k)
        live = (self._live_spec() or {}).get("acceptance_rate")
        gate = gate_swap(cscore, sscore, live, margin=self.margin)
        if adapter is not None and gate["swap"]:
            # the adapter slice must ALSO win on its own labeled lanes
            ahold = [s for s in ghold if s.adapter == adapter]
            if ahold:
                a_live = ((self._live_spec() or {}).get(
                    "by_adapter", {}).get(adapter, {})
                    .get("acceptance_rate"))
                agate = gate_swap(
                    score_holdout(draft_module, candidate, ahold,
                                  spec_k=spec_k),
                    score_holdout(draft_module, serving_params, ahold,
                                  spec_k=spec_k),
                    a_live, margin=self.margin)
                if not agate["swap"]:
                    gate = {**gate, "swap": False,
                            "reason": f"adapter_{agate['reason']}"}
        info.update(gate)
        if not gate["swap"]:
            self.rejected += 1
            if corrupted:
                self.corrupt_rejected += 1
            return self._done(info, swapped=False, reason=gate["reason"],
                              t0=t0)
        swap_info = self.server.swap_draft(candidate)
        self.swaps += 1
        info["swap_s"] = swap_info.get("swap_s")
        info["lanes_rearmed"] = swap_info.get("lanes_rearmed")
        return self._done(info, swapped=True, reason=gate["reason"], t0=t0)

    def _done(self, info: dict, *, swapped: bool, reason: str,
              t0: float) -> dict:
        from tpudist import telemetry

        info["swapped"] = swapped
        info["reason"] = reason
        info["round_s"] = round(time.monotonic() - t0, 6)
        self.last_round = info
        telemetry.event("distill_round", **info)
        return info

    def _adapter_bound(self, name: str) -> bool:
        engines = self.server._adapter_engines()
        return bool(engines) and engines[0].has_adapter(name)

    def _live_spec(self) -> Optional[dict]:
        try:
            st = self.server.stats()
            # InferenceServer: top-level; DisaggServer: the decode pool
            # owns the draft, its aggregated gauges live under it
            return st.get("spec") or st.get("decode_pool", {}).get("spec")
        except Exception:
            return None

    # -- the thread ----------------------------------------------------------

    def start(self) -> "DistillLoop":
        if self._thread is not None:
            raise RuntimeError("distill loop already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="tpudist-distill", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> bool:
        self._stop.set()
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        ok = not t.is_alive()
        if ok:
            self._thread = None
        return ok

    def _run(self) -> None:
        from tpudist import telemetry

        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception as e:  # the lane must never take serving down
                telemetry.event("distill_round", round=self.rounds,
                                swapped=False, reason="error",
                                error=repr(e)[:200])

    def stats(self) -> dict:
        return {
            "rounds": self.rounds,
            "swaps": self.swaps,
            "rejected": self.rejected,
            "corrupt_rejected": self.corrupt_rejected,
            "interval_s": self.interval_s,
            "steps": self.steps,
            "min_tokens": self.min_tokens,
            "margin": self.margin,
            "per_adapter": self.per_adapter,
            **({"last_round": self.last_round}
               if self.last_round is not None else {}),
        }
