"""Live-traffic capture for the online draft-distillation flywheel.

The serving loop's EXISTING ``request_finished`` seam is the tap: every
finished request offers its (prompt, emitted-token) stream to a bounded
ring here, greedy and sampled lanes alike, tagged per-tenant and
per-adapter so the distillation lane can bias rounds toward the
heaviest traffic.  The buffer is the training-set side of the flywheel
— acceptance is a property of (draft, workload), and this ring IS the
workload the serving process actually saw.

Discipline (the telemetry-drop rule): the ring is bounded in TOKENS
(``TPUDIST_DISTILL_BUFFER_TOKENS``), eviction is oldest-first, and
every stream that falls out — evicted, sampled past, or oversize — is
COUNTED, never silently gone (:meth:`CaptureBuffer.stats` and the
``/statusz`` ``distill`` block both read the counters).

Dependency-light on purpose: numpy + stdlib, importable without jax —
the capture tap sits on the serving hot path's finish seam and must
cost one attribute load + None check when disarmed.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class CapturedStream:
    """One finished request's token stream: prompt + emitted, already
    concatenated — exactly the training sequence sequence-level
    distillation wants (the draft learns to continue the prompts the
    target actually continued)."""

    tokens: np.ndarray  # [prompt_len + emitted] int32
    prompt_len: int
    greedy: bool  # temperature == 0 (the byte-identity lane)
    tenant: Optional[str] = None
    adapter: Optional[str] = None

    def __len__(self) -> int:
        return int(self.tokens.shape[0])


class CaptureBuffer:
    """Bounded, sampled ring of :class:`CapturedStream`.

    ``budget_tokens`` bounds the SUM of stream lengths (a ring bounded
    in streams would let one long-prompt tenant squeeze everyone else
    out while looking half empty); ``sample_every`` keeps every Nth
    finished request (1 = all).  Thread-safe: the engine loop offers,
    the distillation thread snapshots.
    """

    def __init__(self, budget_tokens: int = 65536, sample_every: int = 1):
        if budget_tokens <= 0:
            raise ValueError("budget_tokens must be positive")
        self.budget_tokens = int(budget_tokens)
        self.sample_every = max(1, int(sample_every))
        self._dq: Deque[CapturedStream] = collections.deque()
        self._tokens = 0
        self._lock = threading.Lock()
        # the never-silent ledger
        self.seen = 0          # finished requests offered
        self.captured = 0      # streams that entered the ring
        self.sampled_out = 0   # skipped by the sampling knob
        self.dropped_empty = 0     # no emitted tokens (reject/shutdown)
        self.dropped_oversize = 0  # single stream exceeds the budget
        self.evicted = 0       # pushed out of the ring by newer streams

    @classmethod
    def from_env(cls) -> Optional["CaptureBuffer"]:
        """Build from the ``TPUDIST_DISTILL_*`` knobs; ``None`` unless
        ``TPUDIST_DISTILL_CAPTURE`` is on (the disarmed default — the
        tap then costs one None check per finished request)."""
        from tpudist.utils.envutil import env_flag, env_int

        if not env_flag("TPUDIST_DISTILL_CAPTURE", False):
            return None
        return cls(
            budget_tokens=env_int("TPUDIST_DISTILL_BUFFER_TOKENS", 65536),
            sample_every=env_int("TPUDIST_DISTILL_SAMPLE", 1))

    # -- the tap -------------------------------------------------------------

    def offer(self, prompt, emitted, *, greedy: bool,
              tenant: Optional[str] = None,
              adapter: Optional[str] = None) -> bool:
        """Offer one finished stream; returns whether it was kept.
        Never raises into the serving loop (defensive coercion only at
        the boundary — a malformed stream is a counted drop)."""
        with self._lock:
            self.seen += 1
            if self.seen % self.sample_every != 0:
                self.sampled_out += 1
                return False
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            emitted = np.asarray(emitted, np.int32).reshape(-1)
            if emitted.size == 0:
                self.dropped_empty += 1
                return False
            toks = np.concatenate([prompt, emitted])
            if toks.size > self.budget_tokens:
                self.dropped_oversize += 1
                return False
            while self._tokens + toks.size > self.budget_tokens:
                old = self._dq.popleft()
                self._tokens -= len(old)
                self.evicted += 1
            self._dq.append(CapturedStream(
                tokens=toks, prompt_len=int(prompt.size),
                greedy=bool(greedy),
                tenant=None if tenant is None else str(tenant),
                adapter=None if adapter is None else str(adapter)))
            self._tokens += toks.size
            self.captured += 1
            return True

    def offer_handle(self, h) -> bool:
        """The serving-loop convenience: tap a finished
        :class:`~tpudist.serve.scheduler.RequestHandle` (both server
        flavors call this from ``_note_finished``).  Streams that
        produced no tokens (rejects, shutdown aborts) are counted
        drops, not training data."""
        req = h.request
        return self.offer(req.prompt, h.tokens,
                          greedy=float(req.temperature) == 0.0,
                          tenant=req.tenant, adapter=req.adapter)

    # -- the training-set side ----------------------------------------------

    def snapshot(self, adapter: Optional[str] = None,
                 only_adapter: bool = False) -> List[CapturedStream]:
        """A stable copy of the ring (the distillation round trains on
        a snapshot while the loop keeps capturing).  ``only_adapter``
        restricts to streams tagged ``adapter`` — the per-adapter round
        of the PR 15 binding."""
        with self._lock:
            streams = list(self._dq)
        if only_adapter:
            streams = [s for s in streams if s.adapter == adapter]
        return streams

    @staticmethod
    def split_holdout(streams: List[CapturedStream],
                      holdout_frac: float = 0.25,
                      ) -> Tuple[List[CapturedStream],
                                 List[CapturedStream]]:
        """Deterministic train/held-out split via a fixed-seed
        permutation: both slices sample the WHOLE ring uniformly, so a
        traffic-mix shift mid-ring lands in both (a contiguous tail
        split would let the gate score yesterday's distribution), and
        the pick is decorrelated from any periodicity in the traffic —
        a strided every-k-th split aligned with a repeat-prompt pool's
        period would systematically exclude the held-out prompts from
        training, scoring generalization to unseen prompts instead of
        fit to the live workload (the gate's actual question).  At
        least one stream lands on each side when there are two or
        more; order within each slice stays arrival order."""
        if not streams:
            return [], []
        if len(streams) == 1:
            return list(streams), list(streams)
        frac = min(0.5, max(0.05, float(holdout_frac)))
        n = len(streams)
        n_hold = min(n - 1, max(1, int(round(frac * n))))
        perm = np.random.default_rng(0x5EED).permutation(n)
        hidx = set(int(i) for i in perm[:n_hold])
        hold = [s for i, s in enumerate(streams) if i in hidx]
        train = [s for i, s in enumerate(streams) if i not in hidx]
        return train, hold

    def heaviest_adapter(self, min_streams: int = 2) -> Optional[str]:
        """The adapter name carrying the most captured tokens (``None``
        when no adapter-tagged stream clears ``min_streams``) — the
        per-adapter round's target selection."""
        by: Dict[str, List[int]] = {}
        for s in self.snapshot():
            if s.adapter is not None:
                e = by.setdefault(s.adapter, [0, 0])
                e[0] += 1
                e[1] += len(s)
        best = None
        for name, (n, toks) in sorted(by.items()):
            if n >= min_streams and (best is None or toks > best[1]):
                best = (name, toks)
        return best[0] if best else None

    def stats(self) -> dict:
        """The never-silent ledger (rides into ``/statusz`` and the
        distillation-round telemetry events)."""
        with self._lock:
            by_adapter: Dict[str, int] = {}
            by_tenant: Dict[str, int] = {}
            greedy = 0
            for s in self._dq:
                if s.adapter is not None:
                    by_adapter[s.adapter] = by_adapter.get(s.adapter, 0) + 1
                key = s.tenant if s.tenant else "default"
                by_tenant[key] = by_tenant.get(key, 0) + 1
                greedy += int(s.greedy)
            return {
                "streams": len(self._dq),
                "tokens": self._tokens,
                "budget_tokens": self.budget_tokens,
                "sample_every": self.sample_every,
                "greedy_streams": greedy,
                "seen": self.seen,
                "captured": self.captured,
                "sampled_out": self.sampled_out,
                "dropped_empty": self.dropped_empty,
                "dropped_oversize": self.dropped_oversize,
                "evicted": self.evicted,
                **({"by_adapter": by_adapter} if by_adapter else {}),
                **({"by_tenant": by_tenant} if by_tenant else {}),
            }
