"""Pallas TPU kernel: the toy 5-layer MLP forward, fused into one kernel.

The reference's entire workload is this MLP (2→10→10→10→10→1, LeakyReLU —
``toy_model_and_data.py:12-22``).  XLA already fuses the chain well; this
kernel is the explicit-VMEM formulation: all five weight matrices are
zero-padded once to lane-aligned ``[128, 128]`` tiles, a batch tile streams
in per grid step, and the five matmul+LeakyReLU stages run back-to-back on
the MXU/VPU with activations never leaving VMEM.  Padding with zeros is
exact: padded input lanes are zero, padded weight rows/cols are zero, and
LeakyReLU(0) = 0, so the extra lanes stay zero through every layer.

Entry points: :func:`pad_params` once per weight set, then
:func:`fused_mlp` per batch; :func:`mlp_reference` is the dense XLA
formulation the tests compare against.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
NEGATIVE_SLOPE = 0.01  # torch.nn.LeakyReLU default, toy_model_and_data.py:14


def _leaky_relu(x):
    return jnp.where(x >= 0, x, NEGATIVE_SLOPE * x)


def _fused_kernel(x_ref, *refs, n_layers: int):
    """refs = (w_0, b_0, …, w_{n-1}, b_{n-1}, o_ref); everything VMEM."""
    o_ref = refs[-1]
    h = x_ref[:]
    for i in range(n_layers):
        w, b = refs[2 * i][:], refs[2 * i + 1][:]
        # HIGHEST: full-f32 MXU passes — the toy dims are tiny, so the 3-pass
        # cost is noise, and it keeps the kernel bit-comparable to XLA's VPU
        # fallback for small shapes.
        h = jnp.dot(h, w, preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST) + b
        if i + 1 < n_layers:  # final layer is the linear regression head
            h = _leaky_relu(h)
    o_ref[:] = h


def pad_params(
    weights: Sequence[Tuple[jax.Array, jax.Array]],
) -> Tuple[Tuple[jax.Array, ...], int, int]:
    """Zero-pad each ``(w [din, dout], b [dout])`` to ``[LANE, LANE]``/
    ``[1, LANE]`` tiles.  Returns (flat padded refs, true d_in, true d_out)."""
    flat = []
    for w, b in weights:
        wp = jnp.zeros((LANE, LANE), jnp.float32).at[: w.shape[0], : w.shape[1]].set(w)
        bp = jnp.zeros((1, LANE), jnp.float32).at[0, : b.shape[0]].set(b)
        flat += [wp, bp]
    return tuple(flat), weights[0][0].shape[0], weights[-1][0].shape[1]


def fused_mlp(
    x: jax.Array,
    padded_params: Tuple[jax.Array, ...],
    d_out: int,
    *,
    block_batch: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Run the fused forward.  ``x: [batch, d_in]`` (batch % block_batch == 0
    or batch < block_batch); params from :func:`pad_params`."""
    n_layers = len(padded_params) // 2
    batch, d_in = x.shape
    bb = min(block_batch, batch)
    if batch % bb:
        raise ValueError(f"block_batch {bb} must divide batch {batch}")
    xp = jnp.zeros((batch, LANE), x.dtype).at[:, :d_in].set(x)

    kernel = functools.partial(_fused_kernel, n_layers=n_layers)
    wspecs = []
    for _ in range(n_layers):
        wspecs += [
            pl.BlockSpec((LANE, LANE), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, LANE), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((batch, LANE), jnp.float32),
        grid=(batch // bb,),
        in_specs=[
            pl.BlockSpec((bb, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
            *wspecs,
        ],
        out_specs=pl.BlockSpec((bb, LANE), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xp, *padded_params)
    return out[:, :d_out]


def mlp_reference(x, weights):
    """Dense XLA forward for the same ``[(w, b), …]`` list."""
    h = x
    for i, (w, b) in enumerate(weights):
        h = h @ w + b
        if i + 1 < len(weights):
            h = _leaky_relu(h)
    return h
