"""Pallas TPU fused-sampling kernels: the decode step's tail in one pass.

After attention, the decode step's tail runs as a string of tiny HLOs —
grammar constrain-mask gather (``_gmask``), greedy argmax, temperature
scale, optional top-k/top-p filtering — each a separate elementwise
dispatch over ``[slots, vocab]``, each round-tripping the logits through
HBM.  :func:`fused_sample_prep` fuses them into one kernel over a
``(slots,)`` grid: the slot's grammar row rides in via a BlockSpec index
map over the scalar-prefetched ``(gidx, gstate)`` coordinates (the same
indirection discipline as the paged-attention block-table walk), and the
kernel emits everything the in-graph tail needs — the constrain-masked
logits (fed unchanged to top-logprobs and the automaton advance), the
temperature-scaled-and-filtered logits (fed to ``categorical``), and the
greedy argmax.

The RANDOM DRAW stays in-graph: ``jax.random.categorical(fold_in(key,
count), scaled)`` consumes the kernel's ``scaled`` output, so the
fold_in substream contract is untouched and sampled streams are
byte-identical to the unfused tail (division by ``max(temp, 1e-6)`` is
the same op either way).  Masking uses ``finfo(dtype).min`` — the same
constant as ``_gmask`` — so greedy streams are byte-identical too.

:func:`fused_residual_prep` is the speculative-verify sibling: it fuses
``_accept``'s per-(slot, draft-position) softmax pair and residual
distribution (``max(p_target - p_draft, 0)``, log with the 1e-30 floor,
``lt/temp`` fallback when the residual is empty) into one kernel over a
``(slots, k)`` grid.  Acceptance tests, clamping, and all draws stay
in-graph — the kernel only replaces elementwise dispatches, so the
accept/reject decisions are bit-identical.

``interpret=True`` (any non-TPU backend) is the tier-1 CPU path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def _sample_kernel(temps_ref, gidx_ref, gstate_ref, lg_ref, ga_ref,
                   masked_ref, scaled_ref, greedy_ref, *,
                   top_k: int, top_p: float, grammar: bool):
    """One slot: grammar mask -> greedy argmax -> temp scale -> filters."""
    s = pl.program_id(0)
    lg = lg_ref[0].astype(jnp.float32)                 # [V]
    if grammar:
        allow = ga_ref[0, 0]                           # [V] bool
        lg = jnp.where(allow, lg, jnp.finfo(jnp.float32).min)
    masked_ref[0] = lg
    greedy_ref[0] = jnp.argmax(lg).astype(jnp.int32)
    sc = lg / jnp.maximum(temps_ref[s], 1e-6)
    neg = jnp.finfo(sc.dtype).min
    if top_k > 0 and top_k < lg.shape[0]:
        # value-space kth-largest cutoff — same semantics as
        # generate.sample_logits (ties at the threshold all survive)
        kth = jax.lax.top_k(sc, top_k)[0][-1:]
        sc = jnp.where(sc < kth, neg, sc)
    if 0.0 < top_p < 1.0:
        # nucleus in value space: smallest prefix of the sorted probs
        # reaching top_p, the top token force-kept — mirroring
        # generate.sample_logits's shifted-cumsum form
        srt = jnp.sort(sc)[::-1]
        cum = jnp.cumsum(jax.nn.softmax(srt))
        keep = jnp.concatenate([jnp.zeros((1,), cum.dtype),
                                cum[:-1]]) < top_p
        keep = keep.at[0].set(True)
        cutoff = jnp.min(jnp.where(keep, srt, jnp.inf))
        sc = jnp.where(sc < cutoff, neg, sc)
    scaled_ref[0] = sc


def fused_sample_prep(
    logits: jax.Array,
    temps: jax.Array,
    gallow: jax.Array | None = None,
    gidx: jax.Array | None = None,
    gstate: jax.Array | None = None,
    *,
    top_k: int = 0,
    top_p: float = 0.0,
    interpret: bool = False,
):
    """Fused sampling prep over ``logits [S, V]``.

    - ``temps [S]`` f32 — per-slot temperatures (0 = greedy; the caller
      selects greedy vs sampled exactly like ``_slot_sample``);
    - ``gallow [G+1, n_states, V]`` bool / ``gidx [S]`` / ``gstate [S]``
      — the grammar pool's allow table and each slot's (program, state)
      coordinates (``gidx`` rows are always valid — unconstrained slots
      point at the sentinel all-True program), or all ``None`` for no
      grammar;
    - ``top_k`` (0 = off) / ``top_p`` (0.0 = off) — static filters
      applied to the scaled logits, value-space semantics matching
      ``generate.sample_logits``.

    Returns ``(masked [S, V] f32, scaled [S, V] f32, greedy [S] i32)``:
    ``masked`` is the constrain-masked logits (feed to top-logprobs /
    automaton advance), ``scaled`` the temperature-scaled filtered
    logits (feed to ``categorical``), ``greedy`` the argmax of
    ``masked``.
    """
    S, V = logits.shape
    grammar = gallow is not None
    temps = temps.astype(jnp.float32)
    if grammar:
        G1, n_states, _ = gallow.shape

        def ga_index(s, t, gi, gs):
            return (jnp.minimum(gi[s], G1 - 1),
                    jnp.minimum(gs[s], n_states - 1), 0)

        scalars = (temps, gidx.astype(jnp.int32), gstate.astype(jnp.int32))
        in_specs = [
            pl.BlockSpec((1, V), lambda s, *_: (s, 0)),
            pl.BlockSpec((1, 1, V), ga_index),
        ]
        operands = scalars + (logits, gallow)
    else:
        zero = jnp.zeros((S,), jnp.int32)
        scalars = (temps, zero, zero)
        in_specs = [pl.BlockSpec((1, V), lambda s, *_: (s, 0))]
        operands = scalars + (logits,)

    def kernel(*refs):
        if grammar:
            t_ref, gi_ref, gs_ref, lg_ref, ga_ref = refs[:5]
            outs = refs[5:8]
        else:
            t_ref, gi_ref, gs_ref, lg_ref = refs[:4]
            ga_ref = None
            outs = refs[4:7]
        _sample_kernel(t_ref, gi_ref, gs_ref, lg_ref, ga_ref, *outs,
                       top_k=top_k, top_p=top_p, grammar=grammar)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, V), lambda s, *_: (s, 0)),
            pl.BlockSpec((1, V), lambda s, *_: (s, 0)),
            pl.BlockSpec((1,), lambda s, *_: (s,)),
        ],
    )
    masked, scaled, greedy = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((S, V), jnp.float32),
            jax.ShapeDtypeStruct((S, V), jnp.float32),
            jax.ShapeDtypeStruct((S,), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(*operands)
    return masked, scaled, greedy


def fused_sample_reference(
    logits: jax.Array,
    temps: jax.Array,
    gallow: jax.Array | None = None,
    gidx: jax.Array | None = None,
    gstate: jax.Array | None = None,
    *,
    top_k: int = 0,
    top_p: float = 0.0,
):
    """Plain-jnp twin of :func:`fused_sample_prep` — the in-graph tail's
    math, spelled out (and the kernel's equivalence oracle)."""
    S, V = logits.shape
    lg = logits.astype(jnp.float32)
    if gallow is not None:
        allow = gallow[jnp.minimum(gidx, gallow.shape[0] - 1),
                       jnp.minimum(gstate, gallow.shape[1] - 1)]
        lg = jnp.where(allow, lg, jnp.finfo(jnp.float32).min)
    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    sc = lg / jnp.maximum(temps.astype(jnp.float32), 1e-6)[:, None]
    neg = jnp.finfo(sc.dtype).min
    if top_k > 0 and top_k < V:
        kth = jax.lax.top_k(sc, top_k)[0][..., -1:]
        sc = jnp.where(sc < kth, neg, sc)
    if 0.0 < top_p < 1.0:
        srt = jnp.sort(sc, axis=-1)[..., ::-1]
        cum = jnp.cumsum(jax.nn.softmax(srt, axis=-1), axis=-1)
        keep = jnp.concatenate(
            [jnp.zeros((S, 1), cum.dtype), cum[..., :-1]], axis=-1) < top_p
        keep = keep.at[..., 0].set(True)
        cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                         keepdims=True)
        sc = jnp.where(sc < cutoff, neg, sc)
    return lg, sc, greedy


def _residual_kernel(temps_ref, lt_ref, ld_ref, pt_ref, pd_ref, lr_ref):
    """One (slot, draft position): softmax pair + residual logits."""
    s = pl.program_id(0)
    temp = jnp.maximum(temps_ref[s], 1e-6)
    lt = lt_ref[0, 0].astype(jnp.float32) / temp       # [V]
    ld = ld_ref[0, 0].astype(jnp.float32) / temp
    pt = jax.nn.softmax(lt)
    pd = jax.nn.softmax(ld)
    pt_ref[0, 0] = pt
    pd_ref[0, 0] = pd
    res = jnp.maximum(pt - pd, 0.0)
    has_res = jnp.sum(res) > 0.0
    lr_ref[0, 0] = jnp.where(has_res, jnp.log(res + 1e-30), lt)


def fused_residual_prep(
    lt: jax.Array,
    ld: jax.Array,
    temps: jax.Array,
    *,
    interpret: bool = False,
):
    """Fused speculative-verify prep over ``lt``/``ld [S, k, V]``
    (target/draft logits at the k draft positions).

    Returns ``(pt, pd, res_logits)``, each ``[S, k, V]`` f32 —
    temperature-softmaxed target/draft distributions and the residual
    sampling logits (``log(max(pt - pd, 0) + 1e-30)``, falling back to
    ``lt/temp`` where the residual is empty) — exactly ``_accept``'s
    elementwise block, one kernel instead of a dispatch string.
    """
    S, k, V = lt.shape
    temps = temps.astype(jnp.float32)

    def index(s, j, *_):
        return (s, j, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S, k),
        in_specs=[
            pl.BlockSpec((1, 1, V), index),
            pl.BlockSpec((1, 1, V), index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, V), index),
            pl.BlockSpec((1, 1, V), index),
            pl.BlockSpec((1, 1, V), index),
        ],
    )
    pt, pd, lr = pl.pallas_call(
        functools.partial(_residual_kernel),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((S, k, V), jnp.float32),
            jax.ShapeDtypeStruct((S, k, V), jnp.float32),
            jax.ShapeDtypeStruct((S, k, V), jnp.float32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(temps, lt, ld)
    return pt, pd, lr


def fused_residual_reference(lt, ld, temps):
    """Plain-jnp twin of :func:`fused_residual_prep` (the `_accept`
    formulas, verbatim)."""
    temp = jnp.maximum(temps.astype(jnp.float32), 1e-6)[:, None, None]
    pt = jax.nn.softmax(lt.astype(jnp.float32) / temp, axis=-1)
    pd = jax.nn.softmax(ld.astype(jnp.float32) / temp, axis=-1)
    res = jnp.maximum(pt - pd, 0.0)
    has_res = jnp.sum(res, axis=-1, keepdims=True) > 0.0
    lr = jnp.where(has_res, jnp.log(res + 1e-30),
                   lt.astype(jnp.float32) / temp)
    return pt, pd, lr
