"""Pallas TPU fused linear kernels for the decode hot path: RoPE+QKV
projection and the LoRA gather-matmul.

:func:`fused_rope_qkv` fuses the decode step's QKV projection, the
head split/transpose, and the rotary embedding into one kernel over a
``(slots,)`` grid.  The unfused path runs these as separate HLOs —
Dense matmul, three slices, three reshape/transposes, then
``rope_rotate``'s trig tower — each round-tripping the ``[S, T, d]``
activations through HBM.  Here the weight tile stays resident in VMEM
across the slot loop, the per-slot VECTOR offsets (PR 11's paged
cursors) ride in as a scalar-prefetch operand, and the rotation applies
in-registers right after the matmul, bit-matching
``transformer.rope_rotate`` (same f32 angle/trig math, same half-split
layout).  The optional ``extra`` operand is the LoRA delta, applied
pre-rotation under its ``on`` mask — exactly where ``Block._ad``
applies it on the unfused path.

:func:`lora_delta` is the in-kernel LoRA gather-matmul: instead of
``gather_collection`` materializing each slot's ``[d_in, r]`` /
``[r, d_out]`` factors with an in-graph gather before a batched double
matmul, the FULL adapter pool rides in and each slot's grid step DMAs
only its own factor block, addressed through the scalar-prefetched
adapter ids — the same indirection discipline as the paged KV walk
(sentinel ids clamp; the caller keeps the ``on`` mask select, so
adapter-less slots stay bit-identical to the base model).

``interpret=True`` (any non-TPU backend) is the tier-1 CPU path.
Weight/factor tiles are loaded whole per grid step — fine for the model
sizes this repo runs; tile the contraction dimension before pointing
this at multi-GB weights.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def _rope_qkv_kernel(off_ref, on_ref, h_ref, w_ref, e_ref,
                     q_ref, k_ref, v_ref, *, n_heads: int, n_kv: int,
                     dh: int, base: float, rope: bool, has_extra: bool):
    """One slot: matmul -> (+ masked LoRA delta) -> split -> rotate."""
    s = pl.program_id(0)
    hm = h_ref[0]                                      # [T, d]
    qkv = jnp.dot(hm, w_ref[...])                      # [T, d + 2*kv_dim]
    if has_extra:
        qkv = jnp.where(on_ref[s] != 0, qkv + e_ref[0], qkv)
    T = hm.shape[0]

    def heads(t, n):                                   # [T, n*dh] -> [n, T, dh]
        return t.reshape(T, n, dh).transpose(1, 0, 2)

    qh = heads(qkv[:, : n_heads * dh], n_heads)
    kh = heads(qkv[:, n_heads * dh: (n_heads + n_kv) * dh], n_kv)
    vh = heads(qkv[:, (n_heads + n_kv) * dh:], n_kv)
    if rope:
        # mirror transformer.rope_rotate bit-for-bit: f32 angles from
        # the slot's absolute offset, GPT-NeoX half-split rotation
        half = dh // 2
        freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
        positions = off_ref[s].astype(jnp.float32) + jnp.arange(
            T, dtype=jnp.float32)
        angles = positions[:, None] * freqs[None]      # [T, half]
        sin, cos = jnp.sin(angles), jnp.cos(angles)

        def rot(x):                                    # [n, T, dh]
            x1 = x[..., :half].astype(jnp.float32)
            x2 = x[..., half:].astype(jnp.float32)
            return jnp.concatenate(
                [x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                axis=-1).astype(x.dtype)

        qh, kh = rot(qh), rot(kh)
    q_ref[0] = qh.astype(q_ref.dtype)
    k_ref[0] = kh.astype(k_ref.dtype)
    v_ref[0] = vh.astype(v_ref.dtype)


def fused_rope_qkv(
    h: jax.Array,
    w: jax.Array,
    offsets: jax.Array,
    extra: jax.Array | None = None,
    on: jax.Array | None = None,
    *,
    n_heads: int,
    n_kv: int,
    dh: int,
    base: float = 10000.0,
    rope: bool = True,
    interpret: bool = False,
):
    """Fused QKV projection + head split + rotary embedding.

    - ``h [S, T, d]`` — post-norm activations in the compute dtype;
    - ``w [d, n_heads*dh + 2*n_kv*dh]`` — the ``qkv`` Dense kernel
      (same param, fetched via ``_Kernel``), compute dtype;
    - ``offsets [S]`` int32 — each slot's absolute position of the
      window's first token (the rope offset vector);
    - ``extra [S, T, d + 2*kv_dim]`` — optional additive delta (the
      LoRA qkv delta), applied pre-rotation where ``on [S]`` is
      nonzero — the ``Block._ad`` contract in-kernel;
    - ``rope=False`` skips rotation (non-rope models still win the
      dispatch fusion).

    Returns ``(q [S, n_heads, T, dh], k [S, n_kv, T, dh], v)`` with q/k
    already rotated — feed straight to the attention arms with their
    own rope skipped.
    """
    S, T, d = h.shape
    dtot = w.shape[1]
    if w.shape[0] != d or dtot != (n_heads + 2 * n_kv) * dh:
        raise ValueError(f"qkv kernel shape {w.shape} does not match "
                         f"d={d}, n_heads={n_heads}, n_kv={n_kv}, dh={dh}")
    has_extra = extra is not None
    if on is None:
        on = jnp.ones((S,), jnp.int32)
    scalars = (offsets.astype(jnp.int32), on.astype(jnp.int32))

    def hidx(s, *_):
        return (s, 0, 0)

    in_specs = [
        pl.BlockSpec((1, T, d), hidx),
        pl.BlockSpec((d, dtot), lambda s, *_: (0, 0)),
    ]
    operands = scalars + (h, w)
    if has_extra:
        in_specs.append(pl.BlockSpec((1, T, dtot), hidx))
        operands = operands + (extra,)

    def kernel(*refs):
        off_ref, on_ref = refs[0], refs[1]
        h_ref, w_ref = refs[2], refs[3]
        e_ref = refs[4] if has_extra else None
        outs = refs[5:] if has_extra else refs[4:]
        _rope_qkv_kernel(off_ref, on_ref, h_ref, w_ref, e_ref, *outs,
                         n_heads=n_heads, n_kv=n_kv, dh=dh, base=base,
                         rope=rope, has_extra=has_extra)

    def oidx(s, *_):
        return (s, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, n_heads, T, dh), oidx),
            pl.BlockSpec((1, n_kv, T, dh), oidx),
            pl.BlockSpec((1, n_kv, T, dh), oidx),
        ],
    )
    q, k, v = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((S, n_heads, T, dh), h.dtype),
            jax.ShapeDtypeStruct((S, n_kv, T, dh), h.dtype),
            jax.ShapeDtypeStruct((S, n_kv, T, dh), h.dtype),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(2 * S * T * d * dtot),
            transcendentals=int(S * T * dh),
            bytes_accessed=int((h.size + S * w.size + 3 * S * T * dtot)
                               * h.dtype.itemsize),
        ),
        interpret=interpret,
    )(*operands)
    return q, k, v


def fused_rope_qkv_reference(h, w, offsets, extra=None, on=None, *,
                             n_heads, n_kv, dh, base=10000.0, rope=True):
    """Plain-jnp twin: Dense matmul + `_ad` select + head split +
    `rope_rotate`, composed exactly as `Block.__call__` does."""
    from tpudist.models.transformer import rope_rotate
    S, T, d = h.shape
    qkv = h @ w
    if extra is not None:
        m = (on if on is not None else jnp.ones((S,), bool))
        qkv = jnp.where(m[:, None, None] != 0, qkv + extra, qkv)

    def heads(t, n):
        return t.reshape(S, T, n, dh).transpose(0, 2, 1, 3)

    q = heads(qkv[..., : n_heads * dh], n_heads)
    k = heads(qkv[..., n_heads * dh: (n_heads + n_kv) * dh], n_kv)
    v = heads(qkv[..., (n_heads + n_kv) * dh:], n_kv)
    if rope:
        q = rope_rotate(q, base=base, offset=offsets)
        k = rope_rotate(k, base=base, offset=offsets)
    return q, k, v


def _lora_kernel(ids_ref, x_ref, a_ref, b_ref, o_ref):
    """One slot: double matmul against its own factor block."""
    x = x_ref[0]                                       # [T, din]
    a = a_ref[0, 0].astype(x.dtype)                    # [din, r]
    bm = b_ref[0, 0].astype(x.dtype)                   # [r, dout]
    o_ref[0] = jnp.dot(jnp.dot(x, a), bm).astype(o_ref.dtype)


def lora_delta(
    x: jax.Array,
    pool_a: jax.Array,
    pool_b: jax.Array,
    ids: jax.Array,
    *,
    layer: int,
    interpret: bool = False,
):
    """In-kernel LoRA gather-matmul: ``delta[s] = (x[s] @ A[ids[s]]) @
    B[ids[s]]`` without materializing the gathered factors.

    - ``x [S, T, d_in]`` — activations in the compute dtype;
    - ``pool_a [L, B, d_in, r]`` / ``pool_b [L, B, r, d_out]`` — the
      FULL adapter pool (f32 factors, cast to the compute dtype
      in-registers, matching ``Block._ad``);
    - ``ids [S]`` int32 — per-slot adapter block ids (sentinel ``B`` =
      no adapter; clamped here, masked by the caller's ``on`` select).

    Returns ``[S, T, d_out]`` in ``x.dtype``.
    """
    S, T, d_in = x.shape
    L, B, _, r = pool_a.shape
    d_out = pool_b.shape[-1]
    if not 0 <= layer < L:
        raise ValueError(f"layer {layer} out of range [0, {L})")

    def a_index(s, ids_ref):
        return (layer, jnp.minimum(ids_ref[s], B - 1), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, T, d_in), lambda s, *_: (s, 0, 0)),
            pl.BlockSpec((1, 1, d_in, r), a_index),
            pl.BlockSpec((1, 1, r, d_out), a_index),
        ],
        out_specs=pl.BlockSpec((1, T, d_out), lambda s, *_: (s, 0, 0)),
    )
    out = pl.pallas_call(
        _lora_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, T, d_out), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(2 * S * T * r * (d_in + d_out)),
            transcendentals=0,
            bytes_accessed=int(
                (x.size + S * (d_in * r + r * d_out) + S * T * d_out)
                * x.dtype.itemsize),
        ),
        interpret=interpret,
    )(ids.astype(jnp.int32), x, pool_a, pool_b)
    return out


def lora_delta_reference(x, pool_a, pool_b, ids, *, layer):
    """Plain-jnp twin: `gather_collection`'s gather + `Block._ad`'s
    double matmul."""
    B = pool_a.shape[1]
    rows = jnp.minimum(ids, B - 1)
    a = pool_a[layer][rows].astype(x.dtype)            # [S, d_in, r]
    bm = pool_b[layer][rows].astype(x.dtype)           # [S, r, d_out]
    return (x @ a) @ bm
