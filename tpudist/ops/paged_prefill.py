"""Pallas TPU paged-PREFILL flash-attention kernel: block-table walk plus
in-kernel KV block WRITES.

:mod:`tpudist.ops.paged_attention` closed the decode path's dense
``[slots, max_len]`` gather; this kernel closes the last one — prefill.
The gather prefill path (``_force_chunk``) teacher-forces a chunk one
token at a time over a DENSE per-lane cache gathered from the pool up
front and scattered back afterwards (``_Paged.commit_lanes`` /
``commit_window``), so bytes moved scale with POOL GEOMETRY and the
chunk runs as ``prefill_pad`` sequential dispatches.  Here the whole
batch of chunks runs in ONE fused dispatch per layer:

- the reused prefix (prefix caching / chunked prefill) is walked
  straight out of the pool via the scalar-prefetched block table,
  exactly like the decode kernel — bytes read scale with live prefix;
- the chunk attends to itself under the causal mask as the walk's
  final virtual block (FlashAttention-2 online softmax throughout);
- the blocks the chunk TOUCHES (``ceil`` span of ``[pos0, pos0+clen)``)
  are then emitted as quantized pool blocks in-kernel: the original
  block is read back (partial first block of a chunked-prefill step
  keeps its committed prefix), the chunk's fresh K/V is overlaid via an
  exact one-hot gather, and the merged block is requantized with the
  same ``amax/127`` formula as ``_Paged._scatter_values`` — the caller
  scatters the returned blocks with a sentinel-dropping ``.at[].set``
  (``_Paged.commit_quantized``), never materializing a dense view.

Grid: ``(slots, kv_heads, M + 1 + Mw)`` — ``M`` prefix walk steps (dead
steps past a lane's live count elide their DMA by repeating the last
block index), one chunk self-attention step that also emits the
attention output, then ``Mw`` write steps addressed through a second
scalar-prefetched table (``wtable``) holding the touched blocks' ids
(sentinel rows — dead lanes, untouched tail — clamp and are dropped at
commit).  Because positions at/after ``pos0 + clen`` keep the ORIGINAL
block contents, a partially-filled block's quantization scale is not
polluted by another lane's garbage — slightly better int8 numerics than
the gather path's dense round-trip, same masking contract.

``interpret=True`` (any non-TPU backend) is the tier-1 CPU path.  The
scale outputs use rank-3 ``(1, 1, 1)`` blocks, fine under the
interpreter; native lowering keeps them in VMEM (revisit as SMEM
outputs if a real-TPU run objects).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MASK_VALUE = -1e30

_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def _kernel(table_ref, wtable_ref, pos_ref, clen_ref, sk_ref, sv_ref,
            q_ref, kn_ref, vn_ref, pk_ref, pv_ref,
            o_ref, ok_ref, ov_ref, osk_ref, osv_ref,
            m_ref, l_ref, acc_ref, *, layer: int, block_size: int,
            chunk: int, n_prefix: int, quantized: bool, scale: float,
            window):
    """One (slot, kv_head, step) grid step.

    Steps ``j < live(slot)`` walk the prefix out of the pool;
    ``j == n_prefix`` is the chunk's causal self-attention and emits the
    normalized output; ``j > n_prefix`` are the write steps — each reads
    the touched block's ORIGINAL contents (same ref pair as the walk,
    re-aimed by the shared index map), overlays the chunk's K/V, and
    emits the requantized block + scale.
    """
    b = pl.program_id(0)
    h = pl.program_id(1)
    j = pl.program_id(2)
    bs = block_size
    P = chunk
    M = n_prefix

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _MASK_VALUE)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    pos0 = pos_ref[b]
    cl = clen_ref[b]
    live = lax.div(pos0 + bs - 1, bs)

    def update(s_tile, v_tile):
        """FlashAttention-2 online-softmax rescale/accumulate (the same
        recurrence as ops/paged_attention.py)."""
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s_tile, axis=-1))
        p = jnp.exp(s_tile - m_new[:, None])
        corr = jnp.exp(m - m_new)
        m_ref[:, 0] = m_new
        l_ref[:, 0] = l * corr + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jnp.dot(
            p.astype(v_tile.dtype), v_tile,
            preferred_element_type=jnp.float32)

    @pl.when(j < live)
    def _():
        # prefix walk: identical contract to the decode kernel — pool
        # positions below pos0 are the live prefix, masked hard past it
        q = q_ref[0, 0]                       # [R, dh] (R = group * P)
        k = pk_ref[0, 0, 0]                   # [bs, dh] storage dtype
        v = pv_ref[0, 0, 0]
        if quantized:
            bid = jnp.minimum(table_ref[b, j], sk_ref.shape[1] - 1)
            k = k.astype(q.dtype) * sk_ref[layer, bid, h].astype(q.dtype)
            v = v.astype(q.dtype) * sv_ref[layer, bid, h].astype(q.dtype)
        st = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        R, _ = st.shape
        kpos = j * bs + lax.broadcasted_iota(jnp.int32, (R, bs), 1)
        keep = kpos < pos0
        if window is not None:
            qpos = pos0 + lax.broadcasted_iota(jnp.int32, (R, bs), 0) % P
            keep &= kpos > qpos - window
        update(jnp.where(keep, st, _MASK_VALUE), v)

    @pl.when(j == M)
    def _():
        # the chunk is the walk's final virtual block: query i sees
        # chunk columns 0..i (itself included), so every row keeps at
        # least its own token and l > 0 — padding rows past clen emit
        # garbage the caller never reads (causality: row i's output only
        # depends on columns <= i)
        q = q_ref[0, 0]
        k = kn_ref[0, 0]                      # [P, dh] compute dtype
        v = vn_ref[0, 0]
        st = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        R, _ = st.shape
        col = lax.broadcasted_iota(jnp.int32, (R, P), 1)
        row_i = lax.broadcasted_iota(jnp.int32, (R, P), 0) % P
        keep = col <= row_i
        if window is not None:
            keep &= (pos0 + col) > (pos0 + row_i) - window
        update(jnp.where(keep, st, _MASK_VALUE), v)
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, 0][:, None]).astype(o_ref.dtype)

    @pl.when(j > M)
    def _():
        # write step w: merge chunk K/V into touched block t0 + w and
        # requantize, bit-matching _Paged._scatter_values.  Positions
        # outside [pos0, pos0 + clen) keep the ORIGINAL block contents
        # (chunked prefill's partial first block; untouched tail).
        w = j - (M + 1)
        bid = jnp.minimum(wtable_ref[b, w], sk_ref.shape[1] - 1)
        blk0 = (lax.div(pos0, bs) + w) * bs
        kpos = blk0 + lax.broadcasted_iota(jnp.int32, (bs, 1), 0)
        in_new = (kpos >= pos0) & (kpos < pos0 + cl)
        # one-hot gather from the chunk: each in-range row selects
        # exactly one chunk position, so the matmul is exact
        sel = ((kpos - pos0)
               == lax.broadcasted_iota(jnp.int32, (bs, P), 1)) & in_new
        selm = sel.astype(jnp.float32)

        def emit(chunk_ref, pool_ref, sc_ref, oq_ref, osc_ref):
            orig = pool_ref[0, 0, 0]          # [bs, dh] storage dtype
            cdtype = chunk_ref.dtype
            if quantized:
                orig = orig.astype(cdtype) * sc_ref[layer, bid, h].astype(
                    cdtype)
            else:
                orig = orig.astype(cdtype)
            new = jnp.dot(selm, chunk_ref[0, 0].astype(jnp.float32),
                          preferred_element_type=jnp.float32).astype(cdtype)
            merged = jnp.where(in_new, new, orig)
            if quantized:
                v32 = merged.astype(jnp.float32)
                amax = jnp.max(jnp.abs(v32))
                sc = jnp.where(amax > 0, amax / 127.0, 1.0)
                oq_ref[0, 0, 0] = jnp.clip(
                    jnp.round(v32 / sc), -127, 127).astype(oq_ref.dtype)
                osc_ref[0, 0, 0] = sc
            else:
                oq_ref[0, 0, 0] = merged.astype(oq_ref.dtype)
                osc_ref[0, 0, 0] = 1.0

        emit(kn_ref, pk_ref, sk_ref, ok_ref, osk_ref)
        emit(vn_ref, pv_ref, sv_ref, ov_ref, osv_ref)


def paged_prefill_attention(
    q: jax.Array,
    k_new: jax.Array,
    v_new: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    scale_k: jax.Array,
    scale_v: jax.Array,
    table: jax.Array,
    wtable: jax.Array,
    pos0: jax.Array,
    clen: jax.Array,
    *,
    layer: int,
    window: int | None = None,
    interpret: bool = False,
):
    """Paged prefill attention + in-kernel block writes, one model layer.

    - ``q [S, n_heads, P, dh]`` — the chunk's queries, already
      rope-rotated at absolute positions ``pos0 + i``;
    - ``k_new``/``v_new [S, n_kv, P, dh]`` — the chunk's fresh K
      (rotated) / V in the compute dtype;
    - ``pool_k``/``pool_v``/``scale_k``/``scale_v``/``table``/``pos0``
      — exactly as in :func:`tpudist.ops.paged_attention.paged_attention`;
    - ``wtable [S, Mw]`` int32 — physical ids of the blocks the chunk
      touches (logical blocks ``pos0 // bs + w``), sentinel
      ``num_blocks`` for dead lanes / untouched tail (their emitted
      blocks are garbage the commit scatter drops);
    - ``clen [S]`` int32 — the chunk's live length per lane (ragged;
      ``clen <= P``); queries/writes past it are garbage-by-contract.

    Returns ``(o, qk, qv, sk, sv)``: attention output
    ``[S, n_heads, P, dh]`` in ``q.dtype``, the touched blocks
    ``[S, Mw, n_kv, bs, dh]`` in the pool's storage dtype, and their
    dequant scales ``[S, Mw, n_kv]`` f32 (all-ones when the pool is not
    quantized).  Feed the last four to ``_Paged.commit_quantized``.
    """
    S, nh, P, dh = q.shape
    L, nb, n_kv, bs, _ = pool_k.shape
    M = table.shape[1]
    Mw = wtable.shape[1]
    if nh % n_kv:
        raise ValueError(f"n_heads {nh} must be a multiple of n_kv {n_kv}")
    if not 0 <= layer < L:
        raise ValueError(f"layer {layer} out of range [0, {L})")
    group = nh // n_kv
    R = group * P
    quantized = pool_k.dtype == jnp.int8
    q4 = q.reshape(S, n_kv, R, dh)

    def chunk_index(b, h, j, *_):
        return (b, h, 0, 0)

    def pool_index(b, h, j, tbl, wtbl, pos, cl, *_):
        # walk steps (j <= M) follow the table, clamped to the last live
        # block so dead steps elide their DMA; write steps re-aim the
        # SAME ref pair at the touched block to read its original
        # contents for the merge
        live1 = jnp.maximum(lax.div(pos[b] + bs - 1, bs), 1)
        walk = jnp.minimum(tbl[b, jnp.minimum(j, live1 - 1)], nb - 1)
        w = jnp.clip(j - (M + 1), 0, Mw - 1)
        wr = jnp.minimum(wtbl[b, w], nb - 1)
        return (layer, jnp.where(j <= M, walk, wr), h, 0, 0)

    def wblock_index(b, h, j, *_):
        return (b, jnp.clip(j - (M + 1), 0, Mw - 1), h, 0, 0)

    def wscale_index(b, h, j, *_):
        return (b, jnp.clip(j - (M + 1), 0, Mw - 1), h)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(S, n_kv, M + 1 + Mw),
        in_specs=[
            pl.BlockSpec((1, 1, R, dh), chunk_index),   # q4
            pl.BlockSpec((1, 1, P, dh), chunk_index),   # k_new
            pl.BlockSpec((1, 1, P, dh), chunk_index),   # v_new
            pl.BlockSpec((1, 1, 1, bs, dh), pool_index),
            pl.BlockSpec((1, 1, 1, bs, dh), pool_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, R, dh), chunk_index),
            pl.BlockSpec((1, 1, 1, bs, dh), wblock_index),
            pl.BlockSpec((1, 1, 1, bs, dh), wblock_index),
            pl.BlockSpec((1, 1, 1), wscale_index),
            pl.BlockSpec((1, 1, 1), wscale_index),
        ],
        scratch_shapes=[
            pltpu.VMEM((R, 1), jnp.float32),   # m (running row max)
            pltpu.VMEM((R, 1), jnp.float32),   # l (running normalizer)
            pltpu.VMEM((R, dh), jnp.float32),  # acc (unnormalized out)
        ],
    )
    kernel = functools.partial(
        _kernel, layer=layer, block_size=bs, chunk=P, n_prefix=M,
        quantized=quantized, scale=dh ** -0.5, window=window)
    work = S * n_kv * R * (M * bs + P)
    storage = pool_k.dtype
    o, qk, qv, sk, sv = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((S, n_kv, R, dh), q.dtype),
            jax.ShapeDtypeStruct((S, Mw, n_kv, bs, dh), storage),
            jax.ShapeDtypeStruct((S, Mw, n_kv, bs, dh), storage),
            jax.ShapeDtypeStruct((S, Mw, n_kv), jnp.float32),
            jax.ShapeDtypeStruct((S, Mw, n_kv), jnp.float32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * work * dh),
            transcendentals=int(work),
            bytes_accessed=int(
                (q4.size + 2 * S * n_kv * (M + Mw) * bs * dh
                 + k_new.size + v_new.size + q4.size
                 + 2 * S * Mw * n_kv * bs * dh) * q.dtype.itemsize),
        ),
        interpret=interpret,
    )(table, wtable, pos0, clen, scale_k, scale_v,
      q4, k_new, v_new, pool_k, pool_v)
    return o.reshape(S, nh, P, dh), qk, qv, sk, sv


paged_prefill_attention.supports_gqa = True


def paged_prefill_reference(
    q: jax.Array,
    k_new: jax.Array,
    v_new: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    scale_k: jax.Array,
    scale_v: jax.Array,
    table: jax.Array,
    wtable: jax.Array,
    pos0: jax.Array,
    clen: jax.Array,
    *,
    layer: int,
    window: int | None = None,
):
    """Gather-to-dense XLA reference with the identical mask/merge/quant
    contract — the equivalence oracle for the kernel's tests and the
    plain-jnp documentation of its math."""
    S, nh, P, dh = q.shape
    L, nb, n_kv, bs, _ = pool_k.shape
    M = table.shape[1]
    Mw = wtable.shape[1]
    group = nh // n_kv
    rows = jnp.minimum(table, nb - 1)
    compute = q.dtype

    def view(pool, scale):
        g = pool[layer][rows].astype(compute)          # [S, M, nk, bs, dh]
        if pool.dtype == jnp.int8:
            sc = scale[layer][rows]                    # [S, M, nk]
            g = g * sc[..., None, None].astype(compute)
        g = jnp.moveaxis(g, 2, 1)                      # [S, nk, M, bs, dh]
        return g.reshape(S, n_kv, M * bs, dh)

    ks = jnp.concatenate([view(pool_k, scale_k), k_new], axis=2)
    vs = jnp.concatenate([view(pool_v, scale_v), v_new], axis=2)
    scale = dh ** -0.5
    qg = q.reshape(S, n_kv, group, P, dh)
    scores = jnp.einsum("bngqd,bnkd->bngqk", qg, ks,
                        preferred_element_type=jnp.float32) * scale
    kpos = jnp.arange(M * bs + P)
    in_pool = kpos < M * bs
    row = jnp.arange(P)
    live = jnp.where(
        in_pool[None, None],
        kpos[None, None] < pos0[:, None, None],
        (kpos[None, None] - M * bs) <= row[None, :, None])
    if window is not None:
        qpos = pos0[:, None] + row[None]                       # [S, P]
        abs_k = jnp.where(in_pool[None, None], kpos[None, None],
                          pos0[:, None, None] + kpos[None, None] - M * bs)
        live &= abs_k > qpos[:, :, None] - window
    scores = jnp.where(live[:, None, None], scores, _MASK_VALUE)
    w = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bngqk,bnkd->bngqd", w.astype(compute), vs,
                   preferred_element_type=jnp.float32)
    o = o.reshape(S, nh, P, dh).astype(q.dtype)

    # --- writes: merge the chunk into the touched blocks + requantize
    wrows = jnp.minimum(wtable, nb - 1)                        # [S, Mw]
    blk0 = (pos0[:, None] // bs + jnp.arange(Mw)[None]) * bs   # [S, Mw]
    kpos_w = blk0[..., None] + jnp.arange(bs)[None, None]      # [S, Mw, bs]
    in_new = ((kpos_w >= pos0[:, None, None])
              & (kpos_w < (pos0 + clen)[:, None, None]))
    ci = jnp.clip(kpos_w - pos0[:, None, None], 0, P - 1)

    def write(chunk, pool, scale):
        orig = pool[layer][wrows].astype(compute)      # [S, Mw, nk, bs, dh]
        if pool.dtype == jnp.int8:
            sc = scale[layer][wrows]
            orig = orig * sc[..., None, None].astype(compute)
        idx = jnp.broadcast_to(ci[:, :, None, :, None],
                               (S, Mw, n_kv, bs, dh))
        src = jnp.broadcast_to(chunk[:, None], (S, Mw, n_kv, P, dh))
        new = jnp.take_along_axis(src, idx, axis=3)
        merged = jnp.where(in_new[:, :, None, :, None],
                           new.astype(compute), orig)
        if pool.dtype == jnp.int8:
            v32 = merged.astype(jnp.float32)
            amax = jnp.max(jnp.abs(v32), axis=(-2, -1))
            sc = jnp.where(amax > 0, amax / 127.0, 1.0)
            qq = jnp.clip(jnp.round(v32 / sc[..., None, None]),
                          -127, 127).astype(jnp.int8)
            return qq, sc.astype(jnp.float32)
        return (merged.astype(pool.dtype),
                jnp.ones((S, Mw, n_kv), jnp.float32))

    qk, sk = write(k_new, pool_k, scale_k)
    qv, sv = write(v_new, pool_v, scale_v)
    return o, qk, qv, sk, sv
