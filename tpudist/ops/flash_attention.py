"""Pallas TPU flash attention (blockwise online-softmax) kernel.

The single-chip hot op behind the long-context path: materializes no
``[seq, seq]`` score matrix — Q blocks stream from HBM into VMEM per grid
step, K/V blocks are walked with a ``fori_loop`` carrying the (m, l, acc)
online-softmax triple, both matmuls per block land on the MXU.  Combined
with :mod:`tpudist.parallel.ring_attention` (which rotates K/V between
chips), this covers intra-chip blocking while the ring covers inter-chip
sharding.

Backward: ``jax.custom_vjp`` whose bwd recomputes attention with the dense
XLA formulation and differentiates that — flash recompute-style memory
behavior on the forward, XLA-fused gradients on the backward.  The fwd/bwd
outputs match ``attention_reference`` exactly (see tests).

No reference counterpart (the reference has no attention and ships no
kernels of its own — SURVEY.md §0, §5.7); this is TPU-native capability.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudist.parallel.ring_attention import attention_reference

_MASK_VALUE = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float):
    """One grid step: one Q block against every K/V block of its (b,h) row.

    Ref shapes: q/o ``[1, block_q, d]``; k/v ``[1, seq_k, d]`` (whole row in
    VMEM — block over KV too if seq outgrows VMEM; the ring shards first).
    """
    q = q_ref[0].astype(jnp.float32) * scale
    block_q, d = q.shape
    seq_k = k_ref.shape[1]
    num_kv = seq_k // block_k
    qi = pl.program_id(1)

    def body(kv, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kv * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kv * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _MASK_VALUE)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    if causal:
        # Blocks strictly above the diagonal are fully masked — skip them.
        num_live = jnp.minimum(
            ((qi + 1) * block_q + block_k - 1) // block_k, num_kv
        )
        m, l, acc = lax.fori_loop(0, num_live, body, (m0, l0, acc0))
    else:
        m, l, acc = lax.fori_loop(0, num_kv, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal, block_q, block_k, interpret):
    batch, heads, seq_q, d = q.shape
    seq_k = k.shape[2]
    bq = min(block_q, seq_q)
    bk = min(block_k, seq_k)
    if seq_q % bq or seq_k % bk:
        raise ValueError(
            f"seq lengths ({seq_q}, {seq_k}) must divide block sizes ({bq}, {bk})"
        )
    scale = d ** -0.5
    bh = batch * heads
    qr = q.reshape(bh, seq_q, d)
    kr = k.reshape(bh, seq_k, d)
    vr = v.reshape(bh, seq_k, d)

    kernel = functools.partial(
        _flash_kernel, block_k=bk, causal=causal, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
        grid=(bh, seq_q // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(batch, heads, seq_q, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention over ``[batch, heads, seq, head_dim]`` inputs.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU
    testing); on TPU leave it False.
    """
    return _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(
        functools.partial(attention_reference, causal=causal), q, k, v
    )
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
