"""Pallas TPU flash attention (blockwise online-softmax) kernel.

The single-chip hot op behind the long-context path: materializes no
``[seq, seq]`` score matrix — the grid is (batch·heads, q_block, kv_block)
with KV innermost, the (m, l, acc) online-softmax state lives in VMEM
scratch across each Q row's KV sweep, and only one [block_k, d] K/V tile
is VMEM-resident at a time (sequence length is bounded by HBM, not VMEM);
both matmuls per block land on the MXU.  Combined with
:mod:`tpudist.parallel.ring_attention` (which rotates K/V between chips),
this covers intra-chip blocking while the ring covers inter-chip sharding.

Backward: ``jax.custom_vjp`` with two Pallas kernels (the standard
FlashAttention-2 split): the forward additionally emits the per-row
logsumexp, the host computes ``delta = rowsum(dO · O)``, then a dq kernel
(KV innermost, dq accumulated in VMEM across the KV sweep) and a dk/dv
kernel (Q innermost, dk/dv accumulated across the Q sweep) reconstruct
``p = exp(s − lse)`` per tile — no [seq, seq] matrix is ever materialized
forward or backward, and both causal variants elide dead-block DMAs the
same way the forward does.  Fwd and bwd match ``attention_reference``
numerically (see tests).  ``blockwise_attention`` (plain-XLA scan with the
same online-softmax math) remains as the kernel-free fallback path.

No reference counterpart (the reference has no attention and ships no
kernels of its own — SURVEY.md §0, §5.7); this is TPU-native capability.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Renamed across pallas versions (TPUCompilerParams -> CompilerParams) —
# same shim as the other kernel families in this package.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

from tpudist.parallel.ring_attention import (
    _block_update,
    _causal_mask,
    attention_reference,
)

_MASK_VALUE = -1e30


def _normalize_band(causal, window):
    """Reduce (causal, window) to the internal band ``lo <= q − k < hi``
    (either side ``None`` = unbounded).

    ``window`` forms: ``None`` (plain causal / full), an ``int`` W
    (causal sliding window: band [0, W)), or an explicit ``(lo, hi)``
    tuple (a shifted band in LOCAL coordinates — how ring attention
    expresses an off-diagonal hop, where the global offset q − k = t·S
    is static; requires ``causal=False`` since the band subsumes it).
    """
    if window is None:
        return (0, None) if causal else (None, None)
    if isinstance(window, tuple):
        if causal:
            raise ValueError("band-tuple window subsumes causal; pass "
                             "causal=False")
        lo, hi = window
        if lo is not None and hi is not None and lo >= hi:
            raise ValueError(f"empty band: lo {lo} >= hi {hi}")
        return lo, hi
    if not causal:
        raise ValueError("sliding window requires causal=True")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return 0, window


def _band_live_pairs(seq_q: int, seq_k: int, lo, hi) -> int:
    """Exact number of (q, k) pairs inside the band — the FLOP-proportional
    work the cost estimates feed the XLA scheduler (a hi-only ring-hop band
    can be a thin corner; calling it dense would overstate work by the
    seq/window ratio)."""
    import numpy as np

    q = np.arange(seq_q)
    k_hi = np.minimum(q - (lo if lo is not None else -seq_k), seq_k - 1)
    k_lo = np.maximum(q - ((hi if hi is not None else seq_q + seq_k) - 1), 0)
    return int(np.clip(k_hi - k_lo + 1, 0, None).sum())


def _tile_live(qi, kv, block_q: int, block_k: int, lo, hi):
    """Whether tile (qi, kv) intersects the band ``lo <= q − k < hi``.
    The unbounded form keeps a traced always-true predicate so every
    variant flows through the same ``pl.when``."""
    live = kv >= 0
    if lo is not None:
        # max(q − k) over the tile = (qi+1)·bq − 1 − kv·bk
        live &= (qi + 1) * block_q - 1 - kv * block_k >= lo
    if hi is not None:
        # min(q − k) over the tile = qi·bq − ((kv+1)·bk − 1)
        live &= qi * block_q - ((kv + 1) * block_k - 1) < hi
    return live


def _tile_interior(qi, kv, block_q: int, block_k: int, lo, hi):
    """Whether EVERY (q, k) pair of tile (qi, kv) lies inside the band —
    the band mask is then a provable no-op.  Interior tiles skip the
    whole VPU mask chain (two [bq, bk] iotas + compare + select per
    tile); at d_head 64 the kernel is VPU-bound, not MXU-bound, and on
    causal long-sequence grids most live tiles are interior (s=8192,
    1024-tiles: 28 of 36), so this is where the attention time goes."""
    inside = kv >= 0
    if lo is not None:
        # min(q − k) over the tile = qi·bq − ((kv+1)·bk − 1)
        inside &= qi * block_q - ((kv + 1) * block_k - 1) >= lo
    if hi is not None:
        # max(q − k) over the tile = (qi+1)·bq − 1 − kv·bk
        inside &= (qi + 1) * block_q - 1 - kv * block_k < hi
    return inside


def _masked_tile_branches(live, qi, kv, block_q: int, block_k: int, lo, hi,
                          tile):
    """Run ``tile(mask=...)`` under the live predicate, splitting interior
    tiles (mask elided) from band-edge tiles (mask applied).  Bandless
    kernels keep the single unmasked branch."""
    if lo is None and hi is None:
        @pl.when(live)
        def _():
            tile(mask=False)
        return
    interior = _tile_interior(qi, kv, block_q, block_k, lo, hi)

    @pl.when(live & interior)
    def _():
        tile(mask=False)

    @pl.when(live & jnp.logical_not(interior))
    def _():
        tile(mask=True)


def _tile_band_mask(s, qi, kv, block_q: int, block_k: int, lo, hi):
    """Mask score tile ``s`` at tile coords (qi, kv) to the band."""
    if lo is None and hi is None:
        return s
    q_pos = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = kv * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    keep = None
    if lo is not None:
        keep = q_pos - k_pos >= lo
    if hi is not None:
        upper = q_pos - k_pos < hi
        keep = upper if keep is None else keep & upper
    return jnp.where(keep, s, _MASK_VALUE)


def _last_live_kv(qi, nkv, block_q: int, block_k: int, lo):
    """Index of Q row ``qi``'s last live KV tile (the emission point of the
    KV-innermost sweeps).  Only the band's lower edge bounds it: k ranges
    up to q − lo."""
    if lo is None:
        return nkv - 1
    return jnp.clip(
        ((qi + 1) * block_q - 1 - lo) // block_k, 0, nkv - 1
    )


def _band_kv_index(block_q: int, block_k: int, lo, hi, nkv: int):
    """Index map for the KV-innermost sweeps: dead KV tiles (outside the
    band on either side) re-map to the Q row's nearest live tile — Pallas
    elides the DMA when consecutive grid steps repeat a block index, so
    dead tiles cost neither fetch bandwidth nor compute (the kernels'
    ``_tile_live`` predicate is already false there)."""
    def kv_index(b, i, j):
        if lo is not None:
            j = jnp.minimum(j, ((i + 1) * block_q - 1 - lo) // block_k)
        if hi is not None:
            j = jnp.maximum(
                j, jnp.maximum(i * block_q - hi + 1, 0) // block_k
            )
        return (b, jnp.clip(j, 0, nkv - 1), 0)

    return kv_index


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                  *, block_q: int, block_k: int, lo, hi, scale: float):
    """One (bh, q_block, kv_block) grid step.

    The grid's KV dimension is innermost (TPU grids run sequentially), so
    the (m, l, acc) online-softmax state lives in VMEM scratch across the
    KV sweep of each Q block; only one [block_k, d] K/V tile is resident at
    a time — sequence length is bounded by HBM, not VMEM.
    """
    qi = pl.program_id(1)
    kv = pl.program_id(2)
    nkv = pl.num_programs(2)

    @pl.when(kv == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _MASK_VALUE)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Tiles outside the band contribute nothing — skip.  Interior tiles
    # (fully inside the band) additionally skip the mask chain.
    def tile(mask: bool):
        # MXU operands stay in the input dtype (bf16 runs at bf16 MXU
        # throughput); accumulation is always f32 via preferred_element_type.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if mask:
            s = _tile_band_mask(s, qi, kv, block_q, block_k, lo, hi)
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        correction = jnp.exp(m - m_new)
        m_ref[:, 0] = m_new
        l_ref[:, 0] = l * correction + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * correction[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )

    _masked_tile_branches(_tile_live(qi, kv, block_q, block_k, lo, hi),
                          qi, kv, block_q, block_k, lo, hi, tile)

    # Last KV block of this Q row: normalize and emit.  A row with no
    # live tile at all (possible under a shifted band — e.g. a ring hop
    # whose window edge crosses mid-shard) emits out=0, lse=_MASK_VALUE:
    # exactly the "no contribution" partial for logsumexp merging, and a
    # 0/0 NaN otherwise.
    @pl.when(kv == _last_live_kv(qi, nkv, block_q, block_k, lo))
    def _():
        l = l_ref[:, 0]
        # A row is dead when m never left its init — catches both "no live
        # tile" (l == 0) and "live tile but every entry masked" (l counts
        # exp(_MASK − _MASK) = 1 per masked entry, so l alone misses it).
        dead = m_ref[:, 0] <= _MASK_VALUE * 0.5
        safe_l = jnp.where(dead, 1.0, l)
        o_ref[0] = jnp.where(
            dead[:, None], 0.0, acc_ref[:] / safe_l[:, None]
        ).astype(o_ref.dtype)
        # Per-row logsumexp (scaled-score domain) — the backward's residual:
        # p = exp(s·scale − lse) reconstructs the softmax tile exactly.
        lse_ref[0, 0, :] = jnp.where(
            dead, _MASK_VALUE, m_ref[:, 0] + jnp.log(safe_l)
        )


def _kv_row_map(heads: int, kv_heads: int):
    """Map a batch-major q-head grid row to its KV head's row (GQA)."""
    group = heads // kv_heads

    def kv_row(b):
        return (b // heads) * kv_heads + (b % heads) // group

    return kv_row


def _gqa_shape_check(q, k, v) -> int:
    """Validate [b, hq, sq, d] x [b, hkv, sk, d] inputs and return the KV
    head count (hkv must divide hq — grouped-query attention runs
    natively, no K/V repeat)."""
    batch, heads, _, d = q.shape
    kv_heads = k.shape[1]
    if k.shape != v.shape or k.shape[0] != batch or k.shape[3] != d:
        raise ValueError(f"k/v shape {k.shape} incompatible with q {q.shape}")
    if heads % kv_heads:
        raise ValueError(
            f"q heads {heads} must be a multiple of kv heads {kv_heads}"
        )
    return kv_heads


def _flash_forward(q, k, v, *, causal, block_q, block_k, interpret,
                   out_f32=False, window=None):
    lo, hi = _normalize_band(causal, window)
    batch, heads, seq_q, d = q.shape
    kv_heads = _gqa_shape_check(q, k, v)
    seq_k = k.shape[2]
    bq = min(block_q, seq_q)
    bk = min(block_k, seq_k)
    if seq_q % bq or seq_k % bk:
        raise ValueError(
            f"block sizes ({bq}, {bk}) must divide seq lengths ({seq_q}, {seq_k})"
        )
    if bq < seq_q and bq % 128 and not interpret:
        # The (bh, 1, seq_q) stats layout puts the Q block on the LANE dim
        # of the lse/delta blocks, so a partial block must be a lane-tile
        # multiple on TPU.  Catch it here with a clear message instead of
        # deep in Mosaic's block-shape check.  (Interpret mode has no tile
        # constraints — tests exercise band edges with small blocks.)
        raise ValueError(
            f"block_q ({bq}) must be a multiple of 128 (or the full seq_q)"
        )
    scale = d ** -0.5
    bh = batch * heads
    qr = q.reshape(bh, seq_q, d)
    kr = k.reshape(batch * kv_heads, seq_k, d)
    vr = v.reshape(batch * kv_heads, seq_k, d)

    kernel = functools.partial(
        _flash_kernel, block_q=bq, block_k=bk, lo=lo, hi=hi, scale=scale,
    )

    kv_row = _kv_row_map(heads, kv_heads)
    band_j = _band_kv_index(bq, bk, lo, hi, seq_k // bk)

    def kv_index(b, i, j):
        return (kv_row(b), band_j(b, i, j)[1], 0)

    # Whole-kernel cost for the XLA scheduler (matmul mult-add = 2 FLOPs;
    # exp per score entry; causal does half the score work).
    work = bh * _band_live_pairs(seq_q, seq_k, lo, hi)
    cost = pl.CostEstimate(
        flops=int(4 * work * d),
        transcendentals=int(work),
        bytes_accessed=int(qr.size + kr.size + vr.size + qr.size)
        * q.dtype.itemsize,
    )
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, d),
                                 jnp.float32 if out_f32 else q.dtype),
            # Stats with seq on the LANE dim.  A trailing singleton
            # ((bh, seq_q, 1)) looks harmless but the T(8,128) HBM layout
            # pads the lane dim 1 → 128 — measured 128× expansion
            # (4 MB → 512 MB at bh=512/seq=2048, the r4 b64 OOM dump) on
            # every lse residual held live until the backward.  The
            # middle singleton here is a SUBLANE dim (1 → 8, 8× pad) —
            # the cheapest layout Pallas' block rule admits: a 2D
            # (bh, seq_q) array would need (1, bq) blocks, whose sublane
            # size 1 is neither divisible by 8 nor equal to bh.
            jax.ShapeDtypeStruct((bh, 1, seq_q), jnp.float32),
        ],
        grid=(bh, seq_q // bq, seq_k // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), kv_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), kv_index, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),   # m (running row max)
            pltpu.VMEM((bq, 1), jnp.float32),   # l (running normalizer)
            pltpu.VMEM((bq, d), jnp.float32),   # acc (unnormalized out)
        ],
        compiler_params=_CompilerParams(
            # bh and q rows are independent; only the KV sweep accumulates.
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=cost,
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(batch, heads, seq_q, d), lse.reshape(batch, heads, seq_q)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    out_f32: bool = False,
    window: int | None = None,
):
    """Flash attention that also returns the per-row logsumexp
    ``[batch, heads, seq_q]`` (f32, scaled-score domain).

    The lse output is what makes partial attentions *mergeable*: two
    results over disjoint KV sets combine exactly via
    ``out = (out_a·e^{lse_a} + out_b·e^{lse_b}) / (e^{lse_a}+e^{lse_b})``
    (stabilized) — the decomposition ring attention uses to run this
    kernel per hop.  Differentiable in both outputs: the lse cotangent
    folds into the backward's delta term (``ds = p·(dp − Δ + dL)``).

    ``out_f32`` emits the attention output in f32 regardless of input
    dtype — partial-merging callers keep full precision across merges
    (the in-kernel accumulator is f32 either way, so this is free).
    """
    return _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, out_f32=out_f32, window=window,
    )


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    window: int | None = None,
) -> jax.Array:
    """Flash attention over ``[batch, heads, seq, head_dim]`` inputs.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU
    testing); on TPU leave it False.  ``window`` (requires ``causal``)
    restricts each token to the previous ``window`` positions (sliding-
    window attention, Mistral-style): tiles outside the band are dead on
    both sides — compute AND fetch cost scale with ``window``, not seq.
    """
    out, _ = flash_attention_with_lse(
        q, k, v, causal, block_q, block_k, interpret, False, window
    )
    return out


# Consume grouped-query K/V natively (fewer KV heads than q heads);
# wrappers that route to these kernels should propagate the tag.
flash_attention.supports_gqa = True
flash_attention_with_lse.supports_gqa = True


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    block_k: int = 128,
    window: int | None = None,
) -> jax.Array:
    """Memory-efficient attention in plain XLA: ``lax.scan`` over KV blocks
    carrying the (m, l, o) online-softmax triple, each block's work wrapped
    in ``jax.checkpoint``.  Numerically identical to
    :func:`attention_reference`; peak memory O(seq·block) forward AND
    backward (XLA differentiates the scan and remat recomputes per-block
    scores instead of saving them).  The kernel-free fallback to
    :func:`flash_attention` for platforms without Pallas (the flash
    backward itself is Pallas — see `_flash_backward`)."""
    if window is not None:
        if not causal:
            raise ValueError("sliding window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    scale = q.shape[-1] ** -0.5
    seq_k = k.shape[2]
    bk = min(block_k, seq_k)
    if seq_k % bk:
        raise ValueError(f"block size {bk} must divide seq_k {seq_k}")
    num_kv = seq_k // bk
    q_len = q.shape[2]

    # [num_kv, b, h, bk, d] blocks, scanned over axis 0.
    kb = jnp.moveaxis(k.reshape(k.shape[0], k.shape[1], num_kv, bk, -1), 2, 0)
    vb = jnp.moveaxis(v.reshape(v.shape[0], v.shape[1], num_kv, bk, -1), 2, 0)

    # One shared implementation of the numerically-sensitive softmax-rescale
    # math: ring_attention's _block_update/_causal_mask (so the flash
    # backward can never drift from the ring forward).
    @jax.checkpoint
    def body(carry, blk):
        m, l, o = carry
        kv_i, kt, vt = blk
        mask = _causal_mask(0, kv_i * bk, q_len, bk, window) \
            if causal else None
        return _block_update(q, kt, vt, m, l, o, scale=scale, mask=mask), None

    m0 = jnp.full(q.shape[:-1], _MASK_VALUE, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    o0 = jnp.zeros(q.shape, jnp.float32)
    (m, l, o), _ = lax.scan(
        body, (m0, l0, o0), (jnp.arange(num_kv), kb, vb)
    )
    return (o / l[..., None]).astype(q.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc_ref, *, block_q: int, block_k: int,
                         lo, hi, scale: float):
    """dq: grid (bh, q_block, kv_block), KV innermost — dq for one Q tile
    accumulates in VMEM scratch across its KV sweep, mirroring the forward's
    schedule (and its causal dead-block elision)."""
    qi = pl.program_id(1)
    kv = pl.program_id(2)
    nkv = pl.num_programs(2)

    @pl.when(kv == 0)
    def _():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    def tile(mask: bool):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if mask:
            s = _tile_band_mask(s, qi, kv, block_q, block_k, lo, hi)
        # Softmax tile from the saved row logsumexp — no m/l recurrence.
        # Dead rows carry the _MASK_VALUE lse sentinel: exp(s − lse) would
        # be exp(0)=1 on their masked entries, so zero them explicitly.
        row_lse = lse_ref[0, 0, :]
        # Dead-row mask as f32: a bool ([:, None]) minor-dim insert on the
        # lane-layout row vector is unsupported by Mosaic (i1 relayout);
        # the f32 multiply lowers cleanly and is numerically identical.
        live = (row_lse > _MASK_VALUE * 0.5).astype(jnp.float32)
        p = jnp.exp(s - row_lse[:, None]) * live[:, None]
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0, :][:, None]) * scale
        dq_acc_ref[:] += jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    _masked_tile_branches(_tile_live(qi, kv, block_q, block_k, lo, hi),
                          qi, kv, block_q, block_k, lo, hi, tile)

    @pl.when(kv == _last_live_kv(qi, nkv, block_q, block_k, lo))
    def _():
        dq_ref[0] = dq_acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *,
                          block_q: int, block_k: int, lo, hi,
                          scale: float, n_q_tiles: int):
    """dk/dv: grid (bh_kv, kv_block, group·q_block) with the (group member,
    Q tile) sweep innermost — dk/dv for one KV tile accumulate in VMEM
    scratch across every Q tile of every q head in its GQA group (group=1
    is plain MHA).  Causal: Q tiles fully above the diagonal are dead
    (elided); each head's final Q tile is always live, so emission at the
    last grid step is safe."""
    kv = pl.program_id(1)
    gi = pl.program_id(2)
    qi = gi % n_q_tiles

    @pl.when(gi == 0)
    def _():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    def tile(mask: bool):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if mask:
            s = _tile_band_mask(s, qi, kv, block_q, block_k, lo, hi)
        row_lse = lse_ref[0, 0, :]
        live = (row_lse > _MASK_VALUE * 0.5).astype(jnp.float32)  # see dq
        p = jnp.exp(s - row_lse[:, None]) * live[:, None]
        pt = p.astype(do.dtype).T
        dv_acc_ref[:] += jnp.dot(pt, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0, :][:, None]) * scale
        dk_acc_ref[:] += jnp.dot(
            ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32
        )

    _masked_tile_branches(_tile_live(qi, kv, block_q, block_k, lo, hi),
                          qi, kv, block_q, block_k, lo, hi, tile)

    @pl.when(gi == pl.num_programs(2) - 1)
    def _():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, do, lse, delta, *, causal, block_q, block_k,
                    interpret, window=None):
    lo, hi = _normalize_band(causal, window)
    batch, heads, seq_q, d = q.shape
    kv_heads = _gqa_shape_check(q, k, v)
    group = heads // kv_heads
    seq_k = k.shape[2]
    bq = min(block_q, seq_q)
    bk = min(block_k, seq_k)
    scale = d ** -0.5
    bh = batch * heads
    bh_kv = batch * kv_heads
    qr = q.reshape(bh, seq_q, d)
    kr = k.reshape(bh_kv, seq_k, d)
    vr = v.reshape(bh_kv, seq_k, d)
    dor = do.reshape(bh, seq_q, d).astype(q.dtype)
    lser = lse.reshape(bh, 1, seq_q)
    deltar = delta.reshape(bh, 1, seq_q)
    nq = seq_q // bq
    nkv = seq_k // bk

    kv_row = _kv_row_map(heads, kv_heads)

    work = bh * _band_live_pairs(seq_q, seq_k, lo, hi)
    in_bytes = int(
        (qr.size + kr.size + vr.size + dor.size) * q.dtype.itemsize
        + (lser.size + deltar.size) * 4
    )

    def q_row_index(b, i, j):
        return (b, i, 0)

    q_spec = pl.BlockSpec((1, bq, d), q_row_index, memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i),
                            memory_space=pltpu.VMEM)
    band_j = _band_kv_index(bq, bk, lo, hi, nkv)

    def kv_index(b, i, j):
        return (kv_row(b), band_j(b, i, j)[1], 0)
    kv_spec = pl.BlockSpec((1, bk, d), kv_index, memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=bq, block_k=bk,
                          lo=lo, hi=hi, scale=scale),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
        grid=(bh, nq, nkv),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(6 * work * d), transcendentals=int(work),
            bytes_accessed=in_bytes + int(qr.size * q.dtype.itemsize),
        ),
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)

    # dk/dv sweep (group x Q tiles) innermost per KV head; causal dead Q
    # tiles (fully above the diagonal) re-map to the KV row's first live
    # tile of the same group head so their DMA is elided, mirroring the
    # forward trick on the transposed schedule.
    def q_row(b, g):
        # KV grid row (batch-major over kv heads) + group member -> q row
        return (b // kv_heads) * heads + (b % kv_heads) * group + g

    def q_index(b, j, gi):
        qi = gi % nq
        if lo is not None:
            # band's lower edge: q < k + lo tiles are dead
            qi = jnp.maximum(qi, (j * bk + lo) // bq)
        if hi is not None:
            # band's upper edge: q tiles past k + hi are dead too
            qi = jnp.minimum(qi, ((j + 1) * bk - 1 + hi - 1) // bq)
        return (q_row(b, gi // nq), jnp.clip(qi, 0, nq - 1), 0)

    q_spec_t = pl.BlockSpec((1, bq, d), q_index, memory_space=pltpu.VMEM)

    def row_index_t(b, j, gi):
        r, qi, _ = q_index(b, j, gi)
        return (r, 0, qi)

    row_spec_t = pl.BlockSpec((1, 1, bq), row_index_t,
                              memory_space=pltpu.VMEM)
    kv_spec_t = pl.BlockSpec((1, bk, d), lambda b, j, gi: (b, j, 0),
                             memory_space=pltpu.VMEM)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=bq, block_k=bk,
                          lo=lo, hi=hi, scale=scale, n_q_tiles=nq),
        out_shape=[
            jax.ShapeDtypeStruct((bh_kv, seq_k, d), k.dtype),
            jax.ShapeDtypeStruct((bh_kv, seq_k, d), v.dtype),
        ],
        grid=(bh_kv, nkv, nq * group),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(8 * work * d), transcendentals=int(work),
            bytes_accessed=in_bytes + int(2 * kr.size * k.dtype.itemsize),
        ),
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)

    shape_q = (batch, heads, seq_q, d)
    shape_k = (batch, kv_heads, seq_k, d)
    return (dq.reshape(shape_q), dk.reshape(shape_k), dv.reshape(shape_k))


def _fwd(q, k, v, causal, block_q, block_k, interpret, out_f32, window):
    out, lse = _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, out_f32=out_f32, window=window,
    )
    return (out, lse), (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, interpret, out_f32, window, residuals, g):
    q, k, v, out, lse = residuals
    g_out, g_lse = g
    # delta_i = rowsum(dO_i · O_i): the dp→ds correction term, cheap
    # elementwise work XLA fuses on its own — no kernel needed.  The lse
    # cotangent enters through ds_ij = p_ij·(dp_ij − Δ_i + dL_i), i.e. it
    # just shifts the delta the kernels already consume.
    delta = jnp.sum(
        out.astype(jnp.float32) * g_out.astype(jnp.float32), axis=-1
    ) - g_lse.astype(jnp.float32)
    return _flash_backward(
        q, k, v, g_out, lse, delta, causal=causal, block_q=block_q,
        block_k=block_k, interpret=interpret, window=window,
    )


flash_attention_with_lse.defvjp(_fwd, _bwd)
