"""Pallas TPU kernels for hot ops, with XLA reference implementations used
as fallbacks and in correctness tests (interpret mode on CPU).

- :mod:`flash_attention` — blockwise online-softmax attention; pairs with
  ``tpudist.parallel.ring_attention`` (ring shards between chips, flash
  blocks within a chip).
- :mod:`fused_mlp` — the toy workload's 5-layer MLP in one kernel, weights
  zero-padded to lane-aligned tiles, activations pinned in VMEM.
"""

from tpudist.ops.flash_attention import (  # noqa: F401
    blockwise_attention,
    flash_attention,
    flash_attention_with_lse,
)
from tpudist.ops.fused_mlp import (  # noqa: F401
    fused_mlp,
    mlp_reference,
    pad_params,
)
