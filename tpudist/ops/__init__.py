"""Pallas TPU kernels for hot ops, with XLA reference implementations used
as fallbacks and in correctness tests (interpret mode on CPU).

- :mod:`flash_attention` — blockwise online-softmax attention; pairs with
  ``tpudist.parallel.ring_attention`` (ring shards between chips, flash
  blocks within a chip).
- :mod:`fused_mlp` — the toy workload's 5-layer MLP in one kernel, weights
  zero-padded to lane-aligned tiles, activations pinned in VMEM.
- :mod:`paged_attention` — serving-decode attention that walks the paged
  KV cache's block table INSIDE the kernel (vLLM-PagedAttention style):
  live blocks only, int8 dequant in-registers, the decode-window mask
  fused so s=1 decode and the speculative verify share one kernel.
"""

from tpudist.ops.flash_attention import (  # noqa: F401
    blockwise_attention,
    flash_attention,
    flash_attention_with_lse,
)
from tpudist.ops.paged_attention import (  # noqa: F401
    paged_attention,
    paged_attention_reference,
)
from tpudist.ops.fused_mlp import (  # noqa: F401
    fused_mlp,
    mlp_reference,
    pad_params,
)
