"""Pallas TPU kernels for hot ops (flash attention, fused MLP) with jnp
reference implementations used as CPU fallbacks and in correctness tests."""
