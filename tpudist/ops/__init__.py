"""Pallas TPU kernels for hot ops, with XLA reference implementations used
as fallbacks and in correctness tests (interpret mode on CPU).

- :mod:`flash_attention` — blockwise online-softmax attention; pairs with
  ``tpudist.parallel.ring_attention`` (ring shards between chips, flash
  blocks within a chip).
- :mod:`fused_mlp` — the toy workload's 5-layer MLP in one kernel, weights
  zero-padded to lane-aligned tiles, activations pinned in VMEM.
- :mod:`paged_attention` — serving-decode attention that walks the paged
  KV cache's block table INSIDE the kernel (vLLM-PagedAttention style):
  live blocks only, int8 dequant in-registers, the decode-window mask
  fused so s=1 decode and the speculative verify share one kernel.
- :mod:`paged_prefill` — the prefill sibling: walks the reused prefix
  out of the pool, runs the chunk's causal self-attention, and WRITES
  the touched KV blocks in-kernel (merge + requantize), closing the
  last dense ``[slots, max_len]`` materialization.
- :mod:`fused_sample` — the decode tail (constrain mask, greedy argmax,
  temperature, top-k/top-p, spec-decode residual prep) in one kernel;
  random draws stay in-graph so sampled streams are byte-identical.
- :mod:`fused_linear` — fused RoPE+QKV projection on per-slot vector
  offsets, and the LoRA gather-matmul addressed through
  scalar-prefetched adapter ids.
"""

from tpudist.ops.flash_attention import (  # noqa: F401
    blockwise_attention,
    flash_attention,
    flash_attention_with_lse,
)
from tpudist.ops.paged_attention import (  # noqa: F401
    paged_attention,
    paged_attention_reference,
)
from tpudist.ops.fused_mlp import (  # noqa: F401
    fused_mlp,
    mlp_reference,
    pad_params,
)
from tpudist.ops.paged_prefill import (  # noqa: F401
    paged_prefill_attention,
    paged_prefill_reference,
)
from tpudist.ops.fused_sample import (  # noqa: F401
    fused_residual_prep,
    fused_residual_reference,
    fused_sample_prep,
    fused_sample_reference,
)
from tpudist.ops.fused_linear import (  # noqa: F401
    fused_rope_qkv,
    fused_rope_qkv_reference,
    lora_delta,
    lora_delta_reference,
)
