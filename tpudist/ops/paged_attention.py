"""Pallas TPU paged-attention decode kernel: in-kernel block-table walk.

The serving engine's paged KV cache (:mod:`tpudist.models.paged`) keeps
K/V in a ``[L, num_blocks, n_kv, block_size, dh]`` pool addressed through
per-slot block tables.  The gather path materializes a dense
``[slots, max_len]`` view of that pool per dispatch before attention
runs — bytes moved per token scale with POOL GEOMETRY (``max_len``), not
with the tokens a lane actually holds, on exactly the path measured at
100.6% of its HBM roofline (ROOFLINE_r05).  This kernel is the
vLLM-PagedAttention idea in Pallas: the block table rides in as a
scalar-prefetch operand, each grid step's ``BlockSpec`` index map reads
it to DMA ONLY the slot's mapped live blocks straight out of the pool,
int8 blocks dequantize in-registers against their per-(layer, block,
kv-head) scales, and a blockwise online softmax accumulates across the
walk — bytes per token drop to live-KV, at any occupancy.

Decode-window fusion: the query operand is a WINDOW of ``s >= 1`` tokens
(s == 1 is plain decode; s == K+1 is the speculative-decoding verify
pass), and the window's own fresh K/V — written this dispatch, not yet
committed to the pool — rides in as a small side buffer processed as the
walk's final virtual block under the per-query causal mask
(``col <= fill + i``).  One kernel covers every decode shape the slot
engine dispatches, so the spec-verify path and the s=1 hot path cannot
drift apart.

Grid: ``(slots, kv_heads, M + 1)`` with the block walk innermost (TPU
grids run sequentially, so the (m, l, acc) online-softmax state lives in
VMEM scratch across one (slot, head)'s walk).  Steps past a slot's live
block count re-map to its last live block — Pallas elides the DMA when
consecutive grid steps repeat a block index, so a short lane costs
fetches proportional to ITS prefix, not the table width.  Grouped-query
attention runs natively: the q rows of one kv head's group are the
kernel's row tile, and each K/V block is fetched once per GROUP, never
per q head.

``interpret=True`` (any non-TPU backend) runs the kernel through the
Pallas interpreter — tier-1 exercises the exact same walk/mask/dequant
code on CPU.  Numerical contract vs the gather path: identical
dequantization (``int8.astype(compute) * scale.astype(compute)``),
identical masking constant (−1e30), f32 score/softmax accumulation —
the only difference is online-softmax accumulation order, so logits
agree to float tolerance and greedy token streams are byte-identical in
practice (tests pin both).

No reference counterpart (the reference ships no kernels — SURVEY.md
§0); PAPER.md names Pallas kernels as the TPU-native equivalent of the
reference's native stack.  This is the serving half's first custom
kernel and the template for the next ones (fused sampling, fused
RoPE+QKV).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MASK_VALUE = -1e30

# jax 0.4.x names the compiler-params struct TPUCompilerParams; newer
# releases renamed it CompilerParams.  The kernel must import under both
# (tier-1 runs whatever the container bakes in).
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def _kernel(table_ref, pos_ref, fill_ref, sk_ref, sv_ref,
            q_ref, pk_ref, pv_ref, wk_ref, wv_ref, o_ref,
            m_ref, l_ref, acc_ref, *, layer: int, block_size: int,
            s: int, quantized: bool, scale: float, window):
    """One (slot, kv_head, walk_step) grid step.

    Walk steps ``j < live(slot)`` consume pool block ``table[slot, j]``
    (dequantized in-registers when the pool is int8); the final step
    (``j == M``) consumes the window side buffer under the per-query
    causal mask and emits the normalized output.  Dead steps in between
    (``live <= j < M``) skip compute and, because their index map
    repeats the last live block, their DMA too.
    """
    b = pl.program_id(0)
    h = pl.program_id(1)
    j = pl.program_id(2)
    nsteps = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _MASK_VALUE)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    pos0 = pos_ref[b]
    fill = fill_ref[b]
    live = lax.div(pos0 + block_size - 1, block_size)

    def update(s_tile, v_tile):
        """Online-softmax rescale/accumulate (FlashAttention-2 form —
        the same recurrence as ops/flash_attention.py)."""
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s_tile, axis=-1))
        p = jnp.exp(s_tile - m_new[:, None])
        corr = jnp.exp(m - m_new)
        m_ref[:, 0] = m_new
        l_ref[:, 0] = l * corr + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jnp.dot(
            p.astype(v_tile.dtype), v_tile,
            preferred_element_type=jnp.float32)

    @pl.when(j < live)
    def _():
        q = q_ref[0, 0]                       # [R, dh] (R = group * s)
        k = pk_ref[0, 0, 0]                   # [bs, dh] storage dtype
        v = pv_ref[0, 0, 0]
        if quantized:
            # in-register dequant, bit-matching the gather path's
            # ``int8.astype(compute) * scale.astype(compute)``.  j < live
            # here, so table_ref[b, j] is a mapped id (clamp is belt
            # only, mirroring the index map's).
            bid = jnp.minimum(table_ref[b, j], sk_ref.shape[1] - 1)
            k = k.astype(q.dtype) * sk_ref[layer, bid, h].astype(q.dtype)
            v = v.astype(q.dtype) * sv_ref[layer, bid, h].astype(q.dtype)
        st = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        R, bs = st.shape
        kpos = j * block_size + lax.broadcasted_iota(jnp.int32, (R, bs), 1)
        # pool positions below the dispatch cursor are the live prefix;
        # at/after it is stale/another-tenant garbage (the paged-gather
        # contract) — masked with the same hard constant
        keep = kpos < pos0
        if window is not None:
            qpos = pos0 + fill + lax.broadcasted_iota(
                jnp.int32, (R, bs), 0) % s
            keep &= kpos > qpos - window
        update(jnp.where(keep, st, _MASK_VALUE), v)

    @pl.when(j == nsteps - 1)
    def _():
        q = q_ref[0, 0]
        k = wk_ref[0, 0]                      # [W, dh] compute dtype
        v = wv_ref[0, 0]
        st = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        R, W = st.shape
        col = lax.broadcasted_iota(jnp.int32, (R, W), 1)
        row_i = lax.broadcasted_iota(jnp.int32, (R, W), 0) % s
        # the fused decode-window mask: query i of the window sees the
        # buffer's pre-existing fill plus window tokens 0..i (itself
        # included) — s=1 plain decode and the s=K+1 spec-verify window
        # are the same mask at different s
        keep = col <= fill + row_i
        if window is not None:
            qpos = pos0 + fill + row_i
            keep &= (pos0 + col) > qpos - window
        update(jnp.where(keep, st, _MASK_VALUE), v)
        # every row keeps at least its own token (col == fill + i), so
        # l > 0 always — no dead-row guard needed
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, 0][:, None]).astype(o_ref.dtype)


def paged_attention(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    scale_k: jax.Array,
    scale_v: jax.Array,
    table: jax.Array,
    pos0: jax.Array,
    fill: jax.Array,
    wk: jax.Array,
    wv: jax.Array,
    *,
    layer: int,
    window: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Paged decode attention over a block pool, one model layer.

    - ``q [S, n_heads, s, dh]`` — the decode window's queries (already
      rope-rotated at their absolute positions); ``s == 1`` is plain
      decode, ``s > 1`` the speculative verify window;
    - ``pool_k``/``pool_v [L, num_blocks, n_kv, block_size, dh]`` — the
      WHOLE pool (int8 when quantized); ``layer`` is the static layer
      index, consumed by the index map so no per-layer slice (and no
      pool copy) is ever materialized;
    - ``scale_k``/``scale_v [L, num_blocks, n_kv]`` f32 dequant scales
      (scalar-prefetched; ignored unless the pool is int8);
    - ``table [S, M]`` int32 — per-slot physical block ids (sentinel
      ``num_blocks`` = unmapped; only entries below a slot's live count
      are ever dereferenced, and the walk clamps defensively);
    - ``pos0 [S]`` int32 — the dispatch-start cursor: pool positions
      ``< pos0`` are the live prefix every window query sees;
    - ``fill [S]`` int32 — window-buffer tokens already written BEFORE
      this call's ``s`` queries (the decode scan's step index; 0 for a
      verify window);
    - ``wk``/``wv [S, n_kv, W, dh]`` — the uncommitted window buffer in
      the compute dtype, current tokens included at
      ``[fill, fill + s)``; ``fill + s <= W`` is the caller's contract.

    Returns ``[S, n_heads, s, dh]`` in ``q.dtype``.  ``window`` is the
    sliding-window (local-attention) bound, matching the gather path's
    decode mask.  ``interpret`` routes through the Pallas interpreter
    (the tier-1 CPU path).
    """
    S, nh, s, dh = q.shape
    L, nb, n_kv, bs, _ = pool_k.shape
    M = table.shape[1]
    W = wk.shape[2]
    if nh % n_kv:
        raise ValueError(f"n_heads {nh} must be a multiple of n_kv {n_kv}")
    if not 0 <= layer < L:
        raise ValueError(f"layer {layer} out of range [0, {L})")
    group = nh // n_kv
    R = group * s
    quantized = pool_k.dtype == jnp.int8
    # q heads are kv-major contiguous ([nk, group]) — the same grouping
    # convention as the gather path's grouped einsum
    q4 = q.reshape(S, n_kv, R, dh)

    def phys(b, j, tbl, pos, *_):
        live1 = jnp.maximum(lax.div(pos[b] + bs - 1, bs), 1)
        jj = jnp.minimum(j, live1 - 1)
        return jnp.minimum(tbl[b, jj], nb - 1)

    def q_index(b, h, j, *_):
        return (b, h, 0, 0)

    def pool_index(b, h, j, *refs):
        return (layer, phys(b, j, *refs), h, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(S, n_kv, M + 1),
        in_specs=[
            pl.BlockSpec((1, 1, R, dh), q_index),
            pl.BlockSpec((1, 1, 1, bs, dh), pool_index),
            pl.BlockSpec((1, 1, 1, bs, dh), pool_index),
            pl.BlockSpec((1, 1, W, dh), q_index),
            pl.BlockSpec((1, 1, W, dh), q_index),
        ],
        out_specs=pl.BlockSpec((1, 1, R, dh), q_index),
        scratch_shapes=[
            pltpu.VMEM((R, 1), jnp.float32),   # m (running row max)
            pltpu.VMEM((R, 1), jnp.float32),   # l (running normalizer)
            pltpu.VMEM((R, dh), jnp.float32),  # acc (unnormalized out)
        ],
    )
    kernel = functools.partial(
        _kernel, layer=layer, block_size=bs, s=s, quantized=quantized,
        scale=dh ** -0.5, window=window)
    # Upper-bound cost for the XLA scheduler: a full walk touches every
    # table entry plus the window (live-KV elision only shrinks it).
    work = S * n_kv * R * (M * bs + W)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, n_kv, R, dh), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * work * dh),
            transcendentals=int(work),
            bytes_accessed=int(
                (q4.size + 2 * S * n_kv * M * bs * dh + wk.size + wv.size
                 + q4.size) * q.dtype.itemsize),
        ),
        interpret=interpret,
    )(table, pos0, fill, scale_k, scale_v, q4, pool_k, pool_v, wk, wv)
    return out.reshape(S, nh, s, dh)


paged_attention.supports_gqa = True


def paged_attention_reference(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    scale_k: jax.Array,
    scale_v: jax.Array,
    table: jax.Array,
    pos0: jax.Array,
    fill: jax.Array,
    wk: jax.Array,
    wv: jax.Array,
    *,
    layer: int,
    window: int | None = None,
) -> jax.Array:
    """Gather-to-dense XLA reference with the identical masking contract
    — what the kernel must match (the equivalence-oracle in tests; also
    the documentation of the math in plain jnp).

    Gathers the slot's mapped blocks into a dense ``[max_len]`` view
    (sentinels clamp into masked territory, exactly like
    ``_Paged._dense_kv``), appends the window buffer, and runs one
    dense masked softmax per query.
    """
    S, nh, s, dh = q.shape
    L, nb, n_kv, bs, _ = pool_k.shape
    M = table.shape[1]
    W = wk.shape[2]
    group = nh // n_kv
    rows = jnp.minimum(table, nb - 1)                  # [S, M]
    compute = q.dtype

    def view(pool, scale):
        g = pool[layer][rows].astype(compute)          # [S, M, nk, bs, dh]
        if pool.dtype == jnp.int8:
            sc = scale[layer][rows]                    # [S, M, nk]
            g = g * sc[..., None, None].astype(compute)
        g = jnp.moveaxis(g, 2, 1)                      # [S, nk, M, bs, dh]
        return g.reshape(S, n_kv, M * bs, dh)

    ks = jnp.concatenate([view(pool_k, scale_k), wk], axis=2)
    vs = jnp.concatenate([view(pool_v, scale_v), wv], axis=2)
    scale = dh ** -0.5
    qg = q.reshape(S, n_kv, group, s, dh)
    scores = jnp.einsum("bngqd,bnkd->bngqk", qg, ks,
                        preferred_element_type=jnp.float32) * scale
    kpos = jnp.arange(M * bs + W)
    in_pool = kpos < M * bs
    qpos = pos0[:, None] + fill[:, None] + jnp.arange(s)[None]   # [S, s]
    live = jnp.where(
        in_pool[None, None],
        kpos[None, None] < pos0[:, None, None],
        (kpos[None, None] - M * bs)
        <= fill[:, None, None] + jnp.arange(s)[None, :, None])
    if window is not None:
        abs_k = jnp.where(in_pool[None, None], kpos[None, None],
                          pos0[:, None, None] + kpos[None, None] - M * bs)
        live &= abs_k > qpos[:, :, None] - window
    scores = jnp.where(live[:, None, None], scores, _MASK_VALUE)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bngqk,bnkd->bngqd", w.astype(compute), vs,
                     preferred_element_type=jnp.float32)
    return out.reshape(S, nh, s, dh).astype(q.dtype)
