"""Hyperparameter sweeps — W&B-sweep-shaped, server-optional.

The reference drives sweeps through the W&B server: ``sweeper.yml`` defines a
grid (``sweeper.yml:1-41``), ``count_sweeps.bash`` multiplies the value
counts to size the SLURM array (``count_sweeps.bash:4-16``), and each array
task runs ``wandb agent --count 1 …`` (``sweep_cmd.txt:1``) which pulls one
configuration and execs the command template.

This module reproduces the whole pattern locally: the same YAML schema
(``program`` / ``method`` / ``metric`` / ``parameters: {p: {values: […]}}`` /
``command`` template with ``${program}``/``${args}``/``${env}``
interpolation), deterministic grid expansion, a ``count`` command for array
sizing, and an ``agent --index i`` that runs the i-th configuration — so a
SLURM array task or a loop over TPU pod workers replaces the W&B server
round-trip.  When wandb *is* installed and a sweep id is given, ``agent``
delegates to the real ``wandb agent --count 1`` for full parity.

Methods: ``grid`` and ``random`` enumerate independently per index (array
tasks need no shared state).  ``method: bayes`` runs a LOCAL
sequential-model-based search (TPE-style — see :meth:`SweepSpec.propose`):
completed runs append ``{config, metric}`` to a shared results file
(``<spec>.results.jsonl`` by default; appends are O_APPEND +
``flock``-serialized, so concurrent array tasks may share it) and later
proposals concentrate where the best quartile lives.  The trained program
reports its objective by calling :func:`report_metric` (or writing a
float to ``$TPUDIST_SWEEP_METRIC_FILE``).  Full GP-based bayes remains
available by delegating to the W&B server exactly like the reference
(``--wandb-sweep-id``).

Parameters take either form of the W&B schema: value grids
(``values: [...]`` / ``value: x``) or continuous distributions
(``min``/``max`` with ``distribution: uniform | log_uniform |
int_uniform | q_uniform`` — ``log_uniform`` here is over the VALUES,
i.e. W&B's ``log_uniform_values``; ``q_uniform`` takes a ``q`` step).
Continuous parameters work under ``random`` and ``bayes``; ``grid``
(and ``count``) rejects them — a distribution has no grid to enumerate.

Honest labeling of the approximation (README "Sweeps"): the local bayes
is a Parzen/TPE flavor — categorical dimensions use smoothed good/bad
frequencies, continuous dimensions a best-quartile kernel-density ratio
over prior + locally-perturbed candidates — not a GP with expected
improvement, and there is no cross-parameter covariance model.  For the
real thing, delegate to the W&B server (``-I``), same as the reference.

CLI::

    python -m tpudist.launch.sweep count  sweeper.yml
    python -m tpudist.launch.sweep show   sweeper.yml --index 3
    python -m tpudist.launch.sweep agent  sweeper.yml --index $SLURM_ARRAY_TASK_ID
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import random
import string
import subprocess
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional

import yaml


def _locked_append(path: Path, line: str) -> None:
    """Append one record to the shared results file safely under
    concurrent agents: O_APPEND (each write lands at the current end) +
    an advisory ``flock`` held across the write (serializes appends so a
    line can never interleave even if a platform splits large writes).
    Lock-less platforms (no fcntl) degrade to bare O_APPEND, which POSIX
    already keeps line-atomic at these sizes."""
    fd = os.open(str(path), os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        try:
            import fcntl

            fcntl.flock(fd, fcntl.LOCK_EX)
        except (ImportError, OSError):
            # non-POSIX (no fcntl) or a filesystem without lock support
            # (ENOLCK on NFS/Lustre): degrade to bare O_APPEND as
            # advertised — losing the lock must never lose the record.
            pass
        os.write(fd, line.encode())
    finally:
        os.close(fd)


def report_metric(value: float, path: Optional[str] = None) -> None:
    """Report the run's objective to the sweep agent (bayes method).

    Programs under a bayes sweep call this once with their final metric
    (or write the float themselves to ``$TPUDIST_SWEEP_METRIC_FILE``);
    the agent appends ``{config, metric}`` to the shared results file
    after the run exits.  A no-op outside a sweep."""
    path = path or os.environ.get("TPUDIST_SWEEP_METRIC_FILE")
    if not path:
        return
    with open(path, "w") as f:
        f.write(repr(float(value)))


@dataclasses.dataclass(frozen=True)
class Continuous:
    """A ``min``/``max`` distribution parameter (W&B schema).

    ``log_uniform`` is over the VALUES (W&B's ``log_uniform_values``
    spelling is accepted too): draws are ``exp(U(ln lo, ln hi))``.
    ``int_uniform`` draws integers inclusive of both ends; ``q_uniform``
    rounds uniform draws to multiples of ``q``.
    """

    lo: float
    hi: float
    distribution: str = "uniform"
    q: Optional[float] = None

    def __post_init__(self):
        if self.distribution not in (
            "uniform", "log_uniform", "log_uniform_values", "int_uniform",
            "q_uniform",
        ):
            raise ValueError(
                f"unsupported distribution {self.distribution!r}")
        if not self.hi > self.lo:
            raise ValueError(f"min {self.lo} must be < max {self.hi}")
        if self._log and self.lo <= 0:
            raise ValueError("log_uniform needs min > 0")
        if self.distribution == "q_uniform" and not self.q:
            raise ValueError("q_uniform needs q")

    @property
    def _log(self) -> bool:
        return self.distribution in ("log_uniform", "log_uniform_values")

    # TPE works in the transformed space where the prior is uniform.
    def to_t(self, x: float) -> float:
        import math

        return math.log(x) if self._log else float(x)

    def from_t(self, t: float) -> Any:
        import math

        x = math.exp(t) if self._log else t
        x = min(max(x, self.lo), self.hi)
        if self.distribution == "int_uniform":
            return int(round(x))
        if self.distribution == "q_uniform":
            # Nearest IN-RANGE multiple of q: plain rounding of a clamped
            # draw can step outside [lo, hi] when the bounds are not
            # themselves multiples of q.
            lo_q = math.ceil(self.lo / self.q - 1e-9) * self.q
            hi_q = math.floor(self.hi / self.q + 1e-9) * self.q
            v = round(x / self.q) * self.q
            return round(min(max(v, lo_q), hi_q), 10)
        return x

    def sample(self, rng: random.Random) -> Any:
        if self.distribution == "int_uniform":
            # Uniform over the integers themselves: uniform-then-round
            # would give both endpoints half the interior mass.
            return rng.randint(int(self.lo), int(self.hi))
        t_lo, t_hi = self.to_t(self.lo), self.to_t(self.hi)
        return self.from_t(rng.uniform(t_lo, t_hi))


@dataclasses.dataclass(frozen=True)
class SweepSpec:
    program: str
    method: str  # grid | random | bayes
    # name -> ordered candidate values (list) or a Continuous distribution
    parameters: Dict[str, Any]
    command: List[str]
    metric: Optional[Dict[str, Any]] = None

    @classmethod
    def from_yaml(cls, path: str | Path) -> "SweepSpec":
        with open(path) as f:
            raw = yaml.safe_load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "SweepSpec":
        params: Dict[str, Any] = {}
        for name, spec in (raw.get("parameters") or {}).items():
            if isinstance(spec, dict):
                if "values" in spec:
                    params[name] = list(spec["values"])
                elif "value" in spec:
                    params[name] = [spec["value"]]
                elif "min" in spec and "max" in spec:
                    dist = spec.get("distribution")
                    if dist is None:
                        # W&B default: ints -> int_uniform, else uniform
                        both_int = (isinstance(spec["min"], int)
                                    and isinstance(spec["max"], int))
                        dist = "int_uniform" if both_int else "uniform"
                    params[name] = Continuous(
                        lo=float(spec["min"]), hi=float(spec["max"]),
                        distribution=dist, q=spec.get("q"))
                else:
                    raise ValueError(
                        f"parameter {name!r}: need values/value or min+max "
                        f"(got keys {sorted(spec)})")
            else:
                params[name] = [spec]
        command = raw.get("command") or ["python", "${program}", "${args}"]
        return cls(
            program=raw.get("program", ""),
            method=raw.get("method", "grid"),
            parameters=params,
            command=[str(c) for c in command],
            metric=raw.get("metric"),
        )

    def _continuous(self) -> List[str]:
        return [k for k, v in self.parameters.items()
                if isinstance(v, Continuous)]

    def _draw(self, rng: random.Random) -> Dict[str, Any]:
        return {k: (v.sample(rng) if isinstance(v, Continuous)
                    else rng.choice(v))
                for k, v in self.parameters.items()}

    def count(self) -> int:
        """Grid size — ``count_sweeps.bash:4-16`` parity (product of value
        counts).  Continuous parameters have no grid: rejected here so an
        array sized from ``count`` can never silently under-cover them."""
        cont = self._continuous()
        if cont:
            raise ValueError(
                f"count() undefined over continuous parameters {cont} — "
                f"size the array explicitly for random/bayes sweeps")
        n = 1
        for values in self.parameters.values():
            n *= len(values)
        return n

    def config_at(self, index: int, seed: int = 0) -> Dict[str, Any]:
        """The index-th configuration.  Grid order is deterministic (product
        order over parameters in YAML order, last varying fastest); ``random``
        draws with a seeded RNG so array tasks are reproducible."""
        if self.method == "random":
            return self._draw(random.Random((seed << 20) ^ index))
        cont = self._continuous()
        if cont:
            raise ValueError(
                f"method {self.method!r} cannot enumerate continuous "
                f"parameters {cont}: use method random or bayes")
        n = self.count()
        if not 0 <= index < n:
            raise IndexError(f"sweep index {index} out of range [0,{n})")
        # Mixed-radix decode (last parameter varies fastest — itertools.product
        # order) without materializing the grid.
        config: Dict[str, Any] = {}
        rem = index
        for name in reversed(list(self.parameters)):
            values = self.parameters[name]
            rem, i = divmod(rem, len(values))
            config[name] = values[i]
        return {k: config[k] for k in self.parameters}

    def propose(self, index: int, results: List[Dict[str, Any]],
                seed: int = 0) -> Dict[str, Any]:
        """Bayes proposal from observed ``[{config, metric}, ...]``.

        TPE-flavored, per-parameter (no cross-parameter covariance):
        runs in the best quartile (by ``metric.goal``, default minimize)
        are "good".

        - **value grids**: each value gets the smoothed score
          ``(good(v) + 1) / (all(v) + n_values)`` (≈ P(good | v) with a
          uniform prior) and the next value is drawn proportionally —
          values that keep landing in the best quartile are sampled more,
          while the +1 smoothing keeps every value alive (exploration).
        - **continuous**: candidates are drawn half from the prior, half
          as Gaussian perturbations around good observations (in log
          space for ``log_uniform``), and the candidate maximizing the
          Parzen density ratio ``l_good(x)/l_all(x)`` wins — the TPE
          acquisition with kernel-density estimators.

        Fewer than 4 observations (or all-failed runs) fall back to the
        seeded random draw, like ``method: random``.
        """
        rng = random.Random((seed << 20) ^ (0xB1A5 + index))
        scored = [(r["config"], float(r["metric"])) for r in results
                  if r.get("metric") is not None]
        if len(scored) < 4:
            return self._draw(rng)
        goal = (self.metric or {}).get("goal", "minimize")
        sign = -1.0 if goal == "maximize" else 1.0
        scored.sort(key=lambda cv: sign * cv[1])
        n_good = max(1, len(scored) // 4)
        good = [c for c, _ in scored[:n_good]]
        allc = [c for c, _ in scored]
        config: Dict[str, Any] = {}
        for name, values in self.parameters.items():
            if isinstance(values, Continuous):
                config[name] = self._propose_continuous(
                    values, name, good, allc, rng)
                continue
            weights = []
            for v in values:
                g = sum(1 for c in good if c.get(name) == v)
                a = sum(1 for c in allc if c.get(name) == v)
                weights.append((g + 1.0) / (a + len(values)))
            config[name] = rng.choices(values, weights=weights, k=1)[0]
        return config

    @staticmethod
    def _propose_continuous(p: Continuous, name: str,
                            good: List[dict], allc: List[dict],
                            rng: random.Random) -> Any:
        import math

        t_lo, t_hi = p.to_t(p.lo), p.to_t(p.hi)
        span = t_hi - t_lo
        bw = span / 8.0  # Parzen bandwidth in transformed space
        good_t = [p.to_t(c[name]) for c in good if name in c]
        all_t = [p.to_t(c[name]) for c in allc if name in c]
        if not good_t:
            return p.sample(rng)

        # Candidates: prior draws (exploration) + local perturbations of
        # good points (exploitation).
        cands = [rng.uniform(t_lo, t_hi) for _ in range(12)]
        cands += [min(max(rng.gauss(rng.choice(good_t), bw), t_lo), t_hi)
                  for _ in range(12)]

        def kde(ts: List[float], x: float) -> float:
            return sum(math.exp(-0.5 * ((x - t) / bw) ** 2) for t in ts) \
                / (len(ts) * bw) + 1e-12

        best = max(cands, key=lambda x: kde(good_t, x) / kde(all_t, x))
        return p.from_t(best)

    def command_for(self, config: Dict[str, Any],
                    env: Optional[Dict[str, str]] = None) -> List[str]:
        """Render the command template (``sweeper.yml:21-41`` interpolation:
        ``${program}``, ``${args}``, ``${env}``, plus ``${VAR}`` from env)."""
        env = {**os.environ, **(env or {})}
        args = [f"--{k}={v}" for k, v in config.items()]
        out: List[str] = []
        for tok in self.command:
            if tok == "${args}":
                out.extend(args)
            elif tok == "${program}":
                out.append(self.program)
            elif tok == "${env}":
                continue  # "/usr/bin/env" marker in wandb templates — drop
            elif tok in ("${interpreter}", "python"):
                out.append(sys.executable)
            else:
                out.append(string.Template(tok).safe_substitute(env))
        return out

    def run_index(self, index: int, extra_env: Optional[Dict[str, str]] = None) -> int:
        config = self.config_at(index)
        cmd = self.command_for(config)
        env = {**os.environ, **(extra_env or {}),
               "TPUDIST_SWEEP_INDEX": str(index),
               "TPUDIST_SWEEP_CONFIG": repr(config)}
        # count() is undefined over continuous parameters (method random
        # draws from a distribution — there is no grid size to show).
        total = "?" if self._continuous() else str(self.count())
        print(f"[sweep] index {index}/{total}: {config}")
        return subprocess.call(cmd, env=env)

    def run_bayes(self, index: int, results_path: str | Path,
                  extra_env: Optional[Dict[str, str]] = None,
                  seed: int = 0) -> int:
        """One bayes step: propose from the shared results file, run the
        command, harvest the reported metric, append the observation
        (appends are line-atomic, so array tasks may share the file)."""
        import json
        import tempfile

        results_path = Path(results_path)
        results: List[Dict[str, Any]] = []
        if results_path.exists():
            for line in results_path.read_text().splitlines():
                try:
                    results.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        config = self.propose(index, results, seed=seed)
        cmd = self.command_for(config)
        # Private per-run directory + fixed name: the path stays reserved
        # (no unlink-then-reuse race in a shared tmpdir); "reported" ==
        # the file has content.
        metric_file = os.path.join(
            tempfile.mkdtemp(prefix="sweep_metric_"), "metric")
        env = {**os.environ, **(extra_env or {}),
               "TPUDIST_SWEEP_INDEX": str(index),
               "TPUDIST_SWEEP_CONFIG": repr(config),
               "TPUDIST_SWEEP_METRIC_FILE": metric_file}
        print(f"[sweep] bayes index {index} "
              f"({len(results)} observed): {config}")
        rc = subprocess.call(cmd, env=env)
        metric: Optional[float] = None
        try:
            with open(metric_file) as f:
                metric = float(f.read().strip())
        except (OSError, ValueError):
            pass  # no report / crashed run -> recorded as metric None
        finally:
            import shutil

            shutil.rmtree(os.path.dirname(metric_file), ignore_errors=True)
        results_path.parent.mkdir(parents=True, exist_ok=True)
        _locked_append(
            results_path,
            json.dumps({"index": index, "config": config,
                        "metric": metric, "rc": rc}) + "\n")
        return rc


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="tpudist-sweep")
    p.add_argument("action", choices=["count", "show", "agent"])
    p.add_argument("spec", help="sweep YAML (sweeper.yml schema)")
    p.add_argument("--index", type=int, default=None,
                   help="configuration index (e.g. $SLURM_ARRAY_TASK_ID)")
    p.add_argument("--wandb-sweep-id", default=None,
                   help="delegate to `wandb agent --count 1 <id>` when wandb "
                        "is installed (full reference parity).  Falls back "
                        "to $WANDB_SWEEP_ID — how `job_submitter.sh -j "
                        "sweep -I <id>` ships the server sweep to every "
                        "array task — unless an explicit --index pins this "
                        "run to the local grid")
    p.add_argument("--results", default=None,
                   help="bayes observations file (default <spec>."
                        "results.jsonl, or $TPUDIST_SWEEP_RESULTS)")
    args = p.parse_args(argv)
    spec = SweepSpec.from_yaml(args.spec)
    if args.action == "count":
        print(spec.count())
        return 0
    sweep_id = args.wandb_sweep_id
    if sweep_id is None and args.index is None:
        # env fallback only when nothing pins this run to the local grid —
        # an explicit --index always means "run MY configuration"
        sweep_id = os.environ.get("WANDB_SWEEP_ID") or None
    index = args.index
    if index is None:
        index = int(os.environ.get("SLURM_ARRAY_TASK_ID", 0))
    if args.action == "show":
        print(spec.config_at(index))
        print(" ".join(spec.command_for(spec.config_at(index))))
        return 0
    if sweep_id:
        # sweep_cmd.txt:1 — `wandb agent --count 1 USER/PROJECT/SWEEPID`.
        return subprocess.call([sys.executable, "-m", "wandb", "agent",
                                "--count", "1", sweep_id])
    if spec.method == "bayes":
        results = (args.results
                   or os.environ.get("TPUDIST_SWEEP_RESULTS")
                   or f"{args.spec}.results.jsonl")
        return spec.run_bayes(index, results)
    return spec.run_index(index)


if __name__ == "__main__":
    sys.exit(main())
