"""Hyperparameter sweeps — W&B-sweep-shaped, server-optional.

The reference drives sweeps through the W&B server: ``sweeper.yml`` defines a
grid (``sweeper.yml:1-41``), ``count_sweeps.bash`` multiplies the value
counts to size the SLURM array (``count_sweeps.bash:4-16``), and each array
task runs ``wandb agent --count 1 …`` (``sweep_cmd.txt:1``) which pulls one
configuration and execs the command template.

This module reproduces the whole pattern locally: the same YAML schema
(``program`` / ``method`` / ``metric`` / ``parameters: {p: {values: […]}}`` /
``command`` template with ``${program}``/``${args}``/``${env}``
interpolation), deterministic grid expansion, a ``count`` command for array
sizing, and an ``agent --index i`` that runs the i-th configuration — so a
SLURM array task or a loop over TPU pod workers replaces the W&B server
round-trip.  When wandb *is* installed and a sweep id is given, ``agent``
delegates to the real ``wandb agent --count 1`` for full parity.

CLI::

    python -m tpudist.launch.sweep count  sweeper.yml
    python -m tpudist.launch.sweep show   sweeper.yml --index 3
    python -m tpudist.launch.sweep agent  sweeper.yml --index $SLURM_ARRAY_TASK_ID
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import random
import string
import subprocess
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional

import yaml


@dataclasses.dataclass(frozen=True)
class SweepSpec:
    program: str
    method: str  # grid | random
    parameters: Dict[str, List[Any]]  # name -> candidate values (ordered)
    command: List[str]
    metric: Optional[Dict[str, Any]] = None

    @classmethod
    def from_yaml(cls, path: str | Path) -> "SweepSpec":
        with open(path) as f:
            raw = yaml.safe_load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "SweepSpec":
        params: Dict[str, List[Any]] = {}
        for name, spec in (raw.get("parameters") or {}).items():
            if isinstance(spec, dict):
                if "values" in spec:
                    params[name] = list(spec["values"])
                elif "value" in spec:
                    params[name] = [spec["value"]]
                else:
                    raise ValueError(
                        f"parameter {name!r}: only values/value grids are "
                        f"supported (got keys {sorted(spec)})")
            else:
                params[name] = [spec]
        command = raw.get("command") or ["python", "${program}", "${args}"]
        return cls(
            program=raw.get("program", ""),
            method=raw.get("method", "grid"),
            parameters=params,
            command=[str(c) for c in command],
            metric=raw.get("metric"),
        )

    def count(self) -> int:
        """Grid size — ``count_sweeps.bash:4-16`` parity (product of value
        counts)."""
        n = 1
        for values in self.parameters.values():
            n *= len(values)
        return n

    def config_at(self, index: int, seed: int = 0) -> Dict[str, Any]:
        """The index-th configuration.  Grid order is deterministic (product
        order over parameters in YAML order, last varying fastest); ``random``
        draws with a seeded RNG so array tasks are reproducible."""
        if self.method == "random":
            rng = random.Random((seed << 20) ^ index)
            return {k: rng.choice(v) for k, v in self.parameters.items()}
        n = self.count()
        if not 0 <= index < n:
            raise IndexError(f"sweep index {index} out of range [0,{n})")
        # Mixed-radix decode (last parameter varies fastest — itertools.product
        # order) without materializing the grid.
        config: Dict[str, Any] = {}
        rem = index
        for name in reversed(list(self.parameters)):
            values = self.parameters[name]
            rem, i = divmod(rem, len(values))
            config[name] = values[i]
        return {k: config[k] for k in self.parameters}

    def command_for(self, config: Dict[str, Any],
                    env: Optional[Dict[str, str]] = None) -> List[str]:
        """Render the command template (``sweeper.yml:21-41`` interpolation:
        ``${program}``, ``${args}``, ``${env}``, plus ``${VAR}`` from env)."""
        env = {**os.environ, **(env or {})}
        args = [f"--{k}={v}" for k, v in config.items()]
        out: List[str] = []
        for tok in self.command:
            if tok == "${args}":
                out.extend(args)
            elif tok == "${program}":
                out.append(self.program)
            elif tok == "${env}":
                continue  # "/usr/bin/env" marker in wandb templates — drop
            elif tok in ("${interpreter}", "python"):
                out.append(sys.executable)
            else:
                out.append(string.Template(tok).safe_substitute(env))
        return out

    def run_index(self, index: int, extra_env: Optional[Dict[str, str]] = None) -> int:
        config = self.config_at(index)
        cmd = self.command_for(config)
        env = {**os.environ, **(extra_env or {}),
               "TPUDIST_SWEEP_INDEX": str(index),
               "TPUDIST_SWEEP_CONFIG": repr(config)}
        print(f"[sweep] index {index}/{self.count()}: {config}")
        return subprocess.call(cmd, env=env)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="tpudist-sweep")
    p.add_argument("action", choices=["count", "show", "agent"])
    p.add_argument("spec", help="sweep YAML (sweeper.yml schema)")
    p.add_argument("--index", type=int, default=None,
                   help="configuration index (e.g. $SLURM_ARRAY_TASK_ID)")
    p.add_argument("--wandb-sweep-id", default=None,
                   help="delegate to `wandb agent --count 1 <id>` when wandb "
                        "is installed (full reference parity).  Falls back "
                        "to $WANDB_SWEEP_ID — how `job_submitter.sh -j "
                        "sweep -I <id>` ships the server sweep to every "
                        "array task — unless an explicit --index pins this "
                        "run to the local grid")
    args = p.parse_args(argv)
    spec = SweepSpec.from_yaml(args.spec)
    if args.action == "count":
        print(spec.count())
        return 0
    sweep_id = args.wandb_sweep_id
    if sweep_id is None and args.index is None:
        # env fallback only when nothing pins this run to the local grid —
        # an explicit --index always means "run MY configuration"
        sweep_id = os.environ.get("WANDB_SWEEP_ID") or None
    index = args.index
    if index is None:
        index = int(os.environ.get("SLURM_ARRAY_TASK_ID", 0))
    if args.action == "show":
        print(spec.config_at(index))
        print(" ".join(spec.command_for(spec.config_at(index))))
        return 0
    if sweep_id:
        # sweep_cmd.txt:1 — `wandb agent --count 1 USER/PROJECT/SWEEPID`.
        return subprocess.call([sys.executable, "-m", "wandb", "agent",
                                "--count", "1", sweep_id])
    return spec.run_index(index)


if __name__ == "__main__":
    sys.exit(main())
