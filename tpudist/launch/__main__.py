import sys

from tpudist.launch.run import main

sys.exit(main())
