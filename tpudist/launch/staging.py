"""Data-staging tarball contract.

Create side: ``job_submitter.sh:166-174`` tars each ``--data`` path into the
experiment's scratch dir *once* (skips when the tarball already exists).
Extract side: ``torchrun_launcher.sh:35-40`` / ``standard_job.sh:19-24``
untar every staged tarball into node-local scratch (``SLURM_TMPDIR``),
timing the extraction.  Same semantics here, in Python so the TPU pod
launcher (no SLURM) can reuse it.
"""

from __future__ import annotations

import os
import tarfile
import time
from pathlib import Path
from typing import Iterable, List


def create_tarball(data_path: str | Path, out_dir: str | Path,
                   overwrite: bool = False) -> Path:
    """Tar ``data_path`` into ``out_dir/<name>.tar``; skip if already staged."""
    data_path = Path(data_path)
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    out = out_dir / f"{data_path.name}.tar"
    if out.exists() and not overwrite:
        return out
    tmp = out.with_suffix(".tar.partial")
    with tarfile.open(tmp, "w") as tf:
        tf.add(data_path, arcname=data_path.name)
    tmp.rename(out)  # atomic publish: never expose a half-written tarball
    return out


def extract_tarballs(tarballs: Iterable[str | Path], dest: str | Path) -> List[Path]:
    """Extract each tarball into ``dest``; returns extraction roots."""
    dest = Path(dest)
    dest.mkdir(parents=True, exist_ok=True)
    roots: List[Path] = []
    for tb in tarballs:
        tb = Path(str(tb).strip())
        if not tb.exists():
            raise FileNotFoundError(f"staged tarball not found: {tb}")
        t0 = time.time()
        with tarfile.open(tb) as tf:
            try:
                tf.extractall(dest, filter="data")
            except TypeError:
                # Python <3.10.12 predates the filter= kwarg; these tarballs
                # are our own staging artifacts, so plain extraction is fine.
                tf.extractall(dest)
            names = tf.getnames()
        top = dest / names[0].split("/")[0] if names else dest
        roots.append(top)
        print(f"[staging] extracted {tb.name} -> {dest} "
              f"({time.time() - t0:.1f}s)")
    return roots


def job_tmpdir() -> Path | None:
    """The job-scoped node-local scratch dir, or None when no launcher or
    scheduler provided one.  Only *per-job* dirs qualify (``TPUDIST_TMPDIR``
    exported by tpurun/dispatcher, SLURM's per-job ``SLURM_TMPDIR``) — the
    generic ``TMPDIR`` is shared across jobs and would collide, so callers
    without a per-job dir should mkdtemp instead (tpurun does)."""
    for var in ("TPUDIST_TMPDIR", "SLURM_TMPDIR"):
        v = os.environ.get(var)
        if v:
            return Path(v)
    return None
